"""Offline frontier tuner: diagnosis-driven knob moves, not grid search.

The propose → serve-window → read-record loop that ROADMAP item 2 calls
the biggest remaining lever: each iteration serves one traffic window on
a live ``QueryQueue``/store under the CURRENT knob vector (every window
is a flight-recorder fingerprint — :func:`raft_tpu.obs.flight.fingerprint`),
reads the window's obs-report record back, runs the attribution engine
(:func:`raft_tpu.obs.explain.explain`) and maps the top diagnosis to ONE
knob move through an explicit :data:`RULE_TABLE` — ``mxu_underfill`` →
raise the batch cap, ``hbm_bound`` → lower ``bits``/switch engine,
``recall_limited`` → raise ``n_probes``/``k_fetch`` — instead of walking
a hand-written sweep grid. Because every move is justified by a
diagnosis, the whole tuning episode is reconstructible: each window
record carries its explain record and the proposal it produced.

Accumulated windows feed ``flight.extract_frontier`` (the same Pareto
fold the flight CLI runs), and :meth:`Autotuner.emit_operating_point`
writes the frontier point that meets a stated SLO — highest QPS subject
to the p99 bound and recall floor — as a JSON config
(``RAFT_TPU_TUNE_OPERATING_POINT``, default
``results/operating_point.json``) that ``bench.py`` sections and serving
entry points consume via :func:`load_operating_point`. The hand-written
``sweep_r*_config.json`` flow is retired by this file.

Each window is deadline-bounded (``RAFT_TPU_TUNE_DEADLINE_S``) and
faultpointed (``tuning.autotune.window`` — the round-7 standing gate;
tier-1 arms oom/hang/fatal): an armed fault skips THAT window classified
(counted, event-ringed) and the next window proceeds — a tuner that dies
on one bad window would be worse than no tuner.

Telemetry-off contract: a disabled registry means the tuner holds ZERO
state (the flight-recorder NOOP gate); ``step()``/``run()`` return None.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Optional

from raft_tpu import obs, resilience
from raft_tpu.resilience.retry import record_event

__all__ = [
    "DEADLINE_ENV",
    "MAX_WINDOWS_ENV",
    "OPERATING_POINT_ENV",
    "RULE_TABLE",
    "Autotuner",
    "Knob",
    "default_operating_point_path",
    "default_tune_deadline",
    "default_tune_windows",
    "load_operating_point",
]

#: operating_point record schema
SCHEMA_VERSION = 1

MAX_WINDOWS_ENV = "RAFT_TPU_TUNE_MAX_WINDOWS"
OPERATING_POINT_ENV = "RAFT_TPU_TUNE_OPERATING_POINT"
DEADLINE_ENV = "RAFT_TPU_TUNE_DEADLINE_S"

_DEFAULT_MAX_WINDOWS = 16
_DEFAULT_DEADLINE_S = 30.0
_DEFAULT_OPERATING_POINT = os.path.join("results", "operating_point.json")

#: diagnosis kind → ordered (knob, step) candidates; the FIRST candidate
#: whose knob exists in the tuner's knob set and has headroom wins, so one
#: table serves ivf_flat (no ``bits``) and ivf_bq (no ``k_fetch``) alike.
#: ``retrace_tax``/``unknown`` map to NO move: a retrace or a blind window
#: is a bug to fix, not a knob to turn — the tuner holds and re-measures.
RULE_TABLE = {
    "mxu_underfill": (("batch_cap", +1), ("q_block", +1)),
    "queue_limited": (("batch_cap", +1),),
    "padding_waste": (("batch_cap", +1), ("page_rows", +1)),
    "hbm_bound": (("bits", -1), ("engine", +1), ("n_probes", -1)),
    "capacity_limited": (("bits", -1), ("page_rows", -1)),
    "recall_limited": (("n_probes", +1), ("k_fetch", +1)),
    "retrace_tax": (),
    "unknown": (),
}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        v = float(raw) if raw else default
    except ValueError:
        return default
    return v if v > 0 else default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw.isdigit() and int(raw) > 0 else default


def default_tune_windows() -> int:
    """Offline window budget per :meth:`Autotuner.run`
    (``RAFT_TPU_TUNE_MAX_WINDOWS``, default 16)."""
    return _env_int(MAX_WINDOWS_ENV, _DEFAULT_MAX_WINDOWS)


def default_tune_deadline() -> float:
    """Per-window wall-clock bound in seconds
    (``RAFT_TPU_TUNE_DEADLINE_S``, default 30)."""
    return _env_float(DEADLINE_ENV, _DEFAULT_DEADLINE_S)


def default_operating_point_path() -> str:
    """Where the tuned operating point lands and is looked up
    (``RAFT_TPU_TUNE_OPERATING_POINT``, default
    ``results/operating_point.json``)."""
    raw = os.environ.get(OPERATING_POINT_ENV, "").strip()
    return raw or _DEFAULT_OPERATING_POINT


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


class Knob:
    """One tunable: a name and an ORDERED ladder of candidate values
    (ascending cost/quality — ``up`` means a later rung). The ladder is
    explicit so every move lands on a value someone chose, never an
    extrapolation; values must be JSON-serializable (they feed the
    config fingerprint)."""

    def __init__(self, name: str, values, start=None):
        self.name = str(name)
        self.values = list(values)
        if not self.values:
            raise ValueError(f"knob {name!r} has an empty ladder")
        if start is None:
            self.idx = 0
        else:
            if start not in self.values:
                raise ValueError(
                    f"knob {name!r} start {start!r} not on its ladder")
            self.idx = self.values.index(start)

    @property
    def value(self):
        return self.values[self.idx]

    def can(self, step: int) -> bool:
        return step != 0 and 0 <= self.idx + step < len(self.values)

    def apply(self, step: int):
        """Move one rung; returns (frm, to)."""
        frm = self.value
        self.idx += int(step)
        self.idx = max(0, min(len(self.values) - 1, self.idx))
        return frm, self.value


class Autotuner:
    """Diagnosis-driven offline tuner over one serving setup.

    ``serve_fn(knob_values: dict) -> dict`` serves ONE traffic window
    under the given knob vector and returns the window record — a
    ``flight_window``-shaped dict carrying at least ``report`` (an
    ``obs.report.collect()`` record) and ``ops`` (window-local
    ``qps``/``p99_ub_s``); a ``FlightRecorder.sample()`` return value is
    exactly right. ``knobs`` is a list of :class:`Knob`. ``slo`` is the
    target the run converges toward and the emitted point must meet:
    ``{"p99_s": float, "recall_floor": float, "qps_min": float}`` (every
    field optional). ``path`` (optional) streams each tuner window
    crash-safe through ``bench/progress``.

    Convergence: a window that meets the SLO and produces no applicable
    move (healthy, or its knob at a ladder bound) increments a hold
    streak; ``settle`` consecutive holds end :meth:`run` early.
    """

    def __init__(self, serve_fn, knobs, *, slo: Optional[dict] = None,
                 rules: Optional[dict] = None,
                 max_windows: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 settle: int = 2,
                 path: Optional[str] = None):
        self._enabled = obs.enabled()
        if not self._enabled:
            return  # telemetry off ⇒ ZERO tuner state (the NOOP contract)
        self._serve_fn = serve_fn
        self._knobs = {k.name: k for k in knobs}
        if not self._knobs:
            raise ValueError("Autotuner needs at least one knob")
        self._slo = dict(slo) if slo else {}
        self._rules = dict(rules) if rules is not None else dict(RULE_TABLE)
        self._max_windows = int(max_windows if max_windows is not None
                                else default_tune_windows())
        self._deadline_s = float(deadline_s if deadline_s is not None
                                 else default_tune_deadline())
        self._settle = max(1, int(settle))
        self._path = path
        self._windows: list = []
        self._prev_report: Optional[dict] = None
        self._window_id = 0
        self._skipped = 0
        self._moves = 0
        self._holds = 0
        self._hold_streak = 0
        self._converged = False

    # -- state --------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def converged(self) -> bool:
        return self._enabled and self._converged

    def knob_values(self) -> dict:
        """The CURRENT knob vector — what serve_fn is handed, and what a
        co-wired FlightRecorder should fingerprint."""
        if not self._enabled:
            return {}
        return {name: k.value for name, k in self._knobs.items()}

    def windows(self) -> list:
        """Accumulated (non-skipped) window records, oldest first."""
        return list(self._windows) if self._enabled else []

    def stats(self) -> dict:
        if not self._enabled:
            return {}
        return {
            "windows": len(self._windows),
            "skipped": self._skipped,
            "moves": self._moves,
            "holds": self._holds,
            "converged": self._converged,
            "knobs": self.knob_values(),
        }

    # -- the loop -----------------------------------------------------------
    def step(self) -> Optional[dict]:
        """One propose → serve → read → move iteration. Returns the
        window record (with its ``explain`` and ``proposal`` attached),
        or a classified ``{"status": kind}`` stub when an armed fault /
        deadline skipped the window, or None when disabled."""
        if not self._enabled:
            return None
        values = self.knob_values()
        wid = self._window_id
        self._window_id += 1
        try:
            with obs.record_span("tuning::window",
                                 attrs={"window": wid}):
                with resilience.Deadline(self._deadline_s,
                                         label="tuning.autotune"):
                    # faultpoint INSIDE the deadline scope: an armed hang
                    # spins on check_interrupt and is bounded by the
                    # window deadline, not the fault's own safety cap
                    resilience.faultpoint("tuning.autotune.window")
                    rec = dict(self._serve_fn(dict(values)) or {})
                    rec = self._fold_window(rec, wid, values)
        except Exception as e:
            kind = resilience.classify(e)
            self._skipped += 1
            obs.add(f"tuning.window.{kind.lower()}")
            record_event("tuning.window_skipped", kind=kind, window=wid,
                         error=repr(e)[:200])
            return {"status": kind, "window": wid}
        self._windows.append(rec)
        self._export(rec)
        return rec

    def _fold_window(self, rec: dict, wid: int, values: dict) -> dict:
        """Stamp fingerprint/explain/proposal onto one served window and
        apply the proposal's knob move."""
        from raft_tpu.obs import explain as obs_explain
        from raft_tpu.obs import flight

        rec.setdefault("type", "flight_window")
        rec.setdefault("t", round(time.time(), 3))
        rec["tuner_window"] = wid
        # the PROPOSAL is ground truth for the frontier grouping — a
        # recorder wired to stale knobs must not split the groups
        rec["fingerprint"] = flight.fingerprint(values)
        report = rec.get("report")
        if isinstance(report, dict) and report.get("type") == "obs_report":
            diag = obs_explain.explain(report, prev=self._prev_report)
            self._prev_report = report
        else:
            # a window with no readable report can only be unknown —
            # classified in the record, never a crash
            diag = {"type": "explain",
                    "schema_version": obs_explain.SCHEMA_VERSION,
                    "window": wid, "pressure": {}, "healthy": False,
                    "primary": "unknown",
                    "diagnoses": [{"kind": "unknown", "score": 0.5,
                                   "evidence": {"missing": "report"}}]}
        rec["explain"] = diag
        rec["proposal"] = self._propose(diag, rec)
        return rec

    def _propose(self, diag: dict, rec: dict) -> dict:
        """Map the top diagnosis to one knob move via the rule table and
        APPLY it (the next window serves the moved vector)."""
        primary = diag.get("primary")
        meets = self._meets_slo(rec)
        out = {"diagnosis": primary, "meets_slo": meets}
        knob = step = None
        for name, s in self._rules.get(primary, ()) if primary else ():
            cand = self._knobs.get(name)
            if cand is not None and cand.can(s):
                knob, step = cand, s
                break
        if knob is None:
            self._holds += 1
            out["move"] = None
            out["reason"] = ("healthy" if primary is None
                            else "no_applicable_knob")
            if meets:
                self._hold_streak += 1
                if self._hold_streak >= self._settle:
                    self._converged = True
            else:
                self._hold_streak = 0
            return out
        frm, to = knob.apply(step)
        self._moves += 1
        self._hold_streak = 0
        out["move"] = {"knob": knob.name, "frm": frm, "to": to}
        obs.add("tuning.moves")
        record_event("tuning.propose", knob=knob.name, frm=frm, to=to,
                     diagnosis=primary)
        return out

    def run(self, max_windows: Optional[int] = None) -> dict:
        """Loop :meth:`step` until convergence or the window budget.
        Returns :meth:`stats` (empty dict when disabled)."""
        if not self._enabled:
            return {}
        budget = int(max_windows if max_windows is not None
                     else self._max_windows)
        for _ in range(budget):
            self.step()
            if self._converged:
                break
        return self.stats()

    # -- SLO ----------------------------------------------------------------
    def _meets_slo(self, rec: dict) -> bool:
        """Does this window's operating point meet the stated SLO? A
        missing measurement FAILS the bound it was needed for (absence
        of evidence is not compliance)."""
        ops = rec.get("ops") or {}
        slo = self._slo
        p99 = slo.get("p99_s")
        if _finite(p99) and not (_finite(ops.get("p99_ub_s"))
                                 and ops["p99_ub_s"] <= p99):
            return False
        qps_min = slo.get("qps_min")
        if _finite(qps_min) and not (_finite(ops.get("qps"))
                                     and ops["qps"] >= qps_min):
            return False
        floor = slo.get("recall_floor")
        if _finite(floor):
            report = rec.get("report") if isinstance(rec.get("report"),
                                                     dict) else {}
            est = report.get("recall")
            if not (isinstance(est, dict) and _finite(est.get("recall"))
                    and est["recall"] >= floor):
                return False
        return True

    # -- frontier + operating point -----------------------------------------
    def frontier(self) -> dict:
        """Pareto frontier over the accumulated windows — the same
        ``flight.extract_frontier`` fold the flight CLI runs."""
        from raft_tpu.obs import flight

        if not self._enabled:
            return {"points": 0, "pareto_points": 0, "groups": []}
        return flight.extract_frontier(self._windows)

    def emit_operating_point(self, slo: Optional[dict] = None,
                             path: Optional[str] = None) -> Optional[dict]:
        """Pick the frontier point that meets ``slo`` (default: the run's
        SLO) with the highest QPS and write it as the operating-point
        JSON (``path`` default: :func:`default_operating_point_path`).
        When NO point meets the SLO the best Pareto point still lands,
        stamped ``meets_slo: false`` — a consumer can refuse it, but the
        episode's outcome is on disk either way. Returns the emitted
        record, or None when disabled/empty."""
        if not self._enabled:
            return None
        with obs.record_span("tuning::emit_operating_point"):
            return self._emit(slo if slo is not None else self._slo,
                              path or default_operating_point_path())

    def _emit(self, slo: dict, path: str) -> Optional[dict]:
        front = self.frontier()
        groups = [g for g in front.get("groups") or [] if g.get("pareto")]
        if not groups:
            return None

        def meets(g: dict) -> bool:
            p99 = slo.get("p99_s")
            if _finite(p99) and not (_finite(g.get("p99_ub_s"))
                                     and g["p99_ub_s"] <= p99):
                return False
            qps_min = slo.get("qps_min")
            if _finite(qps_min) and not (_finite(g.get("qps"))
                                         and g["qps"] >= qps_min):
                return False
            floor = slo.get("recall_floor")
            if _finite(floor) and not (_finite(g.get("recall"))
                                       and g["recall"] >= floor):
                return False
            return True

        eligible = [g for g in groups if meets(g)]
        pool = eligible or groups
        best = max(pool, key=lambda g: (g.get("qps") or 0.0,
                                        g.get("recall") or 0.0))
        doc = {
            "t": round(time.time(), 3),
            "type": "operating_point",
            "schema_version": SCHEMA_VERSION,
            "tuned_by": "raft_tpu.tuning.autotune",
            "fp": best["fp"],
            "knobs": dict(best.get("knobs") or {}),
            "slo": dict(slo),
            "meets_slo": bool(eligible),
            "qps": best.get("qps"),
            "p99_ub_s": best.get("p99_ub_s"),
            "recall": best.get("recall"),
            "windows": len(self._windows),
            "skipped": self._skipped,
            "moves": self._moves,
            "pareto_points": front.get("pareto_points"),
        }
        from raft_tpu.bench import progress

        progress.write_artifact(path, doc)
        obs.add("tuning.operating_points")
        record_event("tuning.operating_point", fp=best["fp"],
                     meets_slo=doc["meets_slo"], qps=doc["qps"])
        return doc

    def _export(self, rec: dict) -> None:
        if not self._path:
            return
        try:
            from raft_tpu.bench import progress

            progress.export_metrics(self._path, rec)
        except Exception as e:
            resilience.classify(e)
            obs.add("tuning.export_degraded")


def load_operating_point(path: Optional[str] = None) -> Optional[dict]:
    """Read a previously emitted operating point; None when absent,
    unreadable, or not an operating_point record — the bench's fallback-
    to-defaults path, never a crash."""
    path = path or default_operating_point_path()
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("type") != "operating_point" \
            or not isinstance(doc.get("knobs"), dict):
        return None
    return doc
