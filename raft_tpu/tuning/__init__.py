"""Decision layer over the observability plane (ROADMAP item 2).

``raft_tpu.tuning.autotune`` closes the offline loop: diagnosis-driven
knob moves over a live serving window (no grid search), a Pareto
frontier over the accumulated fingerprinted windows, and an emitted
operating-point JSON the bench sections and serving entry points
consume. The ONLINE half — the SLO burn-rate controller that nudges
knobs under live pressure — lives with the thing it controls, in
``raft_tpu.serving.controller``.

Like ``obs.report``/``obs.flight``, the heavyweight module is deliberately
NOT imported at package level: ``python -m raft_tpu.tuning.autotune``
stays clean, and importing :mod:`raft_tpu.tuning` costs nothing.
"""
