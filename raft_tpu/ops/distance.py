"""Pairwise distances — the hottest primitive in the framework.

Reference surface: cpp/include/raft/distance/distance_types.hpp:26-66 enumerates
the metrics; public entry points distance()/pairwise_distance() at
distance/distance-inl.cuh:67,238; tile/arch dispatch in
distance/detail/pairwise_matrix/dispatch-inl.cuh:69 (CUTLASS tensor cores on
SM80+); fusedL2NN (distance + per-row argmin, the k-means inner loop) at
distance/fused_l2_nn-inl.cuh:76.

TPU design — two regimes instead of one CUDA tile kernel family:

  * **Expanded (MXU) metrics** — anything expressible as f(x@y.T, row stats):
    sqeuclidean/euclidean, cosine, inner product, correlation, hellinger,
    jaccard (Tanimoto), dice, russellrao. One big gemm (bf16-in/fp32-accum
    optional via Resources.compute_dtype) + rank-1 corrections. This is the
    CUTLASS-path analog and where the FLOPs live.
  * **Elementwise (VPU) metrics** — l1, chebyshev, minkowski, canberra,
    braycurtis, hamming, jensenshannon, kl_divergence: tiled broadcast
    (tile_m, 1, k) vs (1, n, k) reductions, with the row-tile size picked from
    the Resources workspace budget (the chooseTileSize analog,
    neighbors/detail/knn_brute_force.cuh:78-91).

All functions are jit-compatible (static shapes, no Python branching on values).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.utils.tiling import pad_and_tile

# Canonical metric names + aliases (mirrors DistanceType,
# distance/distance_types.hpp:26-66 and pylibraft's DISTANCE_TYPES table).
_ALIASES = {
    "l2": "sqeuclidean",
    "l2_expanded": "sqeuclidean",
    "l2_unexpanded": "sqeuclidean",
    "euclidean_expanded": "euclidean",
    "l2sqrt": "euclidean",
    "l2sqrtexpanded": "euclidean",
    "cityblock": "l1",
    "manhattan": "l1",
    "taxicab": "l1",
    "linf": "chebyshev",
    "lp": "minkowski",
    "ip": "inner_product",
    "dot": "inner_product",
    "kl": "kl_divergence",
    "kldivergence": "kl_divergence",
    "jensen-shannon": "jensenshannon",
}

EXPANDED_METRICS = frozenset(
    {
        "sqeuclidean",
        "euclidean",
        "cosine",
        "inner_product",
        "correlation",
        "hellinger",
        "jaccard",
        "dice",
        "russellrao",
    }
)
ELEMENTWISE_METRICS = frozenset(
    {
        "l1",
        "chebyshev",
        "minkowski",
        "canberra",
        "braycurtis",
        "hamming",
        "jensenshannon",
        "kl_divergence",
    }
)
ALL_METRICS = EXPANDED_METRICS | ELEMENTWISE_METRICS | {"haversine"}


def canonical_metric(metric: str) -> str:
    m = metric.lower()
    m = _ALIASES.get(m, m)
    if m not in ALL_METRICS:
        raise ValueError(f"unknown metric {metric!r}; supported: {sorted(ALL_METRICS)}")
    return m


def sqnorm(x: jax.Array, axis: int = 1) -> jax.Array:
    """Row squared-L2 norms, squaring in fp32: fp16 inputs overflow and int8
    inputs wrap if squared in their own dtype before the fp32 accumulation."""
    xf = jnp.asarray(x).astype(jnp.float32)
    return jnp.sum(xf * xf, axis=axis)


def matmul_t(x: jax.Array, y: jax.Array, compute_dtype=None, precision=None) -> jax.Array:
    """x @ y.T with fp32 accumulation; optionally bf16 MXU inputs.

    The gemm every expanded metric rides on (CUTLASS-dispatch analog,
    distance/detail/pairwise_matrix/dispatch-inl.cuh:104). ``precision``
    follows jax.lax conventions: on TPU, fp32 inputs at "default" precision run
    single-pass bf16 on the MXU (fast, ~3 significant digits); "highest" runs
    the multi-pass fp32-accurate scheme. Primitive APIs (pairwise_distance,
    fused_l2_nn) default to "highest" — their contract is numerical accuracy;
    ANN search paths default to "default" — their contract is recall.
    """
    if jnp.issubdtype(x.dtype, jnp.integer) or jnp.issubdtype(y.dtype, jnp.integer):
        # integer datasets (uint8/int8 big-ann formats) against float
        # queries: upcast the integer operand — bf16 is exact for |v| <= 256
        target = compute_dtype or jnp.float32
        x = x.astype(target)
        y = y.astype(target)
        precision = None if compute_dtype is not None else precision
    elif compute_dtype is not None and x.dtype == jnp.float32 and compute_dtype != jnp.float32:
        x = x.astype(compute_dtype)
        y = y.astype(compute_dtype)
        precision = None
    return lax.dot_general(
        x,
        y,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )


# ---------------------------------------------------------------------------
# Expanded (gemm-based) metrics
# ---------------------------------------------------------------------------


def _expanded_distance(x, y, metric, compute_dtype, precision="highest"):
    ip = matmul_t(x, y, compute_dtype, precision)  # (m, n) fp32 accumulation
    if metric == "inner_product":
        return ip
    if metric in ("sqeuclidean", "euclidean"):
        xn = sqnorm(x)
        yn = sqnorm(y)
        d2 = xn[:, None] + yn[None, :] - 2.0 * ip
        d2 = jnp.maximum(d2, 0.0)
        return jnp.sqrt(d2) if metric == "euclidean" else d2
    if metric == "cosine":
        xn = jnp.sqrt(sqnorm(x))
        yn = jnp.sqrt(sqnorm(y))
        denom = jnp.maximum(xn[:, None] * yn[None, :], 1e-30)
        return 1.0 - ip / denom
    if metric == "correlation":
        xc = x - jnp.mean(x, axis=1, keepdims=True)
        yc = y - jnp.mean(y, axis=1, keepdims=True)
        return _expanded_distance(xc, yc, "cosine", compute_dtype, precision)
    if metric == "hellinger":
        # d = sqrt(1 - sum_i sqrt(x_i * y_i)) via gemm of sqrt-ed inputs
        # (reference hellinger is the "expanded" form too).
        sq_ip = matmul_t(jnp.sqrt(jnp.maximum(x, 0.0)), jnp.sqrt(jnp.maximum(y, 0.0)), compute_dtype, precision)
        return jnp.sqrt(jnp.maximum(1.0 - sq_ip, 0.0))
    if metric == "jaccard":
        # Generalized (Tanimoto): 1 - <x,y> / (|x|^2 + |y|^2 - <x,y>)
        xn = sqnorm(x)
        yn = sqnorm(y)
        denom = xn[:, None] + yn[None, :] - ip
        return 1.0 - jnp.where(denom > 0, ip / jnp.maximum(denom, 1e-30), 1.0)
    if metric == "dice":
        xs = jnp.sum(x, axis=1, dtype=jnp.float32)
        ys = jnp.sum(y, axis=1, dtype=jnp.float32)
        denom = xs[:, None] + ys[None, :]
        return 1.0 - jnp.where(denom > 0, 2.0 * ip / jnp.maximum(denom, 1e-30), 1.0)
    if metric == "russellrao":
        k = x.shape[1]
        return (k - ip) / k
    raise AssertionError(metric)


# ---------------------------------------------------------------------------
# Elementwise (tiled broadcast) metrics
# ---------------------------------------------------------------------------


def _elementwise_tile(xt, y, metric, p):
    """Distance of a row tile (tm,k) against all of y (n,k) → (tm,n)."""
    xt_ = xt[:, None, :]
    y_ = y[None, :, :]
    if metric == "l1":
        return jnp.sum(jnp.abs(xt_ - y_), axis=-1)
    if metric == "chebyshev":
        return jnp.max(jnp.abs(xt_ - y_), axis=-1)
    if metric == "minkowski":
        return jnp.sum(jnp.abs(xt_ - y_) ** p, axis=-1) ** (1.0 / p)
    if metric == "canberra":
        num = jnp.abs(xt_ - y_)
        den = jnp.abs(xt_) + jnp.abs(y_)
        return jnp.sum(jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0), axis=-1)
    if metric == "braycurtis":
        num = jnp.sum(jnp.abs(xt_ - y_), axis=-1)
        den = jnp.sum(jnp.abs(xt_ + y_), axis=-1)
        return jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0)
    if metric == "hamming":
        return jnp.mean((xt_ != y_).astype(jnp.float32), axis=-1)
    if metric == "jensenshannon":
        m = 0.5 * (xt_ + y_)
        safe = lambda a, b: jnp.where(a > 0, a * jnp.log(jnp.maximum(a, 1e-30) / jnp.maximum(b, 1e-30)), 0.0)
        js = 0.5 * jnp.sum(safe(xt_, m) + safe(y_, m), axis=-1)
        return jnp.sqrt(jnp.maximum(js, 0.0))
    if metric == "kl_divergence":
        safe = jnp.where(xt_ > 0, xt_ * jnp.log(jnp.maximum(xt_, 1e-30) / jnp.maximum(y_, 1e-30)), 0.0)
        return jnp.sum(safe, axis=-1)
    raise AssertionError(metric)


def _row_tile_size(n: int, k: int, workspace_bytes: int) -> int:
    """Pick a row-tile so tile_m*n*k fp32 intermediates fit the workspace budget
    (chooseTileSize analog, neighbors/detail/knn_brute_force.cuh:84)."""
    per_row = max(1, n * k * 4)
    tm = max(1, workspace_bytes // per_row)
    return min(tm, 4096)


def _tiled_elementwise(x, y, metric, p, workspace_bytes):
    m, k = x.shape
    n = y.shape[0]
    tm = _row_tile_size(n, k, workspace_bytes)
    if tm >= m:
        return _elementwise_tile(x, y, metric, p)
    tiles, n_tiles = pad_and_tile(x, tm)
    out = lax.map(lambda xt: _elementwise_tile(xt, y, metric, p), tiles)
    return out.reshape(n_tiles * tm, n)[:m]


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def haversine(x: jax.Array, y: jax.Array) -> jax.Array:
    """Great-circle distance between (lat, lon) radian pairs (reference
    spatial/knn/detail/haversine_distance.cuh)."""
    if x.shape[1] != 2 or y.shape[1] != 2:
        raise ValueError("haversine requires 2-d (lat, lon) inputs")
    lat1, lon1 = x[:, 0][:, None], x[:, 1][:, None]
    lat2, lon2 = y[:, 0][None, :], y[:, 1][None, :]
    sin_dlat = jnp.sin(0.5 * (lat2 - lat1))
    sin_dlon = jnp.sin(0.5 * (lon2 - lon1))
    a = sin_dlat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sin_dlon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


@functools.partial(jax.jit, static_argnames=("metric", "p", "workspace_bytes", "compute_dtype"))
def _pairwise_distance_impl(x, y, metric, p, workspace_bytes, compute_dtype):
    if metric == "haversine":
        return haversine(x, y)
    if metric in EXPANDED_METRICS:
        return _expanded_distance(x, y, metric, compute_dtype)
    return _tiled_elementwise(x, y, metric, p, workspace_bytes)


def pairwise_distance(
    x,
    y,
    metric: str = "sqeuclidean",
    p: float = 2.0,
    res: Optional[Resources] = None,
) -> jax.Array:
    """All-pairs distance matrix (m, n) between rows of x (m,k) and y (n,k).

    API analog of raft::distance::pairwise_distance
    (distance/distance-inl.cuh:238). ``metric`` accepts the canonical names in
    :data:`ALL_METRICS` plus common aliases ("l2", "cityblock", ...).
    """
    res = res or current_resources()
    metric = canonical_metric(metric)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if x.ndim != 2 or y.ndim != 2 or x.shape[1] != y.shape[1]:
        raise ValueError(f"shape mismatch: {x.shape} vs {y.shape}")
    compute_dtype = res.compute_dtype if metric in EXPANDED_METRICS else None
    return _pairwise_distance_impl(
        x, y, metric, float(p), int(res.workspace_bytes), compute_dtype
    )


@functools.partial(jax.jit, static_argnames=("sqrt", "tile_m", "precision"))
def _fused_l2_nn_impl(x, y, sqrt, tile_m, precision):
    m, k = x.shape
    yn = sqnorm(y)

    def one_tile(xt):
        ip = matmul_t(xt, y, precision=precision)
        xn = sqnorm(xt)
        d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * ip, 0.0)
        idx = jnp.argmin(d2, axis=1).astype(jnp.int32)
        val = jnp.min(d2, axis=1)
        return val, idx

    if tile_m >= m:
        val, idx = one_tile(x)
    else:
        tiles, _ = pad_and_tile(x, tile_m)
        val, idx = lax.map(one_tile, tiles)
        val = val.reshape(-1)[:m]
        idx = idx.reshape(-1)[:m]
    if sqrt:
        val = jnp.sqrt(val)
    return val, idx


def fused_l2_nn_argmin(
    x,
    y,
    sqrt: bool = False,
    precision: str = "highest",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row nearest neighbor under L2: (min_dist, argmin) of shape (m,).

    Analog of fusedL2NN (distance/fused_l2_nn-inl.cuh:76,181) — the k-means
    assignment inner loop. The fusion here is XLA's: gemm + rank-1 correction +
    row argmin in one compiled program, tiled over query rows.
    """
    res = res or current_resources()
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    n, k = y.shape
    tm = max(1, min(int(res.workspace_bytes) // max(1, n * 4 * 4), 8192))
    return _fused_l2_nn_impl(x, y, bool(sqrt), tm, precision)
