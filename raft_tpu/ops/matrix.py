"""Matrix manipulation primitives (reference cpp/include/raft/matrix/).

argmax/argmin, gather/scatter, slicing, per-row sort, linewise ops — each a
fused XLA expression rather than a kernel. Kept as a module so the API surface
mirrors the reference inventory (SURVEY.md §2.2) one-to-one.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def argmax(x, axis: int = 1) -> jax.Array:
    return jnp.argmax(jnp.asarray(x), axis=axis).astype(jnp.int32)


def argmin(x, axis: int = 1) -> jax.Array:
    return jnp.argmin(jnp.asarray(x), axis=axis).astype(jnp.int32)


def gather(x, row_ids) -> jax.Array:
    """Gather rows (matrix/gather.cuh analog)."""
    return jnp.take(jnp.asarray(x), jnp.asarray(row_ids), axis=0)


def scatter(x, row_ids, updates) -> jax.Array:
    """Functional row scatter (matrix/scatter.cuh analog)."""
    return jnp.asarray(x).at[jnp.asarray(row_ids)].set(jnp.asarray(updates))


def slice_matrix(x, rows: Tuple[int, int], cols: Tuple[int, int]) -> jax.Array:
    """Static submatrix view (matrix/slice.cuh analog)."""
    return jnp.asarray(x)[rows[0] : rows[1], cols[0] : cols[1]]


def sort_cols_per_row(x, ascending: bool = True) -> Tuple[jax.Array, jax.Array]:
    """Sort values within each row, returning (sorted, permutation)
    (matrix/col_wise_sort.cuh analog)."""
    x = jnp.asarray(x)
    idx = jnp.argsort(x, axis=1, descending=not ascending).astype(jnp.int32)
    return jnp.take_along_axis(x, idx, axis=1), idx


def linewise_op(x, vec, along_rows: bool = True, op=jnp.multiply) -> jax.Array:
    """Apply op(x, vec) broadcasting vec along rows or columns
    (matrix/linewise_op.cuh analog). Delegates to linalg.matrix_vector_op."""
    from raft_tpu.ops.linalg import matrix_vector_op

    return matrix_vector_op(x, vec, axis=1 if along_rows else 0, op=op)


def copy(x) -> jax.Array:
    return jnp.array(x, copy=True)


def reverse(x, axis: int = 1) -> jax.Array:
    return jnp.flip(jnp.asarray(x), axis=axis)


def init_constant(shape, value, dtype=jnp.float32) -> jax.Array:
    return jnp.full(shape, value, dtype=dtype)
