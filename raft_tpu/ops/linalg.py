"""Dense linear algebra + reductions (reference cpp/include/raft/linalg/).

On TPU, most of the reference's hand-written reduction/map kernels are a single
jnp expression that XLA fuses; what earns a real design here:
  * key'd reductions as **one-hot matmuls** so they run on the MXU instead of
    scatter-adds (reduce_rows_by_key.cuh / reduce_cols_by_key.cuh analogs) —
    this is also the k-means centroid-update workhorse;
  * gemm with explicit accumulation dtype (linalg/gemm.cuh:61 analog);
  * decompositions (eig/QR/SVD/lstsq/rsvd: linalg/eig.cuh, rsvd.cuh) via
    jnp.linalg with deterministic sign conventions (matrix/detail sign_flip).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def gemm(a, b, transpose_a=False, transpose_b=False, alpha=1.0, beta=0.0, c=None):
    """alpha * op(a) @ op(b) + beta * c with fp32 accumulation
    (raft::linalg::gemm analog, linalg/gemm.cuh:61)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    ca = ((0,) if transpose_a else (1,), (1,) if transpose_b else (0,))
    out = lax.dot_general(a, b, (ca, ((), ())), preferred_element_type=jnp.float32)
    out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out.astype(a.dtype)


def dot(x, y):
    return jnp.dot(x, y, preferred_element_type=jnp.float32)


def axpy(alpha, x, y):
    return alpha * x + y


# -- norms / normalization (linalg/norm.cuh, normalize.cuh) -----------------

_NORM_FNS = {
    "l1": lambda x, ax: jnp.sum(jnp.abs(x), axis=ax),
    "l2": lambda x, ax: jnp.sqrt(jnp.sum(x * x, axis=ax)),
    "sql2": lambda x, ax: jnp.sum(x * x, axis=ax),
    "linf": lambda x, ax: jnp.max(jnp.abs(x), axis=ax),
}


def norm(x, norm_type: str = "l2", axis: int = 1) -> jax.Array:
    """Row (axis=1) or column (axis=0) norms."""
    if norm_type not in _NORM_FNS:
        raise ValueError(f"unknown norm {norm_type!r}")
    return _NORM_FNS[norm_type](jnp.asarray(x), axis)


def normalize(x, norm_type: str = "l2", axis: int = 1, eps: float = 1e-30) -> jax.Array:
    n = norm(x, norm_type, axis)
    n = jnp.maximum(n, eps)
    return x / (n[:, None] if axis == 1 else n[None, :])


# -- reductions (coalesced_reduction.cuh / strided_reduction.cuh) -----------


def reduce(x, axis: int = 1, op: str = "sum", main_op=None):
    """Generic row/col reduction; ``main_op`` maps elements first (the
    reference's main_op/reduce_op functor composition, linalg/reduce.cuh)."""
    x = jnp.asarray(x)
    if main_op is not None:
        x = main_op(x)
    fns = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max, "mean": jnp.mean}
    return fns[op](x, axis=axis)


def reduce_rows_by_key(x, keys, n_keys: int) -> jax.Array:
    """Sum rows of x (m,k) grouped by keys (m,) → (n_keys, k).

    One-hot matmul formulation: out = onehot(keys).T @ x runs on the MXU —
    the TPU answer to reduce_rows_by_key.cuh's atomic scatter kernel, and the
    k-means calc_centers workhorse (cluster/detail/kmeans_balanced.cuh)."""
    x = jnp.asarray(x)
    onehot = jax.nn.one_hot(keys, n_keys, dtype=x.dtype)  # (m, n_keys)
    return lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(x.dtype)


def reduce_cols_by_key(x, keys, n_keys: int) -> jax.Array:
    """Sum columns of x (m,k) grouped by keys (k,) → (m, n_keys)."""
    x = jnp.asarray(x)
    onehot = jax.nn.one_hot(keys, n_keys, dtype=x.dtype)  # (k, n_keys)
    return lax.dot_general(
        x, onehot, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(x.dtype)


def bincount(keys, n_keys: int, weights=None, dtype=jnp.float32) -> jax.Array:
    """Histogram of integer keys (static length, jit-safe)."""
    onehot = jax.nn.one_hot(keys, n_keys, dtype=dtype)
    if weights is not None:
        return (onehot * jnp.asarray(weights)[:, None]).sum(axis=0)
    return onehot.sum(axis=0)


def matrix_vector_op(x, v, axis: int = 1, op=jnp.add):
    """Broadcast a vector along rows (axis=1: v has len k) or cols (axis=0)
    (linalg/matrix_vector_op.cuh analog)."""
    v = jnp.asarray(v)
    return op(x, v[None, :] if axis == 1 else v[:, None])


# -- random rotations (the IVF-PQ/BQ quantizer front end) -------------------

#: recognised rotation representations (core/serialize `rotation_kind`):
#:   * "dense"    — an explicit orthogonal (rot_dim, rot_dim) matrix
#:                  (:func:`make_rotation_matrix`), applied as one gemm;
#:   * "hadamard" — a structured SRHT rotation R = H·D/√d stored as ONLY its
#:                  (rot_dim,) ±1 sign diagonal D (:func:`make_srht_signs`),
#:                  applied in O(d·log d) via the fast Walsh–Hadamard
#:                  butterfly (:func:`srht_rotate`). Same orthogonality —
#:                  and therefore the same estimator-unbiasedness contract —
#:                  at log d the FLOPs and 1/d the stored bytes.
ROTATION_KINDS = ("dense", "hadamard")


def pad_rot(x, rot_dim: int):
    """Zero-pad the trailing dim of ``x`` up to ``rot_dim`` (the rotation
    input width — ivf_pq_build.cuh pads the residual the same way)."""
    pad = rot_dim - x.shape[-1]
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, pad),))


def make_rotation_matrix(key, rot_dim: int) -> jax.Array:
    """Random orthogonal (rot_dim, rot_dim) via QR of a gaussian
    (make_rotation_matrix analog, detail/ivf_pq_build.cuh:119)."""
    g = jax.random.normal(key, (rot_dim, rot_dim), jnp.float32)
    q, r = jnp.linalg.qr(g)
    return q * jnp.sign(jnp.diagonal(r))[None, :]


def hadamard_rot_dim(dim: int) -> int:
    """Rotation width for the SRHT kind: the next power of two ≥ dim (the
    Walsh–Hadamard butterfly needs a pow2 width; ≥ 8 keeps codes at whole
    bytes). The extra zero-padded coordinates rotate to ordinary signal —
    the estimator algebra is width-agnostic."""
    return max(8, 1 << max(0, math.ceil(math.log2(max(int(dim), 1)))))


def make_srht_signs(key, rot_dim: int) -> jax.Array:
    """The SRHT sign diagonal: (rot_dim,) fp32 in {−1, +1}. ``rot_dim``
    must be a power of two (:func:`hadamard_rot_dim`)."""
    if rot_dim & (rot_dim - 1) or rot_dim < 2:
        raise ValueError(f"SRHT needs a power-of-two rot_dim, got {rot_dim}")
    bits = jax.random.bernoulli(key, 0.5, (rot_dim,))
    return jnp.where(bits, jnp.float32(1), jnp.float32(-1))


def hadamard_transform(x) -> jax.Array:
    """Unnormalized fast Walsh–Hadamard transform along the last axis:
    ``x @ H_d`` for the (symmetric) ±1 Hadamard matrix, as log2(d)
    full-width butterfly stages (each one reshape + add/sub — `jax.lax`
    friendly: static shapes, no gathers, fuses into surrounding jits).
    The last axis must be a power of two."""
    d = x.shape[-1]
    if d & (d - 1) or d < 1:
        raise ValueError(f"hadamard_transform needs a power-of-two width, got {d}")
    h = 1
    while h < d:
        y = x.reshape(x.shape[:-1] + (d // (2 * h), 2, h))
        a, b = y[..., 0, :], y[..., 1, :]
        x = jnp.stack([a + b, a - b], axis=-2).reshape(x.shape)
        h *= 2
    return x


def srht_rotate(x, signs) -> jax.Array:
    """Apply the structured rotation R = H·D/√d to rows of ``x``:
    ``fwht(x · D) / √d``. Exactly orthogonal (H/√d is, D is diagonal ±1),
    so ‖R·x‖ = ‖x‖ and the RaBitQ estimator's unbiasedness-over-rotations
    argument carries over unchanged; O(d·log d) per row where the dense
    rotation gemm pays O(d²)."""
    d = signs.shape[-1]
    return hadamard_transform(x * signs) * jnp.float32(1.0 / math.sqrt(d))


def rotate_rows(x, rotation, kind: str = "dense") -> jax.Array:
    """Rows of ``x`` (zero-padded up to the rotation width) through the
    rotation in either representation: ``rotation`` is the dense matrix for
    kind="dense", the (rot_dim,) sign diagonal for kind="hadamard". The
    ONE apply every build/encode/search-prep flow shares, so the two kinds
    cannot drift in padding or normalization conventions."""
    if kind == "dense":
        return pad_rot(x, rotation.shape[0]) @ rotation.T
    if kind == "hadamard":
        return srht_rotate(pad_rot(x, rotation.shape[0]), rotation)
    raise ValueError(f"unknown rotation kind {kind!r} (expected one of "
                     f"{ROTATION_KINDS})")


def unrotate_rows(y, rotation, kind: str = "dense") -> jax.Array:
    """Inverse of :func:`rotate_rows`, back onto the (padded) input space.
    Both representations are exactly orthogonal, so the inverse is the
    transpose: ``y @ R`` for the dense matrix, ``D * fwht(y) / sqrt(d)``
    for the SRHT (H is symmetric and D its own inverse). Callers slice
    ``[..., :dim]`` to drop the zero-padded coordinates. This is what lets
    maintenance re-clustering reconstruct assignment-grade vectors from
    encoded residuals when the raw rows are gone."""
    y = jnp.asarray(y)
    if kind == "dense":
        return y @ rotation
    if kind == "hadamard":
        d = rotation.shape[-1]
        inv = jnp.asarray(1.0 / math.sqrt(d), jnp.float32)
        return hadamard_transform(y) * inv * rotation
    raise ValueError(f"unknown rotation kind {kind!r} (expected one of "
                     f"{ROTATION_KINDS})")


def rotation_matrix_of(rotation, kind: str = "dense") -> jax.Array:
    """The explicit (rot_dim, rot_dim) matrix of either representation —
    for oracles/tests and the rare consumer that genuinely needs the dense
    operator (never on a hot path for kind="hadamard")."""
    if kind == "dense":
        return jnp.asarray(rotation)
    if kind == "hadamard":
        d = rotation.shape[-1]
        return srht_rotate(jnp.eye(d, dtype=jnp.float32), rotation).T
    raise ValueError(f"unknown rotation kind {kind!r} (expected one of "
                     f"{ROTATION_KINDS})")


# -- decompositions (cuSOLVER-wrapper analogs) ------------------------------


def sign_flip(u: jax.Array) -> jax.Array:
    """Deterministic sign convention: flip each column so its max-|.| element
    is positive (matrix/detail/math.cuh signFlip analog — makes eig/svd
    reproducible across backends)."""
    idx = jnp.argmax(jnp.abs(u), axis=0)
    signs = jnp.sign(u[idx, jnp.arange(u.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return u * signs[None, :]


def eig_dc(a) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition, ascending eigenvalues (linalg/eig.cuh)."""
    w, v = jnp.linalg.eigh(a)
    return w, sign_flip(v)


def svd(a, full_matrices: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    u, s, vt = jnp.linalg.svd(a, full_matrices=full_matrices)
    return sign_flip(u), s, vt


def qr(a) -> Tuple[jax.Array, jax.Array]:
    return jnp.linalg.qr(a)


def lstsq(a, b) -> jax.Array:
    """Least-squares solve via normal equations fallback-free SVD
    (linalg/lstsq.cuh analog)."""
    return jnp.linalg.lstsq(a, b)[0]


def rsvd(a, k: int, p: int = 10, n_iter: int = 4, key: Optional[jax.Array] = None):
    """Randomized SVD (linalg/rsvd.cuh analog): range-finder with power
    iterations; rank-k factors."""
    if key is None:
        key = jax.random.key(0)
    m, n = a.shape
    l = min(n, k + p)
    omega = jax.random.normal(key, (n, l), dtype=a.dtype)
    y = a @ omega
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_iter):
        q, _ = jnp.linalg.qr(a.T @ q)
        q, _ = jnp.linalg.qr(a @ q)
    b = q.T @ a
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return sign_flip(u[:, :k]), s[:k], vt[:k]


def lanczos(matvec_or_matrix, n_components: int, n=None, max_iters: int = 0,
            seed: int = 0):
    """Smallest eigenpairs of a symmetric operator via deflated Lanczos
    (reference linalg/lanczos.cuh — same engine as the sparse-tier solver,
    which accepts dense matvecs; re-exported here for the dense linalg
    surface). Accepts a CSR matrix, a (n, n) dense matrix, or a matvec
    callable."""
    import jax.numpy as jnp

    from raft_tpu.sparse.solver import lanczos_smallest
    from raft_tpu.sparse.types import CSR

    a = matvec_or_matrix
    if isinstance(a, CSR) or callable(a):
        return lanczos_smallest(a, n_components, n=n, max_iters=max_iters, seed=seed)
    dense = jnp.asarray(a)
    return lanczos_smallest(lambda v: dense @ v, n_components,
                            n=dense.shape[0], max_iters=max_iters, seed=seed)
