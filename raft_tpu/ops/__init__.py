"""Dense compute primitives (L3 of the reference layer map, SURVEY.md §1).

TPU-native re-designs of cpp/include/raft/{distance,matrix,linalg}:
  * `distance` — pairwise distances, 20 metrics (reference
    distance/distance_types.hpp:26-66) as MXU-friendly gemm expansions where
    possible, tiled VPU elementwise otherwise; fused L2 + argmin.
  * `select_k` — top-k selection (reference matrix/select_k.cuh:84); exact
    (sort-based `lax.top_k`) and TPU-optimized approximate (`lax.approx_min_k`,
    the partial-reduce algorithm from the TPU-KNN paper) backends.
  * `linalg` / `matrix` — reductions, norms, key'd reductions, gather/scatter,
    row/col ops (reference linalg/*.cuh, matrix/*.cuh).
"""

from raft_tpu.ops import distance, kernels, linalg, matrix, select_k, strip_scan
from raft_tpu.ops.distance import pairwise_distance, fused_l2_nn_argmin
from raft_tpu.ops.select_k import select_k as select_k_fn

__all__ = [
    "distance",
    "kernels",
    "strip_scan",
    "linalg",
    "matrix",
    "select_k",
    "pairwise_distance",
    "fused_l2_nn_argmin",
]
