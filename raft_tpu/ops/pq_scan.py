"""Pallas TPU kernel for the IVF-PQ list scan — the flagship kernel.

Reference analog: the `compute_similarity` kernel family
(neighbors/detail/ivf_pq_compute_similarity-inl.cuh) consumed by
`ivfpq_search_worker` (detail/ivf_pq_search.cuh:420): one CTA per (query,
probe) builds a LUT in shared memory and scans the list's packed codes.

TPU redesign. A per-(query, probe) unit is a matvec — it starves the MXU's
N dimension. Instead the scan is **list-centric**: queries probing the same
list are batched as the N dimension of one matmul per list:

    scores[l][j, i] = Σ_s LUT[q_i, s, codes[l, j, s]]
                    = OH_l @ LUT_{q_i}          with OH_l the one-hot expansion
                                                 of list l's codes

  * grid over lists (× subspace chunks when the LUT is wide);
  * the one-hot block OH_l (s_chunk·n_codes, m) is built **in VMEM** from the
    uint8 codes (broadcast + iota compare) — it never touches HBM, which is
    the entire trick: HBM reads stay at one byte per (entry, subspace);
  * one MXU matmul (qpl, s_chunk·n_codes) @ (s_chunk·n_codes, m) per chunk,
    fp32 accumulation across chunks into the output block;
  * the per-entry list-side constant b_sum (see neighbors/ivf_pq.py's LUT
    decomposition) is added on the first chunk.

The query→list grouping (who probes what, padded to a static per-list query
cap) is plain jnp around the kernel: `group_probed_pairs`. Pairs beyond the
cap are dropped (slot -1 → +inf outside); the cap defaults to 2× the mean
load so drops only occur under heavily skewed probe distributions.

VMEM budget: the one-hot block is (s_chunk·n_codes, m_block) bf16 — both
factors are tiled (subspace chunks ≤ 2048 one-hot rows; the list dim in
m_block ≤ 1024 columns) so the block stays ≤ 4 MB at any pq_bits/list size.
The subspace-chunk axis is the *innermost* grid dim so the fp32 output
block's accumulation revisits are consecutive (the Pallas TPU requirement
for read-modify-write output blocks). This is the production TPU backend for
all pq_bits 4..8; the jnp gather path stays as the oracle/CPU route.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


@functools.partial(jax.jit, static_argnames=("n_lists", "qpl_cap"))
def group_probed_pairs(probes, n_lists: int, qpl_cap: int) -> Tuple[jax.Array, jax.Array]:
    """Invert the (query, probe)→list relation.

    probes: (q, p) int32 list ids. Returns:
      qids (n_lists, qpl_cap) int32 — query ids probing each list, -1 pad;
      slot (q, p) int32 — each pair's position in its list's row, -1 dropped.
    """
    q, p = probes.shape
    flat = probes.reshape(-1)
    order = jnp.argsort(flat, stable=True)
    sorted_lists = flat[order]
    sizes = jnp.bincount(flat, length=n_lists)
    offsets = jnp.cumsum(sizes) - sizes
    rank = (jnp.arange(q * p, dtype=jnp.int32) - offsets[sorted_lists]).astype(jnp.int32)
    qid_of_pair = (order // p).astype(jnp.int32)
    # rank >= qpl_cap scatters out of bounds and is dropped
    qids = jnp.full((n_lists, qpl_cap), -1, jnp.int32)
    qids = qids.at[sorted_lists, rank].set(qid_of_pair, mode="drop")
    slot = jnp.full((q * p,), -1, jnp.int32)
    slot = slot.at[order].set(jnp.where(rank < qpl_cap, rank, -1))
    return qids, slot.reshape(q, p)


def _pq_scan_kernel(luts_ref, codes_ref, bsum_ref, out_ref, *, nc, s_chunk):
    sc = pl.program_id(3)
    ck = s_chunk * nc
    mb = codes_ref.shape[2]
    codes = codes_ref[0].astype(jnp.int32)  # (s_chunk, mb)
    # one-hot transpose OH_T[(s', c), j] = (codes[s', j] == c), built in VMEM
    rep = jnp.broadcast_to(codes[:, None, :], (s_chunk, nc, mb)).reshape(ck, mb)
    cidx = lax.broadcasted_iota(jnp.int32, (ck, mb), 0) % nc
    oh = (rep == cidx).astype(jnp.bfloat16)
    lut = luts_ref[0]  # (qpl, ck) bf16
    part = lax.dot_general(
        lut, oh, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # (qpl, mb)

    @pl.when(sc == 0)
    def _():
        # b_sum carries +inf at padding entries, masking them for free
        out_ref[0] = part + bsum_ref[0]

    @pl.when(sc != 0)
    def _():
        out_ref[0] += part


@functools.partial(jax.jit, static_argnames=("nc", "interpret"))
def pq_scan(luts_grouped, codes_t, b_sum, nc: int, interpret: bool = False) -> jax.Array:
    """Scan every list against its grouped queries.

    luts_grouped: (L, qpl, s*nc) bf16 — per-list LUT rows (pre-gathered by
      caller via qids from :func:`group_probed_pairs`; pad rows are zeros).
    codes_t: (L, s, m) uint8 — codes transposed so the list dim is minor;
      m must be a multiple of 128 (Mosaic minor-dim block constraint).
    b_sum: (L, m) fp32 — per-entry list-side constant, +inf at padding
      entries (sentinel flows through to the caller's top-k for free).
    Returns (L, qpl, m) fp32 scores (still missing the per-(q,probe) coarse
    constant, added by the caller).
    """
    L, qpl, f = luts_grouped.shape
    _, s, m = codes_t.shape
    assert f == s * nc, (f, s, nc)
    assert m % 128 == 0, f"max_list_size {m} must be 128-aligned for the kernel"
    assert qpl % 16 == 0, f"qpl {qpl} must be 16-aligned (query-block tiling)"
    # chunk subspaces so the one-hot block stays ~≤ 2048 rows …
    s_chunk = max(1, min(s, 2048 // nc))
    while s % s_chunk:
        s_chunk -= 1
    n_sc = s // s_chunk
    ck = s_chunk * nc
    # … and tile the list dim so it stays ≤ 1024 columns (the (ck, m_block)
    # bf16 one-hot must fit VMEM: unblocked m of 7K+ entries at pq_bits=8 is
    # ~30 MB and faults the chip) — and the query dim to ≤ 256 rows (skew
    # escalation can push qpl past 1000, overflowing the fp32 output block)
    m_block = min(m, 1024)
    while m % m_block:
        m_block -= 128
    n_mb = m // m_block
    q_block = min(qpl, 256)
    while qpl % q_block:
        q_block -= 16
    n_qb = qpl // q_block

    # grid order (l, qb, mb, sc): sc innermost keeps the revisited fp32
    # output block resident across its accumulation steps
    grid = (L, n_qb, n_mb, n_sc)
    return pl.pallas_call(
        functools.partial(_pq_scan_kernel, nc=nc, s_chunk=s_chunk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, q_block, ck), lambda l, qb, mb, sc: (l, qb, sc), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s_chunk, m_block), lambda l, qb, mb, sc: (l, sc, mb), memory_space=pltpu.VMEM),
            # (L, 1, m) so the block's last-two dims equal the array's
            pl.BlockSpec((1, 1, m_block), lambda l, qb, mb, sc: (l, 0, mb), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, q_block, m_block), lambda l, qb, mb, sc: (l, qb, mb), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((L, qpl, m), jnp.float32),
        interpret=interpret,
    )(luts_grouped, codes_t, b_sum.reshape(L, 1, m))


def pq_scan_reference(luts_grouped, codes_t, b_sum, nc: int) -> jax.Array:
    """Pure-jnp oracle with the exact pq_scan contract (for kernel tests)."""
    L, qpl, f = luts_grouped.shape
    s = codes_t.shape[1]
    codes = codes_t.astype(jnp.int32)  # (L, s, m)
    flat_idx = codes + (jnp.arange(s, dtype=jnp.int32) * nc)[None, :, None]

    def one_list(args):
        luts_l, idx_l, b_l = args  # (qpl, f), (s, m), (m,)
        picked = jnp.take(luts_l.astype(jnp.float32), idx_l, axis=1)  # (qpl, s, m)
        return jnp.sum(picked, axis=1) + b_l[None, :]

    return lax.map(one_list, (luts_grouped, flat_idx, b_sum))
