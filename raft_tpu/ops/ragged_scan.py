"""Ragged grouped-matmul list scan — the IVF search engine on TPU.

Reference analog: the per-(query, probe) interleaved/PQ scan kernels
(neighbors/detail/ivf_flat_interleaved_scan-inl.cuh:90,
detail/ivf_pq_compute_similarity-inl.cuh) — one CTA per probed pair, early
exit at the list's real length.

TPU redesign — the scan is a *chunk-table-driven grouped matmul*:

  1. Stage 1 (outside, cheap) computes every query's probed lists; the host
     builds a chunk table from the ACTUAL loads: each (list, query-chunk of
     ≤C queries, m-chunk of ≤MC entries) becomes one grid step. Work is
     therefore ∝ Σ_pairs len(list) — skew cannot force drops (no per-list
     cap exists) and list-length padding costs at most one partial MC chunk
     per list, not max_list_size for every list.
  2. The kernel is one MXU matmul per chunk: queries block (C, dim) ×
     list-entries block (MC, dim)ᵀ, fp32 accumulation, with the per-entry
     bias row (e.g. ‖x‖² for expanded L2, +inf at padding) fused in. Block
     placement is data-dependent → scalar-prefetched chunk arrays drive the
     BlockSpec index maps (pltpu.PrefetchScalarGridSpec), so list data is
     DMA'd straight from the index arrays — no gather materialization.
  3. Top-k: per chunk-row local top-k (a chunk holds MC entries, so
     min(k, MC) per chunk provably contains every query's global top-k),
     then a per-pair gather back through the chunk table and one final
     lax.top_k per query.

IVF-Flat feeds raw list vectors; IVF-PQ feeds *decoded* vectors (codes →
bf16 reconstruction in rotated space, built once per index): at pq_bits=8
a LUT one-hot matmul costs 2·pq_dim·256 FLOP per entry while the decoded
matmul costs 2·dim — 64× less MXU work for identical scores (decode is the
exact reconstruction the LUT sums over). The bf16 decode cache is this
framework's analog of the reference's fp8-compressed LUT
(detail/ivf_pq_fp_8bit.cuh): precision traded for bandwidth, re-ranked by
refine.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C_SLOTS = 128   # queries per q-chunk (MXU M dim)
MC = 512        # list entries per m-chunk (MXU N dim); == list group align.
                # 512 keeps the per-step matmul fat enough that grid-step
                # overhead (~μs) amortizes; lists are padded to this multiple.


def _ceil_div(a, b):
    return -(-a // b)


def _bucket(n: int) -> int:
    """Round up to a power of two (bounds the number of compiled shapes)."""
    return 1 << max(4, math.ceil(math.log2(max(n, 1))))


@dataclass
class RaggedPlan:
    """Host-built chunk table for one query tile (all arrays np.int32)."""

    chunk_list: np.ndarray   # (T,) list id per chunk
    chunk_qc: np.ndarray     # (T,) q-chunk id per chunk
    chunk_mc: np.ndarray     # (T,) m-chunk index within the list
    qids: np.ndarray         # (N_QC, C) query ids per q-chunk slot, -1 pad
    chunk_off_qc: np.ndarray  # (N_QC,) first chunk id of each q-chunk
    qc_nmc: np.ndarray       # (N_QC,) m-chunks of each q-chunk's list
    qc_list: np.ndarray      # (N_QC,) list id of each q-chunk
    pair_qc: np.ndarray      # (q, p) q-chunk of each probed pair
    pair_slot: np.ndarray    # (q, p) slot of each pair within its q-chunk
    n_chunks: int            # real chunks (<= len(chunk_list) == bucket)
    max_mc: int              # max m-chunks among probed lists

    @property
    def t_pad(self) -> int:
        return self.chunk_list.shape[0]

    @property
    def n_qc_pad(self) -> int:
        return self.qids.shape[0]


def plan_scan(probes: np.ndarray, lens: np.ndarray, n_lists: int) -> RaggedPlan:
    """Build the chunk table from a tile's probe matrix (q, p) and the
    per-list entry counts. Pure numpy — runs per tile on host (~ms), the
    data-dependent sizing the GPU does with atomics and CTA scheduling."""
    q, p = probes.shape
    flat = probes.reshape(-1).astype(np.int64)
    order = np.argsort(flat, kind="stable")
    sorted_lists = flat[order]
    qid_of = (order // p).astype(np.int32)

    r = np.bincount(flat, minlength=n_lists)            # pairs per list
    n_qc = _ceil_div(r, C_SLOTS)                        # q-chunks per list
    n_mc = _ceil_div(np.maximum(lens, 0), MC)           # m-chunks per list
    qc_off = np.concatenate([[0], np.cumsum(n_qc)]).astype(np.int64)
    n_qc_total = int(qc_off[-1])

    qc_list = np.repeat(np.arange(n_lists), n_qc)       # (n_qc_total,)
    qc_mc = n_mc[qc_list]                               # chunks per q-chunk
    chunk_off = np.concatenate([[0], np.cumsum(qc_mc)]).astype(np.int64)
    t = int(chunk_off[-1])

    chunk_qc = np.repeat(np.arange(n_qc_total), qc_mc).astype(np.int32)
    chunk_list = qc_list[chunk_qc].astype(np.int32)
    chunk_mc = (np.arange(t) - chunk_off[chunk_qc]).astype(np.int32)

    # qids per q-chunk slot
    pair_off = np.concatenate([[0], np.cumsum(r)]).astype(np.int64)
    qc_within = np.arange(n_qc_total) - qc_off[qc_list]
    pos = pair_off[qc_list][:, None] + qc_within[:, None] * C_SLOTS + np.arange(C_SLOTS)[None, :]
    valid = pos < (pair_off[qc_list] + r[qc_list])[:, None]
    qids = np.where(valid, qid_of[np.minimum(pos, max(q * p - 1, 0))], -1).astype(np.int32)

    # pair → (qc, slot) back-map
    rank = np.arange(q * p) - pair_off[sorted_lists]
    pair_qc_s = (qc_off[sorted_lists] + rank // C_SLOTS).astype(np.int32)
    pair_slot_s = (rank % C_SLOTS).astype(np.int32)
    pair_qc = np.empty(q * p, np.int32)
    pair_slot = np.empty(q * p, np.int32)
    pair_qc[order] = pair_qc_s
    pair_slot[order] = pair_slot_s

    probed_mc = n_mc[np.unique(flat)]
    max_mc = int(probed_mc.max()) if probed_mc.size else 1

    # pad to pow2 buckets (padding chunks point at block 0; their output is
    # never gathered because chunk_off_qc only spans real chunks)
    t_pad = _bucket(t)
    n_qc_pad = _bucket(n_qc_total)

    def pad(a, n, fill):
        out = np.full((n,) + a.shape[1:], fill, a.dtype)
        out[: a.shape[0]] = a
        return out

    return RaggedPlan(
        chunk_list=pad(chunk_list, t_pad, 0),
        chunk_qc=pad(chunk_qc, t_pad, 0),
        chunk_mc=pad(chunk_mc, t_pad, 0),
        qids=pad(qids, n_qc_pad, -1),
        chunk_off_qc=pad(chunk_off[:-1].astype(np.int32), n_qc_pad, 0),
        qc_nmc=pad(qc_mc.astype(np.int32), n_qc_pad, 0),
        qc_list=pad(qc_list.astype(np.int32), n_qc_pad, 0),
        pair_qc=pair_qc.reshape(q, p),
        pair_slot=pair_slot.reshape(q, p),
        n_chunks=t,
        max_mc=max(max_mc, 1),
    )


_G = 4  # chunks per grid step (amortizes the ~µs per-step overhead)


def _scan_kernel(cl_ref, cqc_ref, cmc_ref, *refs, alpha, kf, g):
    """Per step: G chunk matmuls, each immediately reduced to its rows'
    top-kf (iterative masked min — kf passes on the VPU) so only (C, kf)
    values + within-list entry offsets ever reach HBM; the full (C, MC)
    score block lives and dies in VMEM/registers."""
    a_refs = refs[0:g]
    b_refs = refs[g:2 * g]
    bias_refs = refs[2 * g:3 * g]
    outv_ref, oute_ref = refs[3 * g], refs[3 * g + 1]
    i = pl.program_id(0)
    for j in range(g):
        a = a_refs[j][0].astype(jnp.bfloat16)        # (C, dim)
        b = b_refs[j][0].astype(jnp.bfloat16)        # (MC, dim)
        acc = lax.dot_general(
            a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                            # (C, MC)
        s = alpha * acc + bias_refs[j][0]
        cols = lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mc0 = cmc_ref[i * g + j] * MC
        vs, es = [], []
        for _ in range(kf):
            mn = jnp.min(s, axis=1)                  # (C,)
            am = jnp.min(jnp.where(s <= mn[:, None], cols, MC), axis=1)
            vs.append(mn)
            es.append(mc0 + am)                      # entry offset in list
            s = jnp.where(cols == am[:, None], jnp.inf, s)
        outv_ref[0, j] = jnp.stack(vs, axis=1)       # (C, kf)
        oute_ref[0, j] = jnp.stack(es, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("t_pad", "alpha", "kf", "interpret"),
)
def _ragged_matmul(chunk_list, chunk_qc, chunk_mc, a_grouped, list_data,
                   bias, t_pad: int, alpha: float, kf: int, interpret: bool):
    """Per-chunk-row top-kf of ``alpha·A[qc_i] @ B[l_i, mc_i]ᵀ + bias``.
    Returns (vals (T, C, kf), entry_offsets (T, C, kf) int32 — offsets are
    within the chunk's *list*, so id translation can wait until after the
    per-pair reduction (a few MB instead of the full candidate set)."""
    n_qc, c, dim = a_grouped.shape
    n_lists, m, _ = list_data.shape
    g = _G if t_pad % _G == 0 else 1

    def a_map(j):
        return lambda i, cl, cqc, cmc: (cqc[i * g + j], 0, 0)

    def b_map(j):
        return lambda i, cl, cqc, cmc: (cl[i * g + j], cmc[i * g + j], 0)

    def bias_map(j):
        return lambda i, cl, cqc, cmc: (cl[i * g + j], 0, cmc[i * g + j])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(t_pad // g,),
        in_specs=(
            [pl.BlockSpec((1, c, dim), a_map(j)) for j in range(g)]
            + [pl.BlockSpec((1, MC, dim), b_map(j)) for j in range(g)]
            + [pl.BlockSpec((1, 1, MC), bias_map(j)) for j in range(g)]
        ),
        out_specs=(
            # both outputs: one (1, g, C, kf) block per step covering the
            # step's g chunks (chunk id = i*g + j, row-major)
            [pl.BlockSpec((1, g, c, kf), lambda i, cl, cqc, cmc: (i, 0, 0, 0))] * 2
        ),
    )
    bias3 = bias.reshape(n_lists, 1, m)
    lv, le = pl.pallas_call(
        functools.partial(_scan_kernel, alpha=alpha, kf=kf, g=g),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((t_pad // g, g, c, kf), jnp.float32),
            jax.ShapeDtypeStruct((t_pad // g, g, c, kf), jnp.int32),
        ),
        interpret=interpret,
    )(chunk_list, chunk_qc, chunk_mc,
      *([a_grouped] * g), *([list_data] * g), *([bias3] * g))
    return lv.reshape(t_pad, c, kf), le.reshape(t_pad, c, kf)


@functools.partial(jax.jit, static_argnames=("k", "kf", "max_mc"))
def _merge_topk(lv, le, qc_list, pair_qc, pair_slot, chunk_off_qc, qc_nmc,
                list_ids, k: int, kf: int, max_mc: int):
    """Per-chunk-row top-kf -> per-query (vals, ids) top-k.

    lv/le: (T, C, kf) kernel outputs (values + within-list entry offsets).

    Stage order matters for bandwidth: reducing per *pair* first happens in
    chunk-major layout (a dim-0 slice gather over each q-chunk's contiguous
    chunk range), so the only random gathers left touch already-reduced
    (., kp) rows — a few MB instead of the full candidate set.
    """
    t, c, _ = lv.shape
    n_qc = chunk_off_qc.shape[0]

    # per-pair reduction in qc-major layout
    mcs = jnp.arange(max_mc, dtype=jnp.int32)
    rng_ids = jnp.clip(chunk_off_qc[:, None] + mcs[None, :], 0, t - 1)
    in_rng = mcs[None, :] < qc_nmc[:, None]                  # (N_QC, max_mc)
    qc_v = jnp.where(in_rng[:, :, None, None], lv[rng_ids], jnp.inf)
    qc_e = jnp.where(in_rng[:, :, None, None], le[rng_ids], 0)
    # (N_QC, max_mc, C, kf) -> (N_QC*C, max_mc*kf) -> per-pair top-kp
    qc_v = qc_v.transpose(0, 2, 1, 3).reshape(n_qc * c, max_mc * kf)
    qc_e = qc_e.transpose(0, 2, 1, 3).reshape(n_qc * c, max_mc * kf)
    kp = min(k, max_mc * kf)  # a pair can owe up to min(k, its entries)
    pv, sel = lax.top_k(-qc_v, kp)
    pv = -pv
    pe = jnp.take_along_axis(qc_e, sel, axis=1)

    # translate within-list entry offsets -> source row ids (reduced set only)
    li = jnp.take_along_axis(
        list_ids[qc_list],
        jnp.clip(pe.reshape(n_qc, c * kp), 0, list_ids.shape[1] - 1),
        axis=1,
    ).reshape(n_qc, c, kp)
    pv = pv.reshape(n_qc, c, kp)

    # query-major gather of the reduced per-pair rows (small + random)
    q, p = pair_qc.shape
    cand_v = pv[pair_qc, pair_slot].reshape(q, p * kp)
    cand_i = li[pair_qc, pair_slot].reshape(q, p * kp)
    kk = min(k, p * kp)  # k may exceed the candidate width; pad like the
    out_v, sel = lax.top_k(-cand_v, kk)  # gather backend does
    out_i = jnp.take_along_axis(cand_i, sel, axis=1)
    out_v = -out_v
    if kk < k:
        out_v = jnp.pad(out_v, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
        out_i = jnp.pad(out_i, ((0, 0), (0, k - kk)), constant_values=-1)
    out_i = jnp.where(jnp.isfinite(out_v), out_i, -1)
    return out_v, out_i


def ragged_search(
    queries_mat,
    probes,
    list_data,
    list_bias,
    list_ids,
    lens,
    k: int,
    alpha: float = -2.0,
    workspace_bytes: int = 1 << 30,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Query-tiled ragged scan over all queries: sizes tiles so the chunk
    score block stays inside the workspace budget, then concatenates."""
    q = queries_mat.shape[0]
    probes_np = np.asarray(probes)
    lens_np = np.asarray(lens)
    p = probes_np.shape[1]
    n_lists, m = list_data.shape[0], list_data.shape[1]
    if m % MC:
        raise ValueError(f"list_data dim 1 must be a multiple of {MC}, got {m}")

    from raft_tpu.core.interruptible import check_interrupt

    q_tile = min(q, 4096)
    out_v, out_i = [], []
    start = 0
    while start < q:
        check_interrupt()
        qt = min(q_tile, q - start)
        plan = plan_scan(probes_np[start:start + qt], lens_np, n_lists)
        while plan.t_pad * C_SLOTS * MC * 4 > workspace_bytes and q_tile > 256:
            q_tile //= 2
            qt = min(q_tile, q - start)
            plan = plan_scan(probes_np[start:start + qt], lens_np, n_lists)
        v, i = _scan_with_plan(
            queries_mat[start:start + qt], plan, list_data, list_bias,
            list_ids, k, alpha, interpret,
        )
        out_v.append(v)
        out_i.append(i)
        start += qt
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, 0), jnp.concatenate(out_i, 0)


def ragged_scan_topk(
    queries_mat,
    probes,
    list_data,
    list_bias,
    list_ids,
    lens,
    k: int,
    alpha: float = -2.0,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Full ragged scan: probes (q, p) int32 → per-query top-k over the
    probed lists' entries.

    queries_mat: (q, dim) query-side matrix (rotated queries / raw queries).
    list_data: (n_lists, m, dim) entry matrix (decoded PQ / raw vectors),
      m a multiple of MC (512) — the kernel's block granule; anything less
      would read out of bounds.
    list_bias: (n_lists, m) per-entry additive term (+inf at padding).
    list_ids: (n_lists, m) source row ids (-1 padding).
    lens: (n_lists,) real entry counts.
    probes rows must hold *distinct* list ids (coarse top-p guarantees
    this); a duplicated probe would duplicate its candidates.
    Scores are ``alpha * <q, x> + bias``; smaller is better. The caller adds
    per-query constants (e.g. ‖q‖²) afterwards.
    """
    n_lists, m = list_data.shape[0], list_data.shape[1]
    if m % MC:
        raise ValueError(f"list_data dim 1 must be a multiple of {MC}, got {m}")
    plan = plan_scan(np.asarray(probes), np.asarray(lens), n_lists)
    return _scan_with_plan(queries_mat, plan, list_data, list_bias, list_ids,
                           k, alpha, interpret)


def _scan_with_plan(queries_mat, plan: RaggedPlan, list_data, list_bias,
                    list_ids, k, alpha, interpret):
    # group the query side per q-chunk (pad rows are zero; their scores are
    # garbage but unreferenced by the merge gather)
    qids = jnp.asarray(plan.qids)
    a_grouped = jnp.where(
        (qids >= 0)[:, :, None],
        jnp.asarray(queries_mat)[jnp.clip(qids, 0), :],
        0,
    ).astype(jnp.bfloat16)

    kf = min(int(k), MC)
    lv, le = _ragged_matmul(
        jnp.asarray(plan.chunk_list), jnp.asarray(plan.chunk_qc),
        jnp.asarray(plan.chunk_mc), a_grouped, list_data, list_bias,
        plan.t_pad, float(alpha), kf, bool(interpret),
    )
    return _merge_topk(
        lv, le, jnp.asarray(plan.qc_list), jnp.asarray(plan.pair_qc),
        jnp.asarray(plan.pair_slot), jnp.asarray(plan.chunk_off_qc),
        jnp.asarray(plan.qc_nmc), jnp.asarray(list_ids), int(k), kf,
        plan.max_mc,
    )
