"""Top-k selection — the load-bearing primitive for all ANN search.

Reference: raft::matrix::select_k (matrix/select_k.cuh:84) with algorithm
choices enumerated in matrix/select_k_types.hpp:36-66 — radix "AIR top-k"
(detail/select_radix.cuh) and warp-sort (detail/select_warpsort.cuh).

TPU design: radix select does not map to the VPU (no per-lane scatter/atomics);
the idiomatic backends are
  * ``"exact"`` — `lax.top_k` (XLA's sort-based top-k; exact, any k);
  * ``"iter"`` — k masked-extrema passes (exact, VPU-friendly): on TPU,
    lax.top_k lowers to a full per-row sort, measured ~10× slower than k
    sequential min+mask passes for the small k ANN uses (k ≤ 64). Matches
    lax.top_k exactly, including lowest-index tie-breaks;
  * ``"approx"`` — `lax.approx_min_k`/`approx_max_k`, the TPU partial-reduce
    top-k from the TPU-KNN paper (PAPERS.md: "TPU-KNN: K Nearest Neighbor
    Search at Peak FLOP/s") — ~recall_target accuracy at much higher
    throughput; the right default inside ANN search pipelines where candidate
    lists are over-fetched anyway.

All operate row-wise on a (batch, n) matrix, like the reference.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def iter_topk_min(values, k: int):
    """k masked-min passes over the last axis: (vals, idx) exactly matching
    ``lax.top_k(-values, k)`` semantics (ascending values, lowest index on
    ties, distinct indices even on +inf tails) without the sort. The
    per-pass work is ~4 elementwise VPU ops over the full block — for
    k ≤ ~64 this beats TPU top_k's O(n log n) sort by a wide margin."""
    v = values
    n = v.shape[-1]
    cols = lax.broadcasted_iota(jnp.int32, v.shape, v.ndim - 1)
    # explicit taken-mask (not just an inf overwrite): +inf input values are
    # indistinguishable from extracted slots, and top_k still returns
    # DISTINCT indices for them in ascending order
    taken = jnp.zeros(v.shape, jnp.bool_)
    vs, idxs = [], []
    for _ in range(k):
        masked = jnp.where(taken, jnp.inf, v)
        mn = jnp.min(masked, axis=-1, keepdims=True)
        am = jnp.min(jnp.where((masked <= mn) & ~taken, cols, n), axis=-1)
        vs.append(mn[..., 0])
        idxs.append(am)
        taken = taken | (cols == am[..., None])
    return jnp.stack(vs, -1), jnp.stack(idxs, -1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k", "select_min", "algo", "recall_target"))
def _select_k_impl(values, k, select_min, algo, recall_target):
    if algo == "approx":
        if select_min:
            vals, idx = lax.approx_min_k(values, k, recall_target=recall_target)
        else:
            vals, idx = lax.approx_max_k(values, k, recall_target=recall_target)
    elif algo == "iter":
        vals, idx = iter_topk_min(values if select_min else -values, k)
        if not select_min:
            vals = -vals
    else:
        if select_min:
            neg_vals, idx = lax.top_k(-values, k)
            vals = -neg_vals
        else:
            vals, idx = lax.top_k(values, k)
    return vals, idx.astype(jnp.int32)


def select_k(
    values,
    k: int,
    select_min: bool = True,
    indices=None,
    algo: str = "exact",
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Select k smallest (or largest) per row of ``values`` (batch, n).

    Returns ``(selected_values, selected_indices)`` with int32 indices. If
    ``indices`` (batch, n) is given, returned indices are gathered from it —
    the candidate-id remap used by IVF search's two-stage select (reference
    detail/ivf_flat_search-inl.cuh:130,194).

    ``algo``: "exact" (lax.top_k) | "iter" (k masked-min passes; exact,
    the fast TPU route for small k) | "approx" (TPU partial-reduce;
    ``recall_target`` trades recall for speed). "exact" auto-routes to
    "iter" for k <= 64 on TPU — same results, ~10x faster.
    """
    values = jnp.asarray(values)
    squeeze = values.ndim == 1
    if squeeze:
        values = values[None, :]
    if not 0 < k <= values.shape[-1]:
        raise ValueError(f"k={k} out of range for n={values.shape[-1]}")
    if algo not in ("exact", "iter", "approx"):
        raise ValueError(f"unknown select_k algo {algo!r}")
    # iter does k full passes over the row — a win over top_k's sort only
    # while the row is narrow (k·n stays small); wide rows (brute-force over
    # the whole dataset) must keep the single-sort top_k
    if (algo == "exact" and k <= 64 and values.shape[-1] <= 8192
            and jax.default_backend() == "tpu"
            and jnp.issubdtype(values.dtype, jnp.floating)):
        algo = "iter"
    if algo == "iter" and not jnp.issubdtype(values.dtype, jnp.floating):
        algo = "exact"  # the inf mask needs a floating dtype
    vals, idx = _select_k_impl(values, int(k), bool(select_min), algo, float(recall_target))
    if indices is not None:
        indices = jnp.asarray(indices)
        if squeeze and indices.ndim == 1:
            indices = indices[None, :]
        idx = jnp.take_along_axis(indices, idx, axis=1)
    if squeeze:
        return vals[0], idx[0]
    return vals, idx


def merge_topk(
    vals_a, idx_a, vals_b, idx_b, select_min: bool = True
) -> Tuple[jax.Array, jax.Array]:
    """Merge two per-row top-k lists into one (the knn_merge_parts analog,
    reference neighbors/detail/knn_merge_parts.cuh:140)."""
    vals = jnp.concatenate([vals_a, vals_b], axis=-1)
    idx = jnp.concatenate([idx_a, idx_b], axis=-1)
    k = vals_a.shape[-1]
    return select_k(vals, k, select_min=select_min, indices=idx)
