"""Top-k selection — the load-bearing primitive for all ANN search.

Reference: raft::matrix::select_k (matrix/select_k.cuh:84) with algorithm
choices enumerated in matrix/select_k_types.hpp:36-66 — radix "AIR top-k"
(detail/select_radix.cuh) and warp-sort (detail/select_warpsort.cuh).

TPU design: radix select does not map to the VPU (no per-lane scatter/atomics);
the idiomatic backends are
  * ``"exact"`` — `lax.top_k` (XLA's sort-based top-k; exact, any k);
  * ``"approx"`` — `lax.approx_min_k`/`approx_max_k`, the TPU partial-reduce
    top-k from the TPU-KNN paper (PAPERS.md: "TPU-KNN: K Nearest Neighbor
    Search at Peak FLOP/s") — ~recall_target accuracy at much higher
    throughput; the right default inside ANN search pipelines where candidate
    lists are over-fetched anyway.

Both operate row-wise on a (batch, n) matrix, like the reference.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("k", "select_min", "algo", "recall_target"))
def _select_k_impl(values, k, select_min, algo, recall_target):
    if algo == "approx":
        if select_min:
            vals, idx = lax.approx_min_k(values, k, recall_target=recall_target)
        else:
            vals, idx = lax.approx_max_k(values, k, recall_target=recall_target)
    else:
        if select_min:
            neg_vals, idx = lax.top_k(-values, k)
            vals = -neg_vals
        else:
            vals, idx = lax.top_k(values, k)
    return vals, idx.astype(jnp.int32)


def select_k(
    values,
    k: int,
    select_min: bool = True,
    indices=None,
    algo: str = "exact",
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Select k smallest (or largest) per row of ``values`` (batch, n).

    Returns ``(selected_values, selected_indices)`` with int32 indices. If
    ``indices`` (batch, n) is given, returned indices are gathered from it —
    the candidate-id remap used by IVF search's two-stage select (reference
    detail/ivf_flat_search-inl.cuh:130,194).

    ``algo``: "exact" | "approx" (TPU partial-reduce; ``recall_target``
    trades recall for speed).
    """
    values = jnp.asarray(values)
    squeeze = values.ndim == 1
    if squeeze:
        values = values[None, :]
    if not 0 < k <= values.shape[-1]:
        raise ValueError(f"k={k} out of range for n={values.shape[-1]}")
    if algo not in ("exact", "approx"):
        raise ValueError(f"unknown select_k algo {algo!r}")
    vals, idx = _select_k_impl(values, int(k), bool(select_min), algo, float(recall_target))
    if indices is not None:
        indices = jnp.asarray(indices)
        if squeeze and indices.ndim == 1:
            indices = indices[None, :]
        idx = jnp.take_along_axis(indices, idx, axis=1)
    if squeeze:
        return vals[0], idx[0]
    return vals, idx


def merge_topk(
    vals_a, idx_a, vals_b, idx_b, select_min: bool = True
) -> Tuple[jax.Array, jax.Array]:
    """Merge two per-row top-k lists into one (the knn_merge_parts analog,
    reference neighbors/detail/knn_merge_parts.cuh:140)."""
    vals = jnp.concatenate([vals_a, vals_b], axis=-1)
    idx = jnp.concatenate([idx_a, idx_b], axis=-1)
    k = vals_a.shape[-1]
    return select_k(vals, k, select_min=select_min, indices=idx)
