"""Top-k selection — the load-bearing primitive for all ANN search.

Reference: raft::matrix::select_k (matrix/select_k.cuh:84) with algorithm
choices enumerated in matrix/select_k_types.hpp:36-66 — radix "AIR top-k"
(detail/select_radix.cuh) and warp-sort (detail/select_warpsort.cuh).

TPU design: radix select does not map to the VPU (no per-lane scatter/atomics);
the idiomatic backends are
  * ``"exact"`` — `lax.top_k` (XLA's sort-based top-k; exact, any k);
  * ``"iter"`` — k masked-extrema passes (exact, VPU-friendly): on TPU,
    lax.top_k lowers to a full per-row sort, measured ~10× slower than k
    sequential min+mask passes for the small k ANN uses (k ≤ 64). Matches
    lax.top_k exactly, including lowest-index tie-breaks;
  * ``"approx"`` — `lax.approx_min_k`/`approx_max_k`, the TPU partial-reduce
    top-k from the TPU-KNN paper (PAPERS.md: "TPU-KNN: K Nearest Neighbor
    Search at Peak FLOP/s") — ~recall_target accuracy at much higher
    throughput; the right default inside ANN search pipelines where candidate
    lists are over-fetched anyway.

All operate row-wise on a (batch, n) matrix, like the reference.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def iter_topk_min(values, k: int):
    """k masked-min passes over the last axis: (vals, idx) exactly matching
    ``lax.top_k(-values, k)`` semantics (ascending values, lowest index on
    ties, distinct indices even on +inf tails) without the sort. The
    per-pass work is ~4 elementwise VPU ops over the full block — for
    k ≤ ~64 this beats TPU top_k's O(n log n) sort by a wide margin.

    NaN inputs are sanitized to +inf at entry (ADVICE r3: an all-NaN row
    used to emit out-of-range indices; lax.top_k's NaN order is
    implementation-defined anyway, so +inf-tail semantics is the sane
    contract)."""
    v = values
    v = jnp.where(jnp.isnan(v), jnp.inf, v)
    n = v.shape[-1]
    cols = lax.broadcasted_iota(jnp.int32, v.shape, v.ndim - 1)
    # explicit taken-mask (not just an inf overwrite): +inf input values are
    # indistinguishable from extracted slots, and top_k still returns
    # DISTINCT indices for them in ascending order
    taken = jnp.zeros(v.shape, jnp.bool_)
    vs, idxs = [], []
    for _ in range(k):
        masked = jnp.where(taken, jnp.inf, v)
        mn = jnp.min(masked, axis=-1, keepdims=True)
        am = jnp.min(jnp.where((masked <= mn) & ~taken, cols, n), axis=-1)
        vs.append(mn[..., 0])
        idxs.append(am)
        taken = taken | (cols == am[..., None])
    return jnp.stack(vs, -1), jnp.stack(idxs, -1).astype(jnp.int32)


def _pack_bits_for(n: int) -> int:
    b = 1
    while (1 << b) < n:
        b += 1
    return b


def pack_clamp_for(bits: int) -> float:
    """Largest finite fp32 whose truncated mantissa survives OR-ing any
    ``bits``-wide index without overflowing into the exponent."""
    import numpy as _np

    return float(_np.array((0x7F7FFFFF >> bits) << bits, _np.uint32)
                 .view(_np.float32))


def pack_values(v, bits: int):
    """Pack per-position column ids into the low ``bits`` mantissa bits of
    fp32 ``v`` (last axis). Shared by iter_topk_min_packed and the strip
    kernel's in-kernel extraction (ops/strip_scan._pack_scores) so the
    clamp/NaN/±inf invariants live in one place:

    * NaN → +inf → clamped (a NaN would poison every min pass);
    * ±inf → ±max-finite-packable (OR-ing bits into an inf mantissa mints
      NaN — the code-review r4 -inf finding);
    * packed values within a row are unique (distinct column bits), so a
      min + equality-mask pass extracts exactly one element.
    Perturbation ≤ 2^-(23-bits) relative, for negatives too (mantissa grows
    → more negative, same bound)."""
    clamp = pack_clamp_for(bits)
    mask = (1 << bits) - 1
    cols = lax.broadcasted_iota(jnp.int32, v.shape, v.ndim - 1)
    v = jnp.where(jnp.isnan(v), jnp.inf, v)
    v = jnp.clip(v, -clamp, clamp)
    return lax.bitcast_convert_type(
        (lax.bitcast_convert_type(v, jnp.int32) & jnp.int32(~mask)) | cols,
        jnp.float32)


def iter_topk_min_packed(values, k: int):
    """Approximate iter_topk_min at HALF the per-pass cost: the column index
    rides the low mantissa bits of the fp32 value, so each pass is one min
    reduction + one equality mask — no argmin reconstruction.

    Values are perturbed by ≤ 2^-(23-b) relative (b = ceil(log2 n) index
    bits; 10 bits → 1.2e-4) — noise on the order of this repo's bf16 scan
    contract, NOT an exact select. Packed values within a row are unique,
    so ties and +inf tails still yield distinct in-range indices. NaN → +inf.
    """
    v = values.astype(jnp.float32)
    n = v.shape[-1]
    b = _pack_bits_for(n)
    mask = (1 << b) - 1
    clamp = pack_clamp_for(b)
    pv = pack_values(v, b)
    vs, idxs = [], []
    for _ in range(k):
        mn = jnp.min(pv, axis=-1)
        mb = lax.bitcast_convert_type(mn, jnp.int32)
        idxs.append(mb & jnp.int32(mask))
        vs.append(lax.bitcast_convert_type(mb & jnp.int32(~mask),
                                           jnp.float32))
        pv = jnp.where(pv == mn[..., None], jnp.inf, pv)
    out_v = jnp.stack(vs, -1)
    # restore the ±inf the packing clamped away (code-review r4: a clamped
    # +inf sentinel — filtered/padding entries — must NOT come back as a
    # finite ~3.4e38 "hit"; downstream isfinite masks depend on it).
    # clamp's low mantissa bits are zero, so clamped unpacked values equal
    # it exactly; the compare uses the static python float
    out_v = jnp.where(out_v >= clamp, jnp.inf, out_v)
    out_v = jnp.where(out_v <= -clamp, -jnp.inf, out_v)
    return out_v, jnp.stack(idxs, -1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k", "select_min", "algo", "recall_target"))
def _select_k_impl(values, k, select_min, algo, recall_target):
    if algo == "approx":
        if select_min:
            vals, idx = lax.approx_min_k(values, k, recall_target=recall_target)
        else:
            vals, idx = lax.approx_max_k(values, k, recall_target=recall_target)
    elif algo == "packed":
        vals, idx = iter_topk_min_packed(values if select_min else -values, k)
        if not select_min:
            vals = -vals
    elif algo == "iter":
        vals, idx = iter_topk_min(values if select_min else -values, k)
        if not select_min:
            vals = -vals
    else:
        if select_min:
            neg_vals, idx = lax.top_k(-values, k)
            vals = -neg_vals
        else:
            vals, idx = lax.top_k(values, k)
    return vals, idx.astype(jnp.int32)


def select_k(
    values,
    k: int,
    select_min: bool = True,
    indices=None,
    algo: str = "exact",
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Select k smallest (or largest) per row of ``values`` (batch, n).

    Returns ``(selected_values, selected_indices)`` with int32 indices. If
    ``indices`` (batch, n) is given, returned indices are gathered from it —
    the candidate-id remap used by IVF search's two-stage select (reference
    detail/ivf_flat_search-inl.cuh:130,194).

    ``algo``: "exact" (lax.top_k) | "iter" (k masked-min passes; exact,
    the fast TPU route for small k) | "packed" (mantissa-packed iter —
    half the passes' cost, values perturbed ≤ 2^-(23-ceil(log2 n))
    relative — ~1e-4 at n=1024, ~1e-3 at the n=8192 fallback bound) |
    "approx"
    (TPU partial-reduce; ``recall_target`` trades recall for speed).
    "exact" auto-routes to "iter" for k <= 64 on TPU — same results,
    ~10x faster.
    """
    values = jnp.asarray(values)
    squeeze = values.ndim == 1
    if squeeze:
        values = values[None, :]
    if not 0 < k <= values.shape[-1]:
        raise ValueError(f"k={k} out of range for n={values.shape[-1]}")
    if algo not in ("exact", "iter", "approx", "packed"):
        raise ValueError(f"unknown select_k algo {algo!r}")
    # iter does k full passes over the row — a win over top_k's sort only
    # while the row is narrow (k·n stays small); wide rows (brute-force over
    # the whole dataset) must keep the single-sort top_k
    if (algo == "exact" and k <= 64 and values.shape[-1] <= 8192
            and jax.default_backend() == "tpu"
            and jnp.issubdtype(values.dtype, jnp.floating)):
        algo = "iter"
    if (algo in ("iter", "packed")
            and not jnp.issubdtype(values.dtype, jnp.floating)):
        algo = "exact"  # the inf mask needs a floating dtype
    if algo == "packed" and values.shape[-1] > (1 << 13):
        # packing always happens in fp32 regardless of input dtype, and the
        # perturbation is 2^-(23-ceil(log2 n)) relative: 13 index bits keep
        # it ≤ ~1e-3; wider rows would steal 14-16 mantissa bits (~1e-2
        # worst case — inconsistent with the documented contract, ADVICE
        # r4), so they fall back to the exact iter select
        algo = "iter"
    vals, idx = _select_k_impl(values, int(k), bool(select_min), algo, float(recall_target))
    if indices is not None:
        indices = jnp.asarray(indices)
        if squeeze and indices.ndim == 1:
            indices = indices[None, :]
        idx = jnp.take_along_axis(indices, idx, axis=1)
    if squeeze:
        return vals[0], idx[0]
    return vals, idx


def merge_topk(
    vals_a, idx_a, vals_b, idx_b, select_min: bool = True
) -> Tuple[jax.Array, jax.Array]:
    """Merge two per-row top-k lists into one (the knn_merge_parts analog,
    reference neighbors/detail/knn_merge_parts.cuh:140)."""
    vals = jnp.concatenate([vals_a, vals_b], axis=-1)
    idx = jnp.concatenate([idx_a, idx_b], axis=-1)
    k = vals_a.shape[-1]
    return select_k(vals, k, select_min=select_min, indices=idx)
