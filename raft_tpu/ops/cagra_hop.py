"""Pallas TPU kernel for the fused CAGRA traversal hop.

One iteration of the compressed best-first loop
(neighbors/cagra._search_impl_compressed) costs five separate XLA ops —
graph-row gather, neighbor-code gather, int8→bf16 einsum, compare-matrix
dedup, itopk merge — each materializing its intermediate in HBM. At the
1M bench shape (q=10k, w=4, deg=64, p=64) the (q, w·deg, p) code
intermediate alone is ~160 MB written+read back per hop, and the two
gathers are op-bound (~12 ns/row regardless of width, the round-5
measurement). This kernel performs the whole hop in one ``pallas_call``:

* **gather** — for a block of queries, the ``width`` parent graph rows and
  their inlined ``(deg, p)`` int8 code records are DMA'd HBM→VMEM directly
  (the Ragged Paged Attention pattern, PAPERS.md: page indices ride scalar
  prefetch, the kernel issues per-record ``make_async_copy``); neither
  array is ever materialized through an XLA gather;
* **distance** — one int8→bf16 MXU contraction per block
  (``‖c‖² − 2⟨qp, c⟩`` in projected code units, exactly the unfused
  ``code_dists``), accumulated fp32, entirely in VMEM;
* **dedup** — the exact compare-matrix branch of cagra's
  ``_merge_candidates`` (candidate-vs-buffer and candidate-vs-earlier-
  candidate); the (b, b) compare lives in VMEM so the slack+re-select
  fallback the unfused loop needs for wide candidate sets never applies;
* **merge** — the mantissa-packed iter select (ops/select_k.
  ``iter_topk_min_packed`` — the kernel calls the very same function, so
  tie/±inf/NaN semantics cannot drift) over ``[buffer ‖ candidates]``,
  with id/visited payloads extracted by an exact fp32 one-hot contraction
  (single-term sums — bit-identical to ``take_along_axis``, but it lowers
  to an MXU matmul instead of a per-lane gather Mosaic can't do).

Parent *selection* (best ``width`` unvisited buffer slots) stays a tiny
jnp op in the caller's loop body: the DMA engine needs the parent ids as
scalars, and scalar-prefetch is how a Pallas TPU kernel receives them.

Layout/limits:

* queries are processed in ``q_block`` rows per grid step; callers pad q
  to a multiple (the padded rows ride with ids=-1/vis=1 and are sliced
  off by the caller);
* payload ids are extracted through an exact fp32 contraction, so dataset
  ids must stay below 2**24 (asserted); the unfused loop has no such
  bound and remains the route past 16.7M rows per shard;
* ``interpret=True`` is the CPU/test route (pq_scan.py precedent); the
  compiled path is TPU-only.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.select_k import iter_topk_min_packed

# exact-id bound of the fp32 one-hot payload extraction (24-bit mantissa)
MAX_FUSED_ROWS = 1 << 24


def _hop_kernel(parents_smem, parents_ref, qp_ref, bids_ref, bd_ref,
                bvis_ref, graph_hbm, codes_hbm, oid_ref, od_ref, ovis_ref,
                gr_s, code_s, gsem, csem, *, w, itopk):
    qb = pl.program_id(0)
    q_block, p = qp_ref.shape
    deg = graph_hbm.shape[1]
    b = w * deg
    inf = jnp.float32(jnp.inf)
    base = qb * q_block

    # ---- gather: DMA the parent graph rows + code records HBM→VMEM -------
    # all copies are issued before any is awaited so their latencies
    # overlap; the two shared semaphores drain exactly the issued bytes
    def issue(r, _):
        pid = jnp.maximum(parents_smem[base + r // w, r % w], 0)
        pltpu.make_async_copy(graph_hbm.at[pid], gr_s.at[r], gsem).start()
        pltpu.make_async_copy(codes_hbm.at[pid], code_s.at[r], csem).start()
        return 0

    def drain(r, _):
        pid = jnp.maximum(parents_smem[base + r // w, r % w], 0)
        pltpu.make_async_copy(graph_hbm.at[pid], gr_s.at[r], gsem).wait()
        pltpu.make_async_copy(codes_hbm.at[pid], code_s.at[r], csem).wait()
        return 0

    lax.fori_loop(0, q_block * w, issue, 0)
    lax.fori_loop(0, q_block * w, drain, 0)

    # ---- candidates: invalid parents (slot -1) poison their whole row ----
    pvalid = parents_ref[...] >= 0  # (q_block, w)
    gr = gr_s[...].reshape(q_block, b)
    vmask = jnp.broadcast_to(
        pvalid[:, :, None], (q_block, w, deg)).reshape(q_block, b)
    nbrs = jnp.where(vmask & (gr >= 0), gr, -1)

    # ---- distance: one int8→bf16 MXU contraction (code_dists analog) -----
    cf = code_s[...].astype(jnp.bfloat16).reshape(q_block, b, p)
    qpv = qp_ref[...].astype(jnp.bfloat16)
    ip = jnp.einsum("qmp,qp->qm", cf, qpv,
                    preferred_element_type=jnp.float32)
    nrm = jnp.einsum("qmp,qmp->qm", cf, cf,
                     preferred_element_type=jnp.float32)
    cd = jnp.where(nbrs >= 0, nrm - 2.0 * ip, inf)

    # ---- dedup: the exact branch of _merge_candidates, VMEM-resident -----
    bids = bids_ref[...]
    dup_buf = jnp.any(nbrs[:, :, None] == bids[:, None, :], axis=2)
    ii = lax.broadcasted_iota(jnp.int32, (b, b), 0)
    jj = lax.broadcasted_iota(jnp.int32, (b, b), 1)
    dup_self = jnp.any(
        (nbrs[:, :, None] == nbrs[:, None, :]) & (jj < ii)[None], axis=2)
    cd = jnp.where(dup_buf | dup_self | (nbrs < 0), inf, cd)

    # ---- merge: packed select over [buffer ‖ candidates] -----------------
    allv = jnp.concatenate([bd_ref[...], cd], axis=1)
    alli = jnp.concatenate([bids, nbrs], axis=1)
    allvis = jnp.concatenate(
        [bvis_ref[...], jnp.zeros((q_block, b), jnp.float32)], axis=1)
    nv, sel = iter_topk_min_packed(allv, itopk)
    cat_w = itopk + b
    cols = lax.broadcasted_iota(jnp.int32, (q_block, 1, cat_w), 2)
    oh = (sel[:, :, None] == cols).astype(jnp.float32)
    # single-term fp32 sums: exact for ids < 2**24 and for 0/1 vis flags —
    # but ONLY at highest precision: the TPU MXU's default fp32 matmul is
    # single-pass bf16 (~8 mantissa bits, see ops/distance.py), which would
    # round any id > 256 before the multiply
    ni = jnp.einsum("qkc,qc->qk", oh, alli.astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                    precision=lax.Precision.HIGHEST).astype(jnp.int32)
    nvis = jnp.einsum("qkc,qc->qk", oh, allvis,
                      preferred_element_type=jnp.float32,
                      precision=lax.Precision.HIGHEST)
    oid_ref[...] = jnp.where(jnp.isinf(nv), -1, ni)
    od_ref[...] = nv
    ovis_ref[...] = nvis


@functools.partial(jax.jit, static_argnames=("q_block", "interpret"))
def fused_hop(buf_ids, buf_d, buf_vis, parents, qp, graph, nbr_codes,
              q_block: int = 32, interpret: bool = False,
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fused traversal hop for every query.

    buf_ids/buf_d/buf_vis: (q, itopk) int32/fp32/fp32 — the candidate
      buffer (vis is 1.0 at visited slots; parents must already be marked).
    parents: (q, w) int32 — parent ids to expand, -1 = no parent (its
      candidates are masked, mirroring the unfused ``parent_ok`` path).
    qp: (q, p) fp32 — queries in code units ((q @ proj) / code_scale).
    graph: (n, deg) int32; nbr_codes: (n, deg, p) int8 — HBM-resident.

    Returns the merged (ids, distances, vis) buffer. q must be a multiple
    of ``q_block`` (callers pad; see neighbors/cagra's fused driver).
    """
    q, itopk = buf_ids.shape
    w = parents.shape[1]
    n, deg = graph.shape
    p = qp.shape[1]
    assert q % q_block == 0, (q, q_block)
    assert nbr_codes.shape == (n, deg, p), (nbr_codes.shape, (n, deg, p))
    assert n <= MAX_FUSED_ROWS, \
        f"fused hop id extraction is exact below {MAX_FUSED_ROWS} rows"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(q // q_block,),
        in_specs=[
            pl.BlockSpec((q_block, w), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_block, p), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_block, itopk), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_block, itopk), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_block, itopk), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((q_block, itopk), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_block, itopk), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_block, itopk), lambda qb, P: (qb, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_block * w, deg), jnp.int32),
            pltpu.VMEM((q_block * w, deg, p), jnp.int8),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        functools.partial(_hop_kernel, w=w, itopk=itopk),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((q, itopk), jnp.int32),
            jax.ShapeDtypeStruct((q, itopk), jnp.float32),
            jax.ShapeDtypeStruct((q, itopk), jnp.float32),
        ],
        interpret=interpret,
    )(parents, parents, qp, buf_ids, buf_d, buf_vis, graph, nbr_codes)


def occupancy_stats(q: int, q_block: int, width: int, degree: int,
                    proj_dim: int, itopk: int) -> dict:
    """Static occupancy diagnostics of one fused-hop dispatch (round 15:
    the "does the fused hop underfill the MXU" question as numbers).
    ``q`` is the REAL query count; the caller pads to a ``q_block``
    multiple, and the padded rows ride every hop with ids=-1/vis=1 —
    pure overhead the grid still executes. ``block`` is the per-grid-step
    distance contraction shape (q_block × width·degree × proj_dim);
    ``mxu_m_fill`` is how much of the 128-row MXU M-tile the q_block
    occupies — the knob ``RAFT_TPU_CAGRA_QBLOCK`` re-tuning moves."""
    q_block = max(1, int(q_block))
    q_pad = -(-int(q) // q_block) * q_block
    b = int(width) * int(degree)
    return {
        "grid": [int(q_pad // q_block)],
        "q": int(q),
        "q_pad": int(q_pad),
        "q_block": int(q_block),
        "padded_row_fraction": round(1.0 - q / q_pad, 4) if q_pad else 0.0,
        "tile_fill": round(q / q_pad, 4) if q_pad else 0.0,
        "block": [int(q_block), b, int(proj_dim)],
        "candidates_per_query": b,
        "merge_width": int(itopk) + b,
        "mxu_m_fill": round(min(1.0, q_block / 128.0), 4),
    }


def fused_hop_reference(buf_ids, buf_d, buf_vis, parents, qp, graph,
                        nbr_codes) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pure-jnp oracle with the exact fused_hop contract (kernel tests):
    the unfused gather/einsum/dedup/merge ops of cagra's compressed loop
    body, candidate-side duplicates masked exactly pre-select."""
    q, itopk = buf_ids.shape
    w = parents.shape[1]
    deg = graph.shape[1]
    p = qp.shape[1]
    b = w * deg
    inf = jnp.float32(jnp.inf)

    pid_c = jnp.maximum(parents, 0)
    gr = graph[pid_c]                       # (q, w, deg)
    codes = nbr_codes[pid_c]                # (q, w, deg, p)
    nbrs = jnp.where((parents >= 0)[:, :, None] & (gr >= 0), gr, -1)
    nbrs = nbrs.reshape(q, b)
    cf = codes.reshape(q, b, p).astype(jnp.bfloat16)
    ip = jnp.einsum("qmp,qp->qm", cf, qp.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32)
    nrm = jnp.einsum("qmp,qmp->qm", cf, cf,
                     preferred_element_type=jnp.float32)
    cd = jnp.where(nbrs >= 0, nrm - 2.0 * ip, inf)

    dup_buf = jnp.any(nbrs[:, :, None] == buf_ids[:, None, :], axis=2)
    tri = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)
    dup_self = jnp.any(
        (nbrs[:, :, None] == nbrs[:, None, :]) & tri[None], axis=2)
    cd = jnp.where(dup_buf | dup_self | (nbrs < 0), inf, cd)

    allv = jnp.concatenate([buf_d, cd], axis=1)
    alli = jnp.concatenate([buf_ids, nbrs], axis=1)
    allvis = jnp.concatenate([buf_vis, jnp.zeros((q, b), jnp.float32)],
                             axis=1)
    nv, sel = iter_topk_min_packed(allv, itopk)
    ni = jnp.take_along_axis(alli, sel, axis=1)
    nvis = jnp.take_along_axis(allvis, sel, axis=1)
    return jnp.where(jnp.isinf(nv), -1, ni), nv, nvis
