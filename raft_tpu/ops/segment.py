"""Static-shape segment/scatter utilities for graph algorithms.

The GPU reference scatters candidate edges into per-node lists with atomics
(e.g. NN-descent's update loop, neighbors/detail/nn_descent.cuh:1215, and
CAGRA's hashmap dedup, detail/cagra/hashmap.hpp). TPUs have no scatter
atomics; the idiomatic replacement is sort-based distribution: sort the edge
list by target segment, locate each segment's span with ``searchsorted``, and
gather a *capped* number of entries per segment — every shape static, every
step a vectorized sort/gather that XLA maps onto the VPU.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def segment_take(
    keys_sorted: jax.Array,
    n_segments: int,
    cap: int,
    *values: jax.Array,
) -> Tuple[jax.Array, ...]:
    """Per-segment capped gather from a key-sorted flat array.

    ``keys_sorted`` is an ascending (m,) int array of segment ids (invalid
    entries must be sorted to the end with key >= n_segments). For each
    segment s, gathers the first ``cap`` positions of its span. Returns
    ``(valid (n_segments, cap) bool, *gathered values)``.

    This is the TPU replacement for "atomic append to per-node buffer":
    entries beyond ``cap`` per segment are dropped — callers bound the loss
    (it mirrors the reference's fixed-size per-node buffers).
    """
    m = keys_sorted.shape[0]
    starts = jnp.searchsorted(keys_sorted, jnp.arange(n_segments, dtype=keys_sorted.dtype))
    pos = starts[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
    in_range = pos < m
    posc = jnp.minimum(pos, m - 1)
    valid = in_range & (keys_sorted[posc] == jnp.arange(n_segments)[:, None])
    return (valid,) + tuple(v[posc] for v in values)


def merge_topk_dedup(
    ids: jax.Array,
    dists: jax.Array,
    cand_ids: jax.Array,
    cand_dists: jax.Array,
    k: int,
    exclude_self: jax.Array = None,
    payload: jax.Array = None,
    cand_payload: jax.Array = None,
):
    """Row-wise merge of a neighbor list with candidates, dedup by id, top-k.

    Inputs are (n, a) current lists and (n, b) candidates; invalid entries
    are id=-1 / dist=+inf. ``exclude_self`` (n,) optionally removes each
    row's own id. Returns ``(ids (n,k), dists (n,k), from_cand (n,k))`` —
    ``from_cand`` marks entries that came from the candidate side (the
    update counter NN-descent's termination test needs). If ``payload`` /
    ``cand_payload`` (same shapes as the id arrays) are given, the surviving
    entries' payload is returned as a fourth output (used to carry
    NN-descent's new/old flags through the merge).

    This is the sort-based replacement for the reference's bitonic
    merge-and-dedup (nn_descent.cuh local_join / cagra search's
    topk_by_bitonic_sort + hashmap): one lexsort by (id, dist) marks
    duplicates, one value sort restores distance order.
    """
    inf = jnp.float32(jnp.inf)
    all_ids = jnp.concatenate([ids, cand_ids], axis=1)
    all_d = jnp.concatenate([dists, cand_dists], axis=1)
    all_c = jnp.concatenate(
        [jnp.zeros(ids.shape, jnp.bool_), jnp.ones(cand_ids.shape, jnp.bool_)],
        axis=1,
    )
    has_payload = payload is not None
    if has_payload:
        all_p = jnp.concatenate([payload, cand_payload], axis=1)
    # primary key id, secondary dist: first occurrence of each id is its best
    order = jnp.lexsort((all_d, all_ids), axis=-1)
    sid = jnp.take_along_axis(all_ids, order, axis=1)
    sd = jnp.take_along_axis(all_d, order, axis=1)
    sc = jnp.take_along_axis(all_c, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((sid.shape[0], 1), jnp.bool_), sid[:, 1:] == sid[:, :-1]], axis=1
    )
    bad = dup | (sid < 0)
    if exclude_self is not None:
        bad = bad | (sid == exclude_self[:, None])
    sd = jnp.where(bad, inf, sd)
    # restore distance order, take k
    order2 = jnp.argsort(sd, axis=1)[:, :k]
    out_ids = jnp.take_along_axis(sid, order2, axis=1)
    out_d = jnp.take_along_axis(sd, order2, axis=1)
    out_c = jnp.take_along_axis(sc, order2, axis=1)
    out_ids = jnp.where(jnp.isinf(out_d), -1, out_ids)
    out_c = out_c & ~jnp.isinf(out_d)
    if has_payload:
        sp = jnp.take_along_axis(all_p, order, axis=1)
        out_p = jnp.take_along_axis(sp, order2, axis=1)
        return out_ids, out_d, out_c, out_p
    return out_ids, out_d, out_c
