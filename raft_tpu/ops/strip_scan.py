"""Strip scan — the IVF list-scan engine on TPU (round-3 rewrite).

Reference analog: the per-(query, probe) interleaved/PQ scan kernels
(neighbors/detail/ivf_flat_interleaved_scan-inl.cuh:90,
detail/ivf_pq_compute_similarity-inl.cuh) — one CTA per probed pair, early
exit at the list's real length — plus the multi-pass select pipeline
(detail/ivf_pq_search.cuh:586).

TPU redesign, round 3. Round 2's chunk-table scan (one grid step per
(list, q-chunk, 512-entry m-chunk)) measured DMA-latency-bound: ~9 µs per
512-entry chunk of pure block-fetch latency (the matmul itself is ~0.3 µs),
plus a 3-stage XLA merge whose per-pair gather/top_k dominated everything
(lax.top_k on TPU is a full sort; the qc-major gather rematerialized the
candidate set twice). The fix is to make the unit of work a **strip**: one
grid step covers one (list × ≤C-query block) pair across the ENTIRE list —
a single contiguous (L·512, dim) DMA instead of L separate 512-blocks — and
to finish the per-pair top-k INSIDE the kernel, so the host-side merge
shrinks to one gather + one small select over (q, n_probes·kf).

  * Lists are length-classed: class L ∈ {1..MAX_CLASS} (pow2) covers
    lists of up to L·512 entries (list storage is padded to a power-of-two
    number of 512-blocks, so every class divides the array). Longer lists
    keep a (MAX_CLASS·512, dim) working block and iterate sub-blocks via a
    second grid dimension, merging running top-kf across revisits — VMEM
    stays bounded no matter the list length.
  * Per strip: one MXU matmul (C, dim) × (W, dim)ᵀ → (C, W) fp32 scores
    (+ per-entry bias, +inf at padding), then a strided-bin tournament
    top-k on the VPU extracts per-(query, list) top-kf values + offsets.
    A (query, probe) pair maps to exactly one strip slot, so these ARE the
    per-pair candidates — no cross-chunk reduction exists anymore.
  * The merge is one XLA gather of (q, p, kf) candidate rows followed by an
    iterative top-k over p·kf candidates (ops/select_k.iter_topk_min; TPU
    top_k's sort measured ~10× slower at these widths) and one final
    (q, k) id-translate gather.

Work remains ∝ Σ_pairs len(list): no per-list query cap, zero candidate
drops by construction (pairs beyond one strip's query slots get their
own strip). Strip counts per class are bucketed (two buckets per octave) to
bound compiled-shape count; padding strips carry strip_list = -1 and are
skipped entirely in-kernel (round 4 — they used to scan list 0 unread).

The B operand can be fp32/bf16 (IVF-Flat raw vectors, IVF-PQ bf16 decoded
cache) or int8 (IVF-PQ's quantized decoded cache at rot_dim bytes/entry —
the fp8-LUT-compression analog, detail/ivf_pq_fp_8bit.cuh): the kernel
upcasts in VMEM, and the caller folds the dequant scale into the query
operand, so int8 costs one VPU convert and nothing else.

Round-4 changes (measured on the 1M bench shape):

  * **Sync-free fused search** — the dynamic plan's per-tile strip-count
    fetch (device→host sync mid-search) is replaced by a static
    worst-case class layout (``static_layout``); the whole search
    (coarse → device plan → kernel → merge → finalize) compiles into ONE
    dispatch (`ivf_flat._ragged_fused` / `ivf_pq._ragged_fused_pq`).
    Padding strips carry ``strip_list = -1``: the kernel skips their body
    (`pl.when`) and their block maps collapse to constants so the
    pipeline skips the re-fetches.
  * **Mantissa-packed extraction** — the in-kernel top-kf packs the
    column id into the low 12 mantissa bits of the fp32 score
    (select_k.pack_values): each pass is one min + one equality mask (2
    full-width VPU ops vs 5), which was the kernel's dominant cost.
  * Together: IVF-Flat 43K → 92K QPS, IVF-PQ 33K → 54K at unchanged
    recall (0.985), single chip, 1M × 128.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C = 192          # queries per strip (MXU M dim; fewer, fatter strips
                 # amortize the measured ~25 µs fixed per-strip cost;
                 # 256 measured a VMEM stack OOM at kf=40)
MC = 512         # base entry block; class-L strips read L*MC entries at once
MAX_CLASS = 8    # biggest single-fetch strip (w = 4096 entries). Round 4:
                 # the packed extraction holds ONE live score copy, so wide
                 # blocks now fit VMEM where round 3's unrolled extraction
                 # OOM'd at w=2048 — cutting grid steps for 1-4K-entry
                 # lists measured IVF-Flat 97→111K and IVF-PQ 63→92K QPS
                 # at the 1M bench shape (validated up to kf=129 in-kernel)


def _ceil_div(a, b):
    return -(-a // b)


def strip_eligible(m: int) -> bool:
    """True when a padded list length can feed the strip kernel: a
    power-of-two multiple of MC (every length class must divide it)."""
    return m % MC == 0 and (m // MC) & (m // MC - 1) == 0


def _next_pow2(n: int) -> int:
    return 1 << max(0, math.ceil(math.log2(max(int(n), 1))))


def _bucket(n: int) -> int:
    """Two buckets per octave (pow2 and 1.5·pow2): ≤ 33% padding waste while
    keeping the compiled-shape count ~2·log2(range)."""
    n = max(int(n), 8)
    p = 1 << math.floor(math.log2(n))
    if n <= p:
        return p
    if n <= p + p // 2:
        return p + p // 2
    return 2 * p


@dataclass
class StripPlan:
    """Host-built strip table for one query tile (arrays np.int32)."""

    qids: np.ndarray         # (S_pad, C) query id per strip slot, -1 pad
    strip_list: np.ndarray   # (S_pad,) list id per strip
    pair_strip: np.ndarray   # (q, p) strip of each probed pair
    pair_slot: np.ndarray    # (q, p) slot within the strip
    # static per-call layout: ((class_w_blocks, n_sub, start, count), ...)
    class_layout: Tuple[Tuple[int, int, int, int], ...]
    n_strips: int            # real strips (<= S_pad)

    @property
    def s_pad(self) -> int:
        return self.strip_list.shape[0]


def plan_strips(probes: np.ndarray, lens: np.ndarray, n_lists: int) -> StripPlan:
    """Build the strip table from a tile's probe matrix (q, p) and per-list
    entry counts. Pure numpy, ~ms per tile — the data-dependent scheduling
    the GPU does with atomics and CTA dispatch."""
    q, p = probes.shape
    flat = probes.reshape(-1).astype(np.int64)
    order = np.argsort(flat, kind="stable")
    sorted_lists = flat[order]
    qid_of = (order // p).astype(np.int32)

    r = np.bincount(flat, minlength=n_lists)             # pairs per list
    n_qc = _ceil_div(r, C)                               # strips per list
    n_mc = np.maximum(_ceil_div(np.maximum(lens, 0), MC), 1)
    cls_full = 1 << np.ceil(np.log2(n_mc)).astype(np.int64)
    cls = np.minimum(cls_full, MAX_CLASS)                # fetch-block class
    n_sub = np.maximum(cls_full // MAX_CLASS, 1)         # sub-block iterations

    # group probed lists by (cls, n_sub); fixed ascending order keeps the
    # class_layout static across tiles of the same distribution
    probed = np.nonzero(n_qc)[0]
    keys = (cls[probed] << 32) | n_sub[probed]
    uniq_keys = np.unique(keys)

    strip_base = np.zeros(n_lists, np.int64)
    strip_list_parts, layout = [], []
    start = 0
    for key in uniq_keys:
        w_blocks = int(key >> 32)
        sub = int(key & 0xFFFFFFFF)
        lists_g = probed[keys == key]
        count = int(n_qc[lists_g].sum())
        pad = _bucket(count)
        sl = np.full(pad, -1, np.int32)  # padding strips: kernel-skipped
        sl[:count] = np.repeat(lists_g.astype(np.int32), n_qc[lists_g])
        base = start + np.concatenate([[0], np.cumsum(n_qc[lists_g])[:-1]])
        strip_base[lists_g] = base
        strip_list_parts.append(sl)
        layout.append((w_blocks, sub, start, pad))
        start += pad

    s_pad = start
    strip_list = (np.concatenate(strip_list_parts) if strip_list_parts
                  else np.zeros(1, np.int32))
    if not layout:  # degenerate: no probes
        layout = [(1, 1, 0, 1)]
        s_pad = 1

    # per-pair (strip, slot): rank of the pair within its list's probe set
    pair_off = np.concatenate([[0], np.cumsum(r)]).astype(np.int64)
    rank = np.arange(q * p) - pair_off[sorted_lists]
    ps_sorted = (strip_base[sorted_lists] + rank // C).astype(np.int32)
    slot_sorted = (rank % C).astype(np.int32)
    pair_strip = np.empty(q * p, np.int32)
    pair_slot = np.empty(q * p, np.int32)
    pair_strip[order] = ps_sorted
    pair_slot[order] = slot_sorted

    # query ids per strip slot (pair arrays are in original pair order, so
    # the query of pair i is simply i // p)
    qids = np.full((s_pad, C), -1, np.int32)
    qids[pair_strip, pair_slot] = (np.arange(q * p) // p).astype(np.int32)

    return StripPlan(
        qids=qids,
        strip_list=strip_list,
        pair_strip=pair_strip.reshape(q, p),
        pair_slot=pair_slot.reshape(q, p),
        class_layout=tuple(layout),
        n_strips=int(n_qc.sum()),
    )


_PACK_BITS = 12          # low-mantissa bits carrying the column index
                         # (covers w = MAX_CLASS·MC = 4096; ≤ 2⁻¹¹ relative
                         # value perturbation — inside the bf16 contract)
_PACK_MASK = (1 << _PACK_BITS) - 1


def _pack_scores(s, w: int):
    """Pack column ids into the low mantissa bits of fp32 scores
    (ops/select_k.pack_values — shared so the clamp/NaN/±inf invariants
    live in one place).

    A min pass over the packed values yields the winning VALUE and its
    COLUMN in one reduction — the per-pass argmin reconstruction
    (compare-to-min + one-hot sum) that dominated the round-3 kernel cost
    drops out entirely. The ≤ 2⁻¹¹ relative perturbation (12 index bits)
    sits inside this path's documented bf16 (~3 significant digits)
    ranking contract.
    """
    assert w <= (1 << _PACK_BITS), w
    from raft_tpu.ops.select_k import pack_values

    return pack_values(s, _PACK_BITS)


def _extract_topk_packed(pv, kf: int):
    """kf min passes over packed scores (C, n) → ((C, kf) values, (C, kf)
    columns). Two full-width VPU ops per pass (min + mask) vs the generic
    _extract_topk's five — the packed trick halves-to-thirds the kernel's
    dominant cost. Values at the packing clamp are restored to +inf: a
    clamped +inf sentinel (filtered/padding entry) must come back as inf,
    not as a finite ~3.4e38 hit (code-review r4)."""
    c, n = pv.shape
    kcols = lax.broadcasted_iota(jnp.int32, (c, kf), 1)

    def body(i, carry):
        pv, vals, es = carry
        mn = jnp.min(pv, axis=1)                      # packed winner
        mb = lax.bitcast_convert_type(mn, jnp.int32)
        e = mb & jnp.int32(_PACK_MASK)
        v = lax.bitcast_convert_type(mb & jnp.int32(~_PACK_MASK), jnp.float32)
        sel = kcols == i
        vals = jnp.where(sel, v[:, None], vals)
        es = jnp.where(sel, e[:, None], es)
        return jnp.where(pv == mn[:, None], jnp.inf, pv), vals, es

    _, vals, es = lax.fori_loop(
        0, kf, body,
        (pv, jnp.full((c, kf), jnp.inf, jnp.float32),
         jnp.zeros((c, kf), jnp.int32)),
    )
    from raft_tpu.ops.select_k import pack_clamp_for

    # pack_clamp_for's value already has zero low mantissa bits, so the
    # unpacked winner of a clamped entry equals it exactly (static python
    # float: Mosaic rejects scalar bitcast ops in-kernel)
    return jnp.where(vals >= pack_clamp_for(_PACK_BITS), jnp.inf, vals), es


def _extract_topk(v, offs, kf: int):
    """kf masked-min passes over (C, n): (vals (C, kf), offsets (C, kf)).
    Offset picks use a one-hot sum — no gathers in-kernel. A fori_loop (not
    a Python unroll) keeps one live copy of the working block: the unrolled
    form held ~kf copies and blew Mosaic's 16 MB scoped-vmem stack at
    kf=40."""
    c, n = v.shape
    cols = lax.broadcasted_iota(jnp.int32, v.shape, 1)
    kcols = lax.broadcasted_iota(jnp.int32, (c, kf), 1)

    def body(i, carry):
        v, vals, es = carry
        mn = jnp.min(v, axis=1)
        am = jnp.min(jnp.where(v <= mn[:, None], cols, n), axis=1)
        hit = cols == am[:, None]
        e = jnp.sum(jnp.where(hit, offs, 0), axis=1)
        sel = kcols == i
        vals = jnp.where(sel, mn[:, None], vals)
        es = jnp.where(sel, e[:, None], es)
        return jnp.where(hit, jnp.inf, v), vals, es

    _, vals, es = lax.fori_loop(
        0, kf, body,
        (v, jnp.full((c, kf), jnp.inf, jnp.float32),
         jnp.zeros((c, kf), jnp.int32)),
    )
    return vals, es


_NB = 128   # tournament bin count (strided: bin j = cols ≡ j mod _NB —
            # a full VPU lane row, so the per-bin reductions stay wide)
_KEEP = 4   # per-bin survivors in the tournament pool


def _topk_block(s, kf: int, w: int, approx_ok: bool):
    """Top-kf of a (C, w) score block.

    Direct kf masked-min passes cost kf·C·w VPU work — the kernel's
    dominant cost at round-3 profiling. For kf ≥ 16 the block first plays a
    tournament: keep the _KEEP smallest of each of _NB strided bins (built
    with _KEEP passes reduced along the small axis of a (C, w/_NB, _NB)
    view — the minor dim stays a full 128 lanes), then extract kf from the
    _KEEP·_NB pool: (_KEEP·w + kf·_KEEP·_NB) vs kf·w work, ~1.7× at kf=40,
    w=1024. Exact unless > _KEEP of a row's true top-kf collide in one bin
    (entries land in bins by storage position, arbitrary w.r.t. distance —
    a small tail event). The tournament only engages when the caller
    declares the loss acceptable via ``approx_ok`` (ADVICE r3: IVF-PQ
    over-fetches + exact-re-ranks, so it opts in; IVF-Flat's contract is
    exact-within-probes, so it never takes the lossy route at any k).
    """
    c = s.shape[0]
    bs = w // _NB
    # engage when the tournament's total work (build + pool extraction)
    # beats direct extraction (kf·w > _KEEP·w + kf·_KEEP·_NB) AND the
    # collision loss stays a tail event. The loss is governed by the
    # expected per-bin top-kf mass kf/_NB (width-independent!), so cap at
    # kf ≤ _NB/4 = 32 (mass ≤ 0.25 of the _KEEP survivors, P(loss) ~1e-4
    # per strip row); kf ≤ bs·_KEEP additionally guarantees the pool can
    # hold kf at small widths.
    wins = kf * w > _KEEP * w + kf * _KEEP * _NB
    pv = _pack_scores(s, w)
    if (not approx_ok or kf < 16 or kf > min(bs * _KEEP, _NB // 4)
            or bs < 2 or not wins):
        return _extract_topk_packed(pv, kf)
    # tournament on packed values: the bin survivors carry their own column
    # ids in the mantissa, so the pool extraction needs no offset tables
    sv = pv.reshape(c, bs, _NB)
    pool = []
    for _ in range(_KEEP):
        mn = jnp.min(sv, axis=1)                       # (C, _NB) packed
        pool.append(mn)
        sv = jnp.where(sv == mn[:, None, :], jnp.inf, sv)
    return _extract_topk_packed(jnp.concatenate(pool, axis=1), kf)


def _strip_kernel(sl_ref, lv_ref, a_ref, b_ref, bias_ref, outv_ref,
                  oute_ref, *, alpha, kf, w, n_sub, approx_ok):
    """One strip (× one sub-block when n_sub > 1): matmul + fused top-kf.

    Scores = alpha·(A @ Bᵀ) + bias, smaller is better; the (packed)
    tournament top-k (_topk_block) extracts per-row top-kf values and
    within-list entry offsets. Sub-block revisits merge the running top-kf
    via a concat + kf passes over the 2·kf-wide block.

    Strips with ``strip_list == -1`` are static-layout padding (round-4
    sync-free planning, static_layout): the whole body is skipped via
    ``pl.when``, so worst-case grid padding costs only the block DMA —
    their outputs stay unwritten garbage and the merge never reads them.
    (program_id/sl_ref reads happen at kernel top level — the CPU interpret
    path rejects primitive calls inside a ``pl.when`` region.)

    ``lv_ref`` (round 19, predicate push-down) is the per-(list,
    sub-block) liveness word: 0 when the sub-block's bias lanes are ALL
    ``+inf`` (every row filtered out / tombstoned / padding). A dead
    sub-block's B/bias block maps are collapsed to block 0 (DMA skipped
    after the first fetch) and the matmul+top-k is skipped: the first
    visit writes the all-dead extraction result directly — value ``+inf``
    at offsets ``0..kf-1`` in column order, exactly what
    ``_topk_block``/``_extract_topk`` produce for an all-inf score block —
    and revisits leave the carry untouched, which is bitwise what merging
    with an all-inf block returns (ascending carry + earliest-column inf
    ties). Filtered scans therefore stay bit-identical to the
    compute-everything path while skipping dead work entirely."""
    slv = sl_ref[pl.program_id(0)]
    j = pl.program_id(1) if n_sub > 1 else 0
    lvv = lv_ref[jnp.maximum(slv, 0) * n_sub + (j if n_sub > 1 else 0)]

    @pl.when((slv >= 0) & (lvv > 0))
    def _compute():
        a = a_ref[0]                                   # (C, dim) bf16
        b = b_ref[0].astype(jnp.bfloat16)              # (w, dim)
        s = lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        s = alpha * s + bias_ref[0]                    # (C, w)
        nv, ne = _topk_block(s, kf, w, approx_ok)      # (C, kf) each

        if n_sub == 1:
            outv_ref[0] = nv
            oute_ref[0] = ne
            return

        ne = ne + j * w

        @pl.when(j == 0)
        def _():
            outv_ref[0] = nv
            oute_ref[0] = ne

        @pl.when(j > 0)
        def _():
            cv = jnp.concatenate([outv_ref[0], nv], axis=1)    # (C, 2kf)
            ce = jnp.concatenate([oute_ref[0], ne], axis=1)
            mv, me = _extract_topk(cv, ce, kf)
            outv_ref[0] = mv
            oute_ref[0] = me

    # dead sub-block, first visit: write the all-inf extraction constant
    # (revisits skip — the carry IS the merge result, see docstring)
    c = outv_ref.shape[1]
    first = (j == 0) if n_sub > 1 else True

    @pl.when((slv >= 0) & (lvv == 0) & first)
    def _dead_first():
        outv_ref[0] = jnp.full((c, kf), jnp.inf, jnp.float32)
        oute_ref[0] = lax.broadcasted_iota(jnp.int32, (c, kf), 1)


@functools.partial(
    jax.jit,
    static_argnames=("w_blocks", "n_sub", "alpha", "kf", "interpret",
                     "approx_ok"),
)
def _strip_class_call(strip_list, a_grouped, list_data, bias3,
                      w_blocks: int, n_sub: int, alpha: float, kf: int,
                      interpret: bool, approx_ok: bool = False):
    """Run one length-class: grid (S,) or (S, n_sub) over (C, W) strips."""
    s_pad, c, dim = a_grouped.shape
    w = w_blocks * MC
    n_lists = bias3.shape[0]

    # Per-(list, sub-block) liveness words (round 19, predicate push-down):
    # a sub-block whose bias lanes are ALL +inf (filtered out, tombstoned,
    # or padding) contributes nothing to any top-k, so its DMAs and compute
    # are skipped. One cheap VPU pass over the bias operand — rides the
    # same jit as the scan, so mask changes re-dispatch, never recompile.
    fin = jnp.isfinite(bias3[:, 0, : n_sub * w]).reshape(n_lists, n_sub, w)
    sub_live = jnp.any(fin, axis=2).astype(jnp.int32).reshape(-1)

    # Padding strips (sl = -1, kernel-skipped) get ALL their block maps
    # collapsed to constants — consecutive identical block indices make
    # Pallas skip the refetch, so a padding step costs only grid
    # bookkeeping (~1-2 µs), not the 512 KB list DMA + output writeback.
    # Outputs for padding route to a dedicated trash row (s_pad) so real
    # rows are never clobbered by stale-buffer writebacks. Dead sub-blocks
    # (sub_live == 0) collapse their B/bias maps the same way — a fully
    # filtered-out list costs grid bookkeeping, not its list DMA — but
    # keep their output row: the kernel writes the all-dead extraction
    # constant on first visit (bit-parity with computing, see
    # _strip_kernel).
    if n_sub > 1:
        grid = (s_pad, n_sub)
        pad_ = lambda i, sl: sl[i] < 0
        dead_ = lambda i, j, sl, lv: pad_(i, sl) | (
            lv[jnp.maximum(sl[i], 0) * n_sub + j] == 0)
        a_map = lambda i, j, sl, lv: (jnp.where(pad_(i, sl), 0, i), 0, 0)
        b_map = lambda i, j, sl, lv: (
            jnp.where(dead_(i, j, sl, lv), 0, jnp.maximum(sl[i], 0)),
            jnp.where(dead_(i, j, sl, lv), 0, j), 0)
        bias_map = lambda i, j, sl, lv: (
            jnp.where(dead_(i, j, sl, lv), 0, jnp.maximum(sl[i], 0)), 0,
            jnp.where(dead_(i, j, sl, lv), 0, j))
        o_map = lambda i, j, sl, lv: (jnp.where(pad_(i, sl), s_pad, i), 0, 0)
    else:
        grid = (s_pad,)
        pad_ = lambda i, sl: sl[i] < 0
        dead_ = lambda i, sl, lv: pad_(i, sl) | (
            lv[jnp.maximum(sl[i], 0)] == 0)
        a_map = lambda i, sl, lv: (jnp.where(pad_(i, sl), 0, i), 0, 0)
        b_map = lambda i, sl, lv: (
            jnp.where(dead_(i, sl, lv), 0, jnp.maximum(sl[i], 0)), 0, 0)
        bias_map = lambda i, sl, lv: (
            jnp.where(dead_(i, sl, lv), 0, jnp.maximum(sl[i], 0)), 0, 0)
        o_map = lambda i, sl, lv: (jnp.where(pad_(i, sl), s_pad, i), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, c, dim), a_map),
            pl.BlockSpec((1, w, dim), b_map),
            pl.BlockSpec((1, 1, w), bias_map),
        ],
        out_specs=[pl.BlockSpec((1, c, kf), o_map)] * 2,
    )
    ov, oe = pl.pallas_call(
        functools.partial(_strip_kernel, alpha=alpha, kf=kf, w=w, n_sub=n_sub,
                          approx_ok=approx_ok),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.float32),
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.int32),
        ),
        interpret=interpret,
    )(strip_list, sub_live, a_grouped, list_data, bias3)
    return (lax.slice_in_dim(ov, 0, s_pad, axis=0),
            lax.slice_in_dim(oe, 0, s_pad, axis=0))


def _strip_tile_body(queries_mat, qids, strip_list, pair_strip, pair_slot,
                     list_data, bias, list_ids,
                     class_layout, k: int, kf: int, alpha: float,
                     interpret: bool, pair_const=None,
                     approx_ok: bool = False):
    """One query tile: group the query side per strip, run every length
    class, then the two-gather merge. Plain traceable function so SPMD
    callers can run it inside shard_map (distributed/ivf_*).

    ``pair_const`` (q, p): optional per-(query, probe) additive constant,
    applied AFTER the in-kernel extraction — it cannot change within-pair
    ranking, so this is exact. IVF-PQ uses it for the −2⟨q, R·c_l⟩ term so
    the int8 cache only has to carry the (much smaller) residuals."""
    n_lists, m = list_data.shape[0], list_data.shape[1]
    a_grouped = jnp.where(
        (qids >= 0)[:, :, None],
        queries_mat[jnp.clip(qids, 0), :],
        0,
    ).astype(jnp.bfloat16)                           # (S_pad, C, dim)
    bias3 = bias.reshape(n_lists, 1, m)

    outs_v, outs_e = [], []
    for (w_blocks, n_sub, start, count) in class_layout:
        ov, oe = _strip_class_call(
            lax.slice_in_dim(strip_list, start, start + count, axis=0),
            lax.slice_in_dim(a_grouped, start, start + count, axis=0),
            list_data, bias3, w_blocks, n_sub, alpha, kf, interpret,
            approx_ok,
        )
        outs_v.append(ov)
        outs_e.append(oe)
    out_v = jnp.concatenate(outs_v, axis=0) if len(outs_v) > 1 else outs_v[0]
    out_e = jnp.concatenate(outs_e, axis=0) if len(outs_e) > 1 else outs_e[0]

    return merge_strip_candidates(out_v, out_e, strip_list, pair_strip,
                                  pair_slot, list_ids, class_layout, k, kf,
                                  interpret, pair_const)


def merge_strip_candidates(out_v, out_e, strip_list, pair_strip, pair_slot,
                           list_ids, class_layout, k: int, kf: int,
                           interpret: bool, pair_const=None):
    """The two-gather candidate merge shared by every strip-shaped engine
    (the fp B-operand kernel here and the packed 1-bit kernel in
    ops/bq_scan.py — one copy, so the remap/select/translate protocol
    cannot drift between them).

    pair_strip uses the PLAN's strip numbering (device plans leave gaps
    between class regions); the class outputs are concatenated densely —
    remap by the static per-class delta (identity for gap-free host
    plans). Without this the merge reads the wrong rows whenever a
    class's padded count is below its region size (round-3 on-chip bug:
    recall collapsed to 0.16 while every small CPU test's buckets happened
    to equal the region size)."""
    q, p = pair_strip.shape
    if len(class_layout) > 1:
        concat_starts = np.cumsum([0] + [cnt for (_, _, _, cnt)
                                         in class_layout[:-1]])
        deltas = np.asarray(
            [int(cs - start) for cs, (_, _, start, _)
             in zip(concat_starts, class_layout)], np.int32)
        cls_idx = sum((pair_strip >= start).astype(jnp.int32)
                      for (_, _, start, _) in class_layout[1:])
        pair_strip_c = pair_strip + jnp.asarray(deltas)[cls_idx]
    else:
        pair_strip_c = pair_strip - class_layout[0][2]
    cand_v = out_v[pair_strip_c, pair_slot]
    if pair_const is not None:
        cand_v = cand_v + pair_const[:, :, None]
    cand_v = cand_v.reshape(q, p * kf)
    cand_e = out_e[pair_strip_c, pair_slot].reshape(q, p * kf)
    from raft_tpu.ops.select_k import iter_topk_min, iter_topk_min_packed

    kk = min(k, p * kf)
    if kk <= 64 and not interpret and p * kf <= 2048:
        # packed passes: half the VPU cost of iter_topk_min; ≤ 11 index
        # bits keeps the perturbation ≤ 2^-12 ≈ 2.4e-4 — inside this
        # path's bf16 score contract. Wider merges (big n_probes · kf)
        # would dilute the value mantissa (code-review r4), so they take
        # the exact iter passes instead.
        vals, sel = iter_topk_min_packed(cand_v, kk)
    elif kk <= 64 and not interpret:
        vals, sel = iter_topk_min(cand_v, kk)
    else:
        nv, sel = lax.top_k(-cand_v, kk)
        vals = -nv
    win_list = jnp.take_along_axis(strip_list[pair_strip], sel // kf, axis=1)
    win_off = jnp.take_along_axis(cand_e, sel, axis=1)
    out_ids = list_ids[win_list, win_off]            # (q, kk)
    if kk < k:
        vals = jnp.pad(vals, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
        out_ids = jnp.pad(out_ids, ((0, 0), (0, k - kk)), constant_values=-1)
    out_ids = jnp.where(jnp.isfinite(vals), out_ids, -1)
    return vals, out_ids


_strip_tile = jax.jit(
    _strip_tile_body,
    static_argnames=("class_layout", "k", "kf", "alpha", "interpret",
                     "approx_ok"),
)


def max_class_for(dim: int) -> int:
    """Largest fetch class whose (1, w, dim) fp32 B-block stays inside a
    ~6 MB double-buffered VMEM budget (review r4: MAX_CLASS=8 was only
    validated at dim=128 — a dim-768 index would request 12.6 MB blocks).
    dim=128 → 8; dim≈256 → 4; dim≈512 → 2; dim ≥ ~1024 → 1."""
    if dim <= 0:
        return MAX_CLASS
    w_max = max(MC, (6 << 20) // (dim * 4 * 2))
    cls = 1
    while cls * 2 <= MAX_CLASS and cls * 2 * MC <= w_max:
        cls *= 2
    return cls


def class_info(lens_np: np.ndarray, dim: int = 0):
    """Static per-index class table from per-list lengths: ordered distinct
    (w_blocks, n_sub) classes and each list's class ordinal. ``dim`` caps
    the fetch class so wide-row indexes keep their blocks inside VMEM."""
    max_class = min(MAX_CLASS, max_class_for(dim)) if dim else MAX_CLASS
    n_mc = np.maximum(-(-np.maximum(lens_np, 0) // MC), 1)
    cls_full = (1 << np.ceil(np.log2(n_mc)).astype(np.int64))
    w = np.minimum(cls_full, max_class)
    sub = np.maximum(cls_full // max_class, 1)
    keys = w * (1 << 20) + sub
    uniq = np.unique(keys)
    ordinal = np.searchsorted(uniq, keys).astype(np.int32)
    classes = [(int(k_ >> 20), int(k_ & ((1 << 20) - 1))) for k_ in uniq]
    return classes, ordinal


@functools.partial(
    jax.jit,
    static_argnames=("n_lists", "region_starts", "s_tot"),
)
def _plan_device(probes, cls_ord, n_lists: int,
                 region_starts: Tuple[int, ...], s_tot: int):
    """Device-side strip planning (round-3 v3): the host↔device link on the
    tunneled TPU measured ~25 MB/s, so host-built plan tables (a few MB per
    tile) dominated search latency. This builds the same tables with jnp
    sorts/scatters ON DEVICE; the host only fetches the per-class strip
    counts (a few ints) to fix the static grid sizes — or nothing at all on
    the static-layout path.

    Strips live in per-class regions starting at ``region_starts[c]``
    (round-4: per-class sizes — a uniform n_lists-wide stride made the
    query-side tables scale as n_classes · n_lists, which OOM'd many-list /
    few-query shapes); unused slots carry qids=-1 / strip_list=-1 and are
    never read by the merge. Returns (qids, strip_list, pair_strip,
    pair_slot, counts_per_class)."""
    q, p = probes.shape
    qp = q * p
    n_classes = len(region_starts)
    flat = probes.reshape(-1)
    order = jnp.argsort(flat, stable=True)
    sorted_lists = flat[order]
    # per-list pair counts from the sorted array (binary search): bincount's
    # scatter-add measured 8 ms at 320K pairs on TPU, searchsorted ~none
    bounds = jnp.searchsorted(sorted_lists,
                              jnp.arange(n_lists + 1, dtype=jnp.int32))
    r = (bounds[1:] - bounds[:-1]).astype(jnp.int32)
    n_qc = -(-r // C)                                  # strips per list

    # class-major list layout: lists sorted by (class, id); each list's
    # strip base = its class region start + strips of earlier lists in class
    list_order = jnp.argsort(cls_ord * n_lists
                             + jnp.arange(n_lists, dtype=jnp.int32))
    n_qc_sorted = n_qc[list_order]
    csum = jnp.cumsum(n_qc_sorted) - n_qc_sorted       # exclusive, global
    cls_sorted = cls_ord[list_order]
    counts = jax.ops.segment_sum(n_qc_sorted, cls_sorted,
                                 num_segments=n_classes)
    class_first = jnp.cumsum(counts) - counts          # exclusive
    starts = jnp.asarray(region_starts, jnp.int32)
    base_sorted = starts[cls_sorted] + (csum - class_first[cls_sorted])
    strip_base = jnp.zeros(n_lists, jnp.int32).at[list_order].set(
        base_sorted.astype(jnp.int32))

    pair_off = jnp.cumsum(r) - r
    rank = (jnp.arange(qp, dtype=jnp.int32)
            - pair_off[sorted_lists].astype(jnp.int32))
    ps_sorted = strip_base[sorted_lists] + rank // C
    slot_sorted = rank % C
    pair_strip = jnp.zeros(qp, jnp.int32).at[order].set(ps_sorted)
    pair_slot = jnp.zeros(qp, jnp.int32).at[order].set(slot_sorted)

    # padding slots = -1: the kernel skips them entirely (round-4; with the
    # static worst-case layout the padded grid would otherwise do real work)
    strip_list = jnp.full(s_tot, -1, jnp.int32).at[ps_sorted].set(
        sorted_lists.astype(jnp.int32))
    qids = jnp.full((s_tot, C), -1, jnp.int32).at[ps_sorted, slot_sorted].set(
        (order // p).astype(jnp.int32))
    return (qids, strip_list, pair_strip.reshape(q, p),
            pair_slot.reshape(q, p), counts)


def fit_q_tile(q: int, p: int, n_lists: int, n_classes: int, kf: int,
               workspace_bytes: int, dim: int = 0,
               class_counts: Optional[Tuple[int, ...]] = None) -> int:
    """Largest query tile whose per-class region tables + kernel outputs
    stay inside the workspace budget. Per strip slot: kf fp32+int32 output
    pairs (kf·8), the qids int32 entry (4), and — the round-3 undercount
    (ADVICE) — the (S_pad, C, dim) bf16 ``a_grouped`` query-side buffer
    (2·dim bytes) built in _strip_tile_body."""
    q_tile = min(q, 16384)
    per_slot = kf * 8 + 4 + 2 * dim
    if class_counts is None:
        class_counts = tuple([n_lists] * max(n_classes, 1))

    def rows_for(qt):
        return sum(static_caps(class_counts, qt, p))

    while (rows_for(q_tile) * C * per_slot > workspace_bytes
           and q_tile > 512):
        q_tile //= 2
    return q_tile


def plan_tile(probes_dev, start: int, qt: int, cls_ord, classes, n_lists: int):
    """Device-plan one query tile and fix its static class layout (the ONE
    host fetch is the per-class strip counts). Shared by strip_search and
    the distributed tiled_search so the planning protocol cannot drift."""
    p = probes_dev.shape[1]
    n_classes = len(classes)
    s_region = _bucket(min(qt * p, _ceil_div(qt * p, C) + n_lists))
    region_starts = tuple(c * s_region for c in range(n_classes))
    qids, strip_list, pair_strip, pair_slot, counts = _plan_device(
        lax.slice_in_dim(probes_dev, start, start + qt, axis=0),
        cls_ord, n_lists, region_starts, n_classes * s_region,
    )
    counts_np = np.asarray(counts)  # ~n_classes ints — the only fetch
    layout = tuple(
        (classes[c][0], classes[c][1], c * s_region,
         min(_bucket(int(counts_np[c])), s_region))
        for c in range(n_classes) if counts_np[c] > 0
    ) or ((1, 1, 0, 1),)
    return qids, strip_list, pair_strip, pair_slot, layout


def class_counts_of(cls_ord_np: np.ndarray, n_classes: int) -> Tuple[int, ...]:
    """Static per-class list counts (hashable, for jit static args)."""
    return tuple(int(x) for x in np.bincount(cls_ord_np, minlength=n_classes))


def static_caps(class_counts: Tuple[int, ...], qt: int, p: int):
    """Per-class worst-case strip counts for a qt-query tile: a class holds
    at most ceil(qt·p/C) full strips + one partial per list IN THAT CLASS,
    and never more strips than pairs (the qt·p bound bites at small tiles).
    """
    full = _ceil_div(qt * p, C)
    return tuple(_bucket(min(qt * p, full + int(nc)))
                 for nc in class_counts)


def static_layout(classes, class_counts: Tuple[int, ...], qt: int, p: int):
    """Host-static worst-case layout for a qt-query tile — no device fetch.

    Regions are sized PER CLASS (round-4: a uniform n_lists-wide stride
    made the query-side tables scale as n_classes · n_lists and OOM'd
    many-list shapes). With one length class (the common large-index case)
    this equals the bucketed dynamic plan's size, so the static grid costs
    nothing extra. Returns (region_starts, s_tot, layout)."""
    caps = static_caps(class_counts, qt, p)
    starts = []
    acc = 0
    for cap in caps:
        starts.append(acc)
        acc += cap
    layout = tuple(
        (classes[c][0], classes[c][1], starts[c], caps[c])
        for c in range(len(classes))
    )
    return tuple(starts), acc, layout


def strip_search_traced(queries_mat, probes, list_data, bias, list_ids,
                        cls_ord, classes, class_counts, k: int, kf: int,
                        alpha: float, q_tile: int, interpret: bool,
                        pair_const=None, approx_ok: bool = False):
    """Sync-free strip search: fully traceable, so callers can fuse coarse
    quantizer + device planning + strip kernel + finalization into ONE
    dispatch with zero host round-trips.

    Round-4 rationale: the dynamic plan (plan_tile) fetches per-class strip
    counts to size the kernel grid — a blocking device→host sync in the
    middle of every search that (a) costs an RTT on the tunneled runtime and
    (b) prevents back-to-back searches from pipelining. Here the grid is
    fixed at the static worst case (static_layout); padding strips carry
    strip_list = -1 and are skipped entirely in-kernel.
    """
    q, p = probes.shape
    n_lists = list_data.shape[0]
    out_v, out_i = [], []
    for start in range(0, q, q_tile):
        qt = min(q_tile, q - start)
        region_starts, s_tot, layout = static_layout(
            classes, class_counts, qt, p)
        qids, strip_list, pair_strip, pair_slot, _ = _plan_device(
            lax.slice_in_dim(probes, start, start + qt, axis=0),
            cls_ord, n_lists, region_starts, s_tot,
        )
        v, i = _strip_tile_body(
            lax.slice_in_dim(queries_mat, start, start + qt, axis=0),
            qids, strip_list, pair_strip, pair_slot, list_data, bias,
            list_ids, layout, int(k), kf, float(alpha), bool(interpret),
            None if pair_const is None
            else lax.slice_in_dim(pair_const, start, start + qt, axis=0),
            approx_ok,
        )
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, 0), jnp.concatenate(out_i, 0)


def occupancy_stats(lens, m: int, q: int, p: int, dim: int = 0,
                    workspace_bytes: int = 1 << 30, kf: int = 10) -> dict:
    """Static occupancy diagnostics of one strip-scan dispatch, from the
    SAME planning code the dispatch uses (class_info / fit_q_tile /
    static_layout) — "the kernel underfills the MXU" as numbers, not a
    hunch (obs/roofline, round 15):

    * ``grid`` — per length-class ``[padded_strips, n_sub, w_blocks]``
      (the compiled kernel grids);
    * ``padded_strip_fraction`` — static-layout padding strips over the
      padded total, with the REAL strip count taken at the planner's
      best case (full ``C``-slot packing, ``ceil(q·p / C)`` — the bench
      regime; skewed probe distributions only add real strips, so this
      is the floor of the padding, not an estimate of it);
    * ``tile_fill`` — real (query, probe) pairs over the slots those
      best-case strips provide (how full the MXU M-dimension runs);
    * ``padded_row_fraction`` — scan-relative row padding: real entries
      over the pow2-block-padded widths the kernel actually fetches per
      list (every probed pair pays its list's padded width);
    * ``storage_padded_fraction`` — index-relative padding against the
      global ``m``-wide list storage (what residency pays).

    ``lens`` are per-list REAL entry counts, ``m`` the padded list width,
    ``(q, p)`` the dispatch's query/probe shape. Pure numpy."""
    lens_np = np.maximum(np.asarray(lens, np.int64), 0)
    n_lists = int(lens_np.shape[0])
    classes, cls_ord = class_info(lens_np, dim=dim)
    class_counts = class_counts_of(cls_ord, len(classes))
    q_tile = fit_q_tile(q, p, n_lists, len(classes), kf, workspace_bytes,
                        dim=dim, class_counts=class_counts)
    qt = min(q_tile, q)
    tiles = _ceil_div(q, qt) if qt else 0
    _, s_tot, layout = static_layout(classes, class_counts, qt, p)
    strips_best = _ceil_div(qt * p, C)
    n_mc = np.maximum(_ceil_div(lens_np, MC), 1)
    scanned = (1 << np.ceil(np.log2(n_mc)).astype(np.int64)) * MC
    real_rows = int(lens_np.sum())
    scanned_sum = int(scanned.sum())
    return {
        "grid": [[int(cnt), int(n_sub), int(w_blocks)]
                 for (w_blocks, n_sub, _start, cnt) in layout],
        "strips_padded": int(s_tot),
        "strips_real_bestcase": int(strips_best),
        "padded_strip_fraction": round(
            max(0.0, 1.0 - strips_best / s_tot), 4) if s_tot else 0.0,
        "tile_fill": round(min(1.0, qt * p / (strips_best * C)), 4)
        if strips_best else 0.0,
        "padded_row_fraction": round(
            max(0.0, 1.0 - real_rows / scanned_sum), 4)
        if scanned_sum else 0.0,
        "storage_padded_fraction": round(
            max(0.0, 1.0 - real_rows / (n_lists * m)), 4)
        if n_lists * m else 0.0,
        "q_tile": int(qt),
        "tiles": int(tiles),
        "c": C,
        "mc": MC,
    }


# ---------------------------------------------------------------------------
# Paged strip scan (serving): the SAME strip engine over a PagedListStore's
# page chains — HBM→VMEM page DMAs instead of contiguous list blocks
# ---------------------------------------------------------------------------
#
# The Ragged Paged Attention pattern (PAPERS.md): the kernel takes the
# store's page table + chain lengths as scalar-prefetch operands and
# issues one ``make_async_copy`` per live page (the ops/cagra_hop.py
# double-semaphore machinery), so mutable paged storage is scanned IN
# PLACE at strip-kernel throughput — no gather materialization, no
# repack. Every list is planned at its CAPACITY length (table_width ×
# page_rows rows — one length class, so the compiled layout depends only
# on capacity and the zero-recompile serving contract holds), but the
# kernel only moves a chain's LIVE pages: dead sub-blocks skip both the
# DMAs and the compute, costing grid bookkeeping like padding strips.
# Tombstoned rows and tail fills self-mask through the store-maintained
# ``page_bias`` pool (+inf at dead slots — the packed kernels' trash-row
# convention); rows past the live page count are masked in-kernel by a
# lane iota against the chain length, so stale VMEM scratch never scores.
#
# Two implementations, bit-identical by construction (the ops/bq_scan.py
# precedent): ``impl="pallas"`` (the kernel; interpret-mode on CPU) and
# ``impl="jnp"`` (a lax.map reference driving the SAME per-block compute,
# :func:`_paged_score_topk`) — the parity oracle tier-1 pins.


def paged_plan(table_width: int, page_rows: int, row_bytes: int,
               kf: int) -> Tuple[int, int, int]:
    """Static fetch plan for one paged scan: ``(pages_per_fetch, n_sub,
    w)`` with ``w = pages_per_fetch · page_rows`` rows per grid step.

    The block must cover ``kf`` rows (the running per-pair top-kf can
    never recover candidates a narrower block dropped), aims for the
    packed kernel's ``MC`` granule, and stays inside the mantissa-packing
    bound (w ≤ 4096, ops/strip_scan._PACK_BITS) and a ~4 MB VMEM payload
    budget. ``table_width`` is a power of two (the store grows it
    geometrically), so ``pages_per_fetch`` always divides it."""
    W, R = int(table_width), int(page_rows)

    def _ok(p_):
        w_ = p_ * R
        return w_ <= (1 << _PACK_BITS) and w_ * max(1, row_bytes) <= (4 << 20)

    ppf = 1
    while ppf < W and ppf * R < min(max(kf, MC), 1 << _PACK_BITS):
        ppf *= 2
    while ppf < W and _ok(ppf * 2):
        ppf *= 2
    while ppf > 1 and not _ok(ppf):
        ppf //= 2
    return ppf, max(1, W // ppf), ppf * R


def paged_eligible(table_width: int, page_rows: int, row_bytes: int,
                   k: int) -> bool:
    """True when the paged Pallas engine can serve this store/k: the plan's
    block covers k (pack-bits + VMEM budget permitting) and the page
    height is sane. Callers fall back to the gather scan otherwise."""
    if page_rows < 8 or k > 512:
        return False
    _, _, w = paged_plan(table_width, page_rows, row_bytes, int(k))
    return int(k) <= min(w, table_width * page_rows, 1 << _PACK_BITS)


def _paged_score_topk(a, block, bias_row, live_rows, alpha: float, kf: int,
                      w: int, approx_ok: bool):
    """One paged block's scores + fused top-kf — THE shared compute of the
    kernel and the jnp reference (both feed it the same operands, which is
    what makes the two paths bit-identical).

    a: (C, dim) query block; block: (w, dim) payload rows (any fetch
    order-stable dtype — fp32/bf16/int8 upcast like the packed kernel);
    bias_row: (1, w) per-row additive term; live_rows: scalar — rows at
    lane >= live_rows are DEAD (absent pages / stale scratch) and masked
    to +inf AFTER the add, so garbage payload (even NaN) never ranks."""
    b = block.astype(jnp.bfloat16)
    s = lax.dot_general(a.astype(jnp.bfloat16), b, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = alpha * s + bias_row
    lanes = lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(lanes < live_rows, s, jnp.inf)
    return _topk_block(s, kf, w, approx_ok)


def _paged_strip_kernel(sl_ref, tbl_ref, chain_ref, lv_ref, a_ref, pages_hbm,
                        bias_hbm, outv_ref, oute_ref, pay_s, bias_s,
                        psem, bsem, *, alpha, kf, w, n_sub, ppf,
                        page_rows, table_width, approx_ok):
    """One (strip × page sub-block): DMA the live pages HBM→VMEM, then the
    shared matmul + fused top-kf. Scalar prefetch carries the strip table
    (``sl``), the flattened page table, the per-list chain lengths and the
    per-(list, sub-block) filter-liveness words (``lv_ref``: 0 when every
    row the sub-block's pages hold is +inf-biased — filtered out or
    tombstoned); only live pages of live sub-blocks are copied (a
    dynamic-trip fori_loop — the Ragged Paged Attention fetch shape), dead
    sub-blocks and padding strips skip the body entirely. A filter-dead
    first sub-block still writes: ``live_rows = 0`` masks every lane, so
    the write is the all-inf extraction — bitwise what the jnp reference
    computes from the all-+inf bias lanes."""
    i = pl.program_id(0)
    slv = sl_ref[i]
    j = pl.program_id(1) if n_sub > 1 else 0
    l = jnp.maximum(slv, 0)
    chain = jnp.where(slv >= 0, chain_ref[l], 0)   # live pages in the list
    lvv = lv_ref[l * n_sub + (j if n_sub > 1 else 0)]
    base = j * ppf
    # live pages this block; a filter-dead block fetches and ranks nothing
    nv = jnp.clip(chain - base, 0, ppf) * lvv
    R = page_rows

    # issue every copy before draining any: latencies overlap; the two
    # semaphores drain exactly the issued bytes (ops/cagra_hop pattern)
    def issue(t, _):
        pid = tbl_ref[l * table_width + base + t]
        pltpu.make_async_copy(pages_hbm.at[pid],
                              pay_s.at[pl.ds(t * R, R)], psem).start()
        pltpu.make_async_copy(bias_hbm.at[pid],
                              bias_s.at[0, pl.ds(t * R, R)], bsem).start()
        return 0

    def drain(t, _):
        pid = tbl_ref[l * table_width + base + t]
        pltpu.make_async_copy(pages_hbm.at[pid],
                              pay_s.at[pl.ds(t * R, R)], psem).wait()
        pltpu.make_async_copy(bias_hbm.at[pid],
                              bias_s.at[0, pl.ds(t * R, R)], bsem).wait()
        return 0

    lax.fori_loop(0, nv, issue, 0)
    lax.fori_loop(0, nv, drain, 0)

    # j == 0 always writes (a strip's outputs must be defined even for an
    # empty list — all-+inf, which the merge translates to id -1); later
    # sub-blocks past the chain end — or filter-dead (lvv == 0) — keep the
    # running top-kf untouched
    @pl.when((slv >= 0) & ((j == 0) | ((base < chain) & (lvv > 0))))
    def _compute():
        bv, be = _paged_score_topk(a_ref[0], pay_s[...], bias_s[...],
                                   nv * R, alpha, kf, w, approx_ok)
        be = be + j * w

        if n_sub == 1:
            outv_ref[0] = bv
            oute_ref[0] = be
            return

        @pl.when(j == 0)
        def _():
            outv_ref[0] = bv
            oute_ref[0] = be

        @pl.when(j > 0)
        def _():
            cv = jnp.concatenate([outv_ref[0], bv], axis=1)   # (C, 2kf)
            ce = jnp.concatenate([oute_ref[0], be], axis=1)
            mv, me = _extract_topk(cv, ce, kf)
            outv_ref[0] = mv
            oute_ref[0] = me


@functools.partial(
    jax.jit,
    static_argnames=("ppf", "n_sub", "page_rows", "table_width", "alpha",
                     "kf", "interpret", "approx_ok"),
)
def _paged_class_call(strip_list, table_flat, chain_pages, sub_live,
                      a_grouped, pages, bias_pool, ppf: int, n_sub: int,
                      page_rows: int, table_width: int, alpha: float,
                      kf: int, interpret: bool, approx_ok: bool = False):
    """Run the (single) paged length class through the Pallas kernel:
    grid (S,) or (S, n_sub); pages/bias stay HBM-resident (memory_space
    ANY) and are fetched per grid step by the kernel's own DMAs.
    ``sub_live`` (n_lists·n_sub,) int32 carries the per-sub-block
    filter-liveness words (0 ⇒ the kernel issues no page DMAs and skips
    ranking for that block)."""
    s_pad, c, dim = a_grouped.shape
    w = ppf * page_rows

    if n_sub > 1:
        grid = (s_pad, n_sub)
        a_map = lambda i, j, sl, tb, ch, lv: (jnp.where(sl[i] < 0, 0, i),
                                              0, 0)
        o_map = lambda i, j, sl, tb, ch, lv: (jnp.where(sl[i] < 0, s_pad, i),
                                              0, 0)
    else:
        grid = (s_pad,)
        a_map = lambda i, sl, tb, ch, lv: (jnp.where(sl[i] < 0, 0, i), 0, 0)
        o_map = lambda i, sl, tb, ch, lv: (jnp.where(sl[i] < 0, s_pad, i),
                                           0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, c, dim), a_map),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[pl.BlockSpec((1, c, kf), o_map)] * 2,
        scratch_shapes=[
            pltpu.VMEM((w, pages.shape[-1]), pages.dtype),
            pltpu.VMEM((1, w), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    ov, oe = pl.pallas_call(
        functools.partial(_paged_strip_kernel, alpha=alpha, kf=kf, w=w,
                          n_sub=n_sub, ppf=ppf, page_rows=page_rows,
                          table_width=table_width, approx_ok=approx_ok),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.float32),
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.int32),
        ),
        interpret=interpret,
    )(strip_list, table_flat, chain_pages, sub_live, a_grouped, pages,
      bias_pool)
    return (lax.slice_in_dim(ov, 0, s_pad, axis=0),
            lax.slice_in_dim(oe, 0, s_pad, axis=0))


@functools.partial(
    jax.jit,
    static_argnames=("ppf", "n_sub", "page_rows", "table_width", "alpha",
                     "kf", "approx_ok"),
)
def _paged_class_jnp(strip_list, table_flat, chain_pages, sub_live,
                     a_grouped, pages, bias_pool, ppf: int, n_sub: int,
                     page_rows: int, table_width: int, alpha: float,
                     kf: int, approx_ok: bool = False):
    """Pure-jnp reference for the paged class: the SAME per-(strip,
    sub-block) op sequence as the kernel — shared :func:`_paged_score_topk`,
    same ``_extract_topk`` sub-block merge, same skip predicate for dead
    sub-blocks (chain-exhausted OR filter-dead ``sub_live`` word) — driven
    by a sequential ``lax.map`` over strips. This IS the jnp gather path
    of the paged engine: pages are fetched with jnp advanced indexing and
    scored identically, so tier-1 pins bitwise (ids + values) parity
    against the kernel."""
    w = ppf * page_rows
    table2 = table_flat.reshape(-1, table_width)
    live2 = sub_live.reshape(-1, n_sub)

    def one_strip(args):
        sl, a = args
        l = jnp.maximum(sl, 0)
        chain = jnp.where(sl >= 0, chain_pages[l], 0)
        trow = table2[l]
        lrow = live2[l]

        def sub(j, carry):
            ov, oe = carry
            lw = lax.dynamic_index_in_dim(lrow, j, keepdims=False)
            pidx = jnp.maximum(
                lax.dynamic_slice_in_dim(trow, j * ppf, ppf), 0)
            blk = pages[pidx].reshape(w, pages.shape[-1])
            brow = bias_pool[pidx].reshape(1, w)
            live = jnp.clip(chain - j * ppf, 0, ppf) * lw * page_rows
            bv, be = _paged_score_topk(a, blk, brow, live, alpha, kf, w,
                                       approx_ok)
            be = be + j * w
            if n_sub == 1:
                return bv, be
            cv = jnp.concatenate([ov, bv], axis=1)
            ce = jnp.concatenate([oe, be], axis=1)
            mv, me = _extract_topk(cv, ce, kf)
            # j == 0 initializes exactly like the kernel's first write;
            # dead sub-blocks keep the running top-kf (kernel skip path)
            first = j == 0
            dead = jnp.logical_and(jnp.logical_not(first),
                                   jnp.logical_or(j * ppf >= chain, lw == 0))
            out_v = jnp.where(first, bv, jnp.where(dead, ov, mv))
            out_e = jnp.where(first, be, jnp.where(dead, oe, me))
            return out_v, out_e

        init = (jnp.full((C, kf), jnp.inf, jnp.float32),
                jnp.zeros((C, kf), jnp.int32))
        return lax.fori_loop(0, n_sub, sub, init)

    return lax.map(one_strip, (strip_list, a_grouped))


class PagedIds:
    """Lazy (list, in-list offset) → source-id translator with the 2-D
    advanced-indexing surface :func:`merge_strip_candidates` expects, so
    the merge is reused UNCHANGED: offset ``o`` of list ``l`` dereferences
    through the page table to ``page_ids[table[l, o // R], o % R]``; absent
    pages answer -1 (their candidates are +inf and already masked)."""

    __slots__ = ("page_ids", "table", "page_rows")

    def __init__(self, page_ids, table, page_rows: int):
        self.page_ids = page_ids
        self.table = table
        self.page_rows = int(page_rows)

    def __getitem__(self, idx):
        win_list, win_off = idx
        pg = self.table[win_list, win_off // self.page_rows]
        ids = self.page_ids[jnp.maximum(pg, 0), win_off % self.page_rows]
        return jnp.where(pg >= 0, ids, -1)


def paged_strip_search_traced(queries_mat, probes, pages, bias_pool,
                              page_ids, table, chain_pages, k: int, kf: int,
                              alpha: float, q_tile: int, interpret: bool,
                              pair_const=None, approx_ok: bool = False,
                              impl: str = "pallas"):
    """Sync-free paged strip search — fully traceable, so family callers
    fuse coarse quantizer + device planning + paged kernel + merge +
    finalize into ONE dispatch (the ``strip_search_traced`` protocol over
    page chains).

    pages: (capacity_pages, page_rows, row_width) payload pool.
    bias_pool: (capacity_pages, page_rows) fp32 — the store-maintained
    per-row additive term, +inf at tombstones/tail fills. table:
    (n_lists, table_width) int32 page table, -1 at absent slots.
    chain_pages: (n_lists,) int32 live pages per list. Every operand is
    CAPACITY-shaped: steady-state upserts/deletes re-dispatch this same
    compiled program (the zero-recompile serving contract)."""
    q, p = probes.shape
    n_lists, table_width = table.shape
    page_rows = pages.shape[1]
    ppf, n_sub, w = paged_plan(
        table_width, page_rows,
        int(pages.shape[-1]) * pages.dtype.itemsize, kf)
    if kf > w:
        # the running per-pair top-kf can never recover candidates a
        # narrower fetch block dropped — refuse instead of silently
        # truncating (callers route ineligible stores to the gather path)
        raise ValueError(
            f"paged strip scan needs kf <= fetch block ({w} rows), got "
            f"{kf}; use the gather backend")
    # one capacity length class: the layout depends only on capacity
    classes = ((ppf, n_sub),)
    class_counts = (n_lists,)
    cls_ord = jnp.zeros((n_lists,), jnp.int32)
    table_flat = table.reshape(-1)
    translator = PagedIds(page_ids, table, page_rows)

    # Per-(list, sub-block) filter-liveness words (round 19, predicate
    # push-down): a page whose bias rows are ALL +inf (every row filtered
    # out or tombstoned) holds nothing rankable; a sub-block whose live
    # chain slots all point at such pages skips its page DMAs and compute
    # in the kernel. Derived from the SAME capacity-shaped operands as the
    # scan (one cheap VPU pass), so it rides the fused jit: mask changes
    # re-dispatch, never recompile.
    span = n_sub * ppf
    page_live = jnp.any(jnp.isfinite(bias_pool), axis=1)   # (cap_pages,)
    slot_live = page_live[jnp.maximum(table, 0)] & (table >= 0)
    if span > table_width:
        slot_live = jnp.pad(slot_live, ((0, 0), (0, span - table_width)))
    elif span < table_width:
        slot_live = slot_live[:, :span]
    pos = jnp.arange(span, dtype=jnp.int32)[None, :]
    slot_live = slot_live & (pos < chain_pages[:, None])
    sub_live = jnp.any(slot_live.reshape(n_lists, n_sub, ppf),
                       axis=2).astype(jnp.int32).reshape(-1)

    out_v, out_i = [], []
    for start in range(0, q, q_tile):
        qt = min(q_tile, q - start)
        region_starts, s_tot, layout = static_layout(
            classes, class_counts, qt, p)
        qids, strip_list, pair_strip, pair_slot, _ = _plan_device(
            lax.slice_in_dim(probes, start, start + qt, axis=0),
            cls_ord, n_lists, region_starts, s_tot,
        )
        a_grouped = jnp.where(
            (qids >= 0)[:, :, None],
            lax.slice_in_dim(queries_mat, start, start + qt,
                             axis=0)[jnp.clip(qids, 0), :],
            0,
        ).astype(jnp.bfloat16)
        fn = _paged_class_call if impl == "pallas" else _paged_class_jnp
        kwargs = {"interpret": interpret} if impl == "pallas" else {}
        ov, oe = fn(strip_list, table_flat, chain_pages, sub_live,
                    a_grouped, pages, bias_pool, ppf, n_sub, page_rows,
                    table_width, alpha, kf, approx_ok=approx_ok, **kwargs)
        v, i = merge_strip_candidates(
            ov, oe, strip_list, pair_strip, pair_slot, translator, layout,
            k, kf, interpret,
            None if pair_const is None
            else lax.slice_in_dim(pair_const, start, start + qt, axis=0))
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, 0), jnp.concatenate(out_i, 0)


def paged_occupancy_stats(table_width: int, page_rows: int, chain_pages,
                          live_rows: int, tombstones: int, q: int, p: int,
                          k: int, row_bytes: int,
                          workspace_bytes: int = 1 << 30,
                          dim: int = 0) -> dict:
    """Static occupancy diagnostics of one paged-Pallas dispatch, from the
    SAME planning code the dispatch uses (:func:`paged_plan` +
    ``static_layout``) — the round-15 standing gate for new hot-path
    kernels. Beyond the strip numbers, the paged plane's own wastes:

    * ``page_fill`` — live rows over the slots of the pages actually
      chained (tail-fill waste the DMA still moves);
    * ``tombstone_fraction`` — tombstoned slots over chained-page slots
      (the waste background compaction reclaims);
    * ``chain_fill`` — chained pages over table capacity (how much of the
      capacity-planned grid the skip path prunes).

    ``chain_pages`` is the per-list live page count (numpy)."""
    chain_np = np.maximum(np.asarray(chain_pages, np.int64), 0)
    n_lists = int(chain_np.shape[0])
    kf = min(int(k), 512)
    ppf, n_sub, w = paged_plan(table_width, page_rows, row_bytes, kf)
    classes = ((ppf, n_sub),)
    class_counts = (n_lists,)
    q_tile = fit_q_tile(q, p, n_lists, 1, kf, workspace_bytes, dim=dim,
                        class_counts=class_counts)
    qt = min(q_tile, q) or 1
    _, s_tot, layout = static_layout(classes, class_counts, qt, p)
    strips_best = _ceil_div(qt * p, C)
    chained = int(chain_np.sum())
    chained_slots = chained * int(page_rows)
    cap_slots = n_lists * int(table_width) * int(page_rows)
    live = max(0, int(live_rows))
    dead = max(0, int(tombstones))
    return {
        "grid": [[int(cnt), int(ns), int(wb)]
                 for (wb, ns, _s, cnt) in layout],
        "pages_per_fetch": int(ppf),
        "n_sub": int(n_sub),
        "w": int(w),
        "strips_padded": int(s_tot),
        "strips_real_bestcase": int(strips_best),
        "padded_strip_fraction": round(
            max(0.0, 1.0 - strips_best / s_tot), 4) if s_tot else 0.0,
        "tile_fill": round(min(1.0, qt * p / (strips_best * C)), 4)
        if strips_best else 0.0,
        "page_fill": round(live / chained_slots, 4) if chained_slots
        else 0.0,
        "tombstone_fraction": round(dead / chained_slots, 4)
        if chained_slots else 0.0,
        "chain_fill": round(chained / (n_lists * table_width), 4)
        if n_lists * table_width else 0.0,
        "padded_row_fraction": round(
            max(0.0, 1.0 - live / chained_slots), 4) if chained_slots
        else 0.0,
        "capacity_slots": cap_slots,
        "q_tile": int(qt),
        "c": C,
    }


def strip_search(
    queries_mat,
    probes,
    list_data,
    list_bias,
    list_ids,
    lens,
    k: int,
    alpha: float = -2.0,
    workspace_bytes: int = 1 << 30,
    interpret: bool = False,
    pair_const=None,
    approx_ok: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Full strip scan: probes (q, p) int32 → per-query top-k over the
    probed lists' entries. Drop-in contract of round 2's ragged_search:

    queries_mat: (q, dim) query-side matrix (rotated/scaled as the caller
      needs). list_data: (n_lists, m, dim) entry matrix, fp32/bf16/int8,
      with m a power-of-two multiple of MC (512) — see _packing.pack_lists'
      pow2_chunks. list_bias: (n_lists, m) per-entry additive term (+inf at
      padding). list_ids: (n_lists, m) source row ids (-1 padding). lens:
      (n_lists,) real entry counts. Scores are ``alpha·⟨q, x⟩ + bias``,
      smaller is better; the caller adds per-query constants afterwards.

    Distances on this path accumulate the matmul in fp32 from bf16 (or
    int8-dequantized) operands: ~3 significant digits relative to the fp32
    gather oracle. The contract here is candidate RANKING (callers re-rank
    exact via neighbors/refine or consume ids); use backend="gather" where
    fp32 distances themselves are the product.
    """
    q = queries_mat.shape[0]
    lens_np = np.asarray(lens)
    n_lists, m = list_data.shape[0], list_data.shape[1]
    if m % MC or (m // MC) & (m // MC - 1):
        raise ValueError(
            f"list_data dim 1 must be a power-of-two multiple of {MC}, got {m}"
        )
    if k > MC:
        # a pair's candidates are capped at its strip's kf slots; k beyond MC
        # would silently drop in-list ranks > MC (use the gather backend)
        raise ValueError(f"strip_search supports k <= {MC}, got {k}")
    kf = min(int(k), MC)

    from raft_tpu.core.interruptible import check_interrupt

    classes, cls_ord_np = class_info(lens_np, dim=queries_mat.shape[1])
    cls_ord = jnp.asarray(cls_ord_np)  # 4 KB — the only per-search upload
    probes_dev = jnp.asarray(probes)
    q_tile = fit_q_tile(q, probes_dev.shape[1], n_lists, len(classes), kf,
                        workspace_bytes, dim=queries_mat.shape[1])

    out_v, out_i = [], []
    start = 0
    while start < q:
        check_interrupt()
        qt = min(q_tile, q - start)
        qids, strip_list, pair_strip, pair_slot, layout = plan_tile(
            probes_dev, start, qt, cls_ord, classes, n_lists)
        v, i = _strip_tile(
            queries_mat[start:start + qt], qids, strip_list, pair_strip,
            pair_slot, list_data, list_bias, list_ids,
            layout, int(k), kf, float(alpha), bool(interpret),
            None if pair_const is None else pair_const[start:start + qt],
            approx_ok,
        )
        out_v.append(v)
        out_i.append(i)
        start += qt
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, 0), jnp.concatenate(out_i, 0)
