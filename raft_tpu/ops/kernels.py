"""Kernel-method Gram matrices (reference
distance/detail/kernels/kernel_matrices.cuh:153,329,497 — Polynomial, Tanh,
RBF over GramMatrixBase distance/detail/kernels/gram_matrix.cuh:53).

Each kernel is one pairwise op + elementwise transform — XLA fuses the
transform into the gemm epilogue, so there is nothing to hand-write here;
the reference's custom kernels exist because cuBLAS can't fuse epilogues.
Dense operands only (the reference's CSR paths map to sparse/distance.py's
densify-by-tiles design the same way).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.ops import distance as dist_mod


def linear_kernel(x, y, res: Optional[Resources] = None) -> jax.Array:
    """K = X·Yᵀ (gram_matrix.cuh evaluate base case)."""
    res = res or current_resources()
    return dist_mod.matmul_t(jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
                             res.compute_dtype, "highest")


def polynomial_kernel(x, y, degree: int = 3, gain: float = 1.0,
                      offset: float = 0.0, res: Optional[Resources] = None) -> jax.Array:
    """K = (gain·X·Yᵀ + offset)^degree (kernel_matrices.cuh:153)."""
    return (gain * linear_kernel(x, y, res) + offset) ** degree


def tanh_kernel(x, y, gain: float = 1.0, offset: float = 0.0,
                res: Optional[Resources] = None) -> jax.Array:
    """K = tanh(gain·X·Yᵀ + offset) (kernel_matrices.cuh:329)."""
    return jnp.tanh(gain * linear_kernel(x, y, res) + offset)


def rbf_kernel(x, y, gain: float = 1.0, res: Optional[Resources] = None) -> jax.Array:
    """K = exp(-gain·‖x-y‖²) (kernel_matrices.cuh:497)."""
    res = res or current_resources()
    d2 = dist_mod.pairwise_distance(x, y, "sqeuclidean", res=res)
    return jnp.exp(-gain * jnp.maximum(d2, 0.0))


def masked_l2_nn(
    x,
    y,
    adj,
    group_idx,
    sqrt: bool = False,
    res: Optional[Resources] = None,
):
    """Masked fused-L2 nearest neighbor (distance/masked_nn.cuh analog):
    for each row i of x, the argmin over columns j of y with
    ``adj[i, group_idx[j]]`` true. Returns ``(min_dists (m,), argmins (m,))``
    with inf/-1 where a row's mask admits nothing.
    """
    res = res or current_resources()
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    adj = jnp.asarray(adj, bool)
    group_idx = jnp.asarray(group_idx, jnp.int32)
    if group_idx.shape[0] != y.shape[0]:
        raise ValueError("group_idx must have one entry per y row")
    if adj.ndim != 2 or adj.shape[0] != x.shape[0]:
        raise ValueError("adj must be (x_rows, n_groups)")
    d = dist_mod.pairwise_distance(x, y, "sqeuclidean", res=res)
    mask = adj[:, jnp.clip(group_idx, 0, adj.shape[1] - 1)]  # (m, n)
    d = jnp.where(mask, d, jnp.inf)
    mins = jnp.min(d, axis=1)
    args = jnp.where(jnp.isfinite(mins), jnp.argmin(d, axis=1), -1).astype(jnp.int32)
    if sqrt:
        mins = jnp.sqrt(jnp.maximum(mins, 0.0))
    return mins, args
