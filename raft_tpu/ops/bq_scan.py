"""Packed 1-bit (RaBitQ-style) list scan — the IVF-BQ search engine.

Reference analog: the RaBitQ scan (IVF-RaBitQ, PAPERS.md) evaluates
binary-code distance estimates per probed list entry; on GPU that is an
XOR/popcount loop over packed sign words. On TPU there is no popcount
datapath worth feeding — but the identity

    popcount-form  ⟨q, b⟩  ≡  matmul-form  q · (2·bits − 1)ᵀ

turns the binarized scan into a dense ±1 contraction, which is exactly the
TPU-KNN peak-FLOP/s formulation (PAPERS.md): saturate the MXU with a
(queries × codes) matmul instead of emulating bit tricks on the VPU.

Two implementations of the same scan, bit-identical by construction:

  * ``impl="pallas"`` — a strip kernel riding ops/strip_scan's ragged-strip
    planning (work ∝ probed entries, per-pair top-kf fused in-kernel): the
    stored codes stay 1 bit/dim in HBM, each grid step DMAs one packed
    (w, rot_dim/8) uint8 block into VMEM, unpacks it to ±1 int8 there
    (8 shift-and-mask VPU ops), upcasts to bf16 and runs ONE MXU matmul
    against the (C, rot_dim) query block. HBM traffic per probed entry is
    rot_dim/8 bytes — 32× under fp32, 8× under the IVF-PQ int8 cache.
  * ``impl="jnp"`` — the pure-jnp reference path: the SAME per-strip
    compute (:func:`_score_topk`, shared code) driven by ``lax.map``
    instead of ``pl.pallas_call``. CPU default, and the bit-parity oracle
    the interpret-mode kernel is tested against.

Scores are ``alpha · ⟨q_rot, ±1⟩ · scale + bias``: the per-entry ``scale``
operand carries the RaBitQ correction scalar (‖u‖²/‖u‖₁ — what makes the
1-bit estimator unbiased, see neighbors/ivf_bq.py) and is the one structural
addition over the fp strip kernel; everything downstream (tournament top-kf,
sub-block revisits, the two-gather merge) is shared with ops/strip_scan.

Bit layout: rotated dimension ``d`` lives at bit ``d // nb`` of byte
``d % nb`` (``nb = rot_dim // 8``) — bit-PLANE-major, so the in-kernel
unpack is eight full-width 2-D shift-and-mask ops plus one lane-axis
concatenate, never a (w, nb, 8) relayout. :func:`pack_sign_bits` /
:func:`unpack_sign_bits` are the only functions that know this layout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops import strip_scan as ss
from raft_tpu.ops.strip_scan import C, MC


def packed_width(rot_dim: int) -> int:
    """Bytes per 1-bit-encoded row (rot_dim must be a multiple of 8)."""
    if rot_dim % 8:
        raise ValueError(f"rot_dim must be a multiple of 8, got {rot_dim}")
    return rot_dim // 8


def pack_sign_bits(signs) -> jax.Array:
    """(…, rot_dim) sign vectors (> 0 ⇒ bit 1) → (…, rot_dim/8) uint8 in
    the bit-plane-major layout (module docstring)."""
    rot_dim = signs.shape[-1]
    nb = packed_width(rot_dim)
    bits = (signs > 0).astype(jnp.uint32)
    planes = bits.reshape(signs.shape[:-1] + (8, nb))
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[:, None]
    return jnp.sum(planes * weights, axis=-2).astype(jnp.uint8)


def unpack_sign_bits(packed, rot_dim: int) -> jax.Array:
    """Inverse of :func:`pack_sign_bits` → (…, rot_dim) int8 in {-1, +1}."""
    nb = packed_width(rot_dim)
    if packed.shape[-1] != nb:
        raise ValueError(f"expected {nb} packed bytes, got {packed.shape[-1]}")
    return _unpack_pm1(packed)


def _unpack_pm1(packed):
    """(…, nb) packed bytes → (…, 8·nb) ±1 int8. 2-D-friendly: eight
    shift-and-masks + one minor-axis concat (each a full-width vector op in
    Mosaic — no (…, nb, 8) relayout, see module docstring)."""
    w = packed.astype(jnp.int32)
    planes = [((w >> j) & 1) for j in range(8)]
    bits = jnp.concatenate(planes, axis=-1)
    return (2 * bits - 1).astype(jnp.int8)


# ---------------------------------------------------------------------------
# Multi-bit (2–4 bit) extended codes — extra bit-planes, same kernels
# ---------------------------------------------------------------------------
#
# A B-bit code c ∈ {0 … 2^B−1} per rotated dimension dequantizes to the odd
# integer LEVEL  L = 2c − (2^B−1) = Σ_p 2^p · (2·bit_p(c) − 1):  the level-
# weighted contraction ⟨q, L⟩ decomposes EXACTLY into B ±1 contractions, one
# per bit-plane, weighted 2^p. Storage stacks each plane as its own packed
# group of rot_dim/8 bytes (pack_sign_bits layout per plane), so the kernels'
# existing byte-block DMA + `_unpack_pm1` + one MXU matmul work UNCHANGED at
# the wider byte width bits·rot_dim/8; the level weights ride the QUERY
# operand (:func:`extend_query_planes` — built once per dispatch, outside the
# kernel), which is why the high-recall multi-bit scan is "still just a
# wider MXU contraction" (TPU-KNN's peak-FLOP/s framing). For B = 1 the
# level set is {−1, +1} and everything degenerates to the original layout.


def multibit_width(rot_dim: int, bits: int) -> int:
    """Bytes per B-bit-encoded row: ``bits`` stacked sign planes."""
    if not 1 <= int(bits) <= 4:
        raise ValueError(f"bits must be in [1, 4], got {bits}")
    return int(bits) * packed_width(rot_dim)


def pack_code_planes(codes, bits: int) -> jax.Array:
    """(…, rot_dim) uint8 codes in [0, 2^bits) → (…, bits·rot_dim/8) uint8:
    plane p (bit p of every code) packed via :func:`pack_sign_bits` into its
    own contiguous nb-byte group. bits=1 gives exactly the 1-bit layout."""
    if not 1 <= int(bits) <= 4:
        raise ValueError(f"bits must be in [1, 4], got {bits}")
    codes = codes.astype(jnp.uint8)
    planes = [pack_sign_bits((((codes >> p) & 1).astype(jnp.int8) * 2 - 1))
              for p in range(int(bits))]
    return planes[0] if bits == 1 else jnp.concatenate(planes, axis=-1)


def unpack_code_levels(packed, rot_dim: int, bits: int) -> jax.Array:
    """Inverse view of :func:`pack_code_planes` → (…, rot_dim) int32 LEVELS
    (odd integers in [−(2^bits−1), 2^bits−1]); bits=1 gives ±1."""
    nb = packed_width(rot_dim)
    if packed.shape[-1] != int(bits) * nb:
        raise ValueError(
            f"expected {int(bits) * nb} packed bytes, got {packed.shape[-1]}")
    lv = None
    for p in range(int(bits)):
        pm1 = _unpack_pm1(packed[..., p * nb:(p + 1) * nb]).astype(jnp.int32)
        lv = pm1 if lv is None else lv + (1 << p) * pm1
    return lv


def extend_query_planes(queries_rot, bits: int) -> jax.Array:
    """(q, rot_dim) rotated queries → (q, bits·rot_dim) plane-weighted query
    operand, ordered to match ``_unpack_pm1`` over a (w, bits·nb) packed
    block: unpacked position ``j·bits·nb + p·nb + r`` is bit j of plane p's
    byte r = plane p of dimension ``j·nb + r``, so the slot carries
    ``2^p · q[j·nb + r]``. Then ⟨ext(q), ±1-planes⟩ == ⟨q, levels⟩ exactly.
    bits=1 is the identity."""
    bits = int(bits)
    if bits == 1:
        return queries_rot
    q, rot_dim = queries_rot.shape
    nb = packed_width(rot_dim)
    w = (2.0 ** jnp.arange(bits)).astype(queries_rot.dtype)
    a = queries_rot.reshape(q, 8, 1, nb) * w[None, None, :, None]
    return a.reshape(q, 8 * bits * nb)


def _score_topk(a, b_packed, scale_row, bias_row, alpha: float, kf: int,
                w: int, approx_ok: bool):
    """One strip's scores + fused top-kf — THE shared compute of both
    implementations (kernel refs and jnp gathers feed the same ops, which
    is what makes the two paths bit-identical).

    a: (C, rot_dim) bf16 query block; b_packed: (w, nb) uint8 codes;
    scale_row / bias_row: (1, w) fp32. Scores = alpha·(A@(±1)ᵀ)·scale +
    bias, smaller is better; bias carries +inf at padding."""
    b = _unpack_pm1(b_packed).astype(jnp.bfloat16)
    s = lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = alpha * s * scale_row + bias_row
    return ss._topk_block(s, kf, w, approx_ok)


def _bq_strip_kernel(sl_ref, lv_ref, a_ref, b_ref, scale_ref, bias_ref,
                     outv_ref, oute_ref, *, alpha, kf, w, n_sub, approx_ok):
    """One strip (× one sub-block when n_sub > 1): in-VMEM unpack + MXU
    matmul + fused top-kf. Mirrors strip_scan._strip_kernel with the packed
    B operand and the per-entry scale; padding strips (strip_list == -1)
    skip the body via ``pl.when`` exactly like the fp kernel, and dead
    sub-blocks (``lv_ref`` word 0: every bias lane +inf — filtered out /
    tombstoned / padding) skip the unpack+matmul and write the all-dead
    extraction constant on first visit (bit parity argument in
    strip_scan._strip_kernel)."""
    slv = sl_ref[pl.program_id(0)]
    j = pl.program_id(1) if n_sub > 1 else 0
    lvv = lv_ref[jnp.maximum(slv, 0) * n_sub + (j if n_sub > 1 else 0)]

    @pl.when((slv >= 0) & (lvv > 0))
    def _compute():
        nv, ne = _score_topk(a_ref[0], b_ref[0], scale_ref[0], bias_ref[0],
                             alpha, kf, w, approx_ok)

        if n_sub == 1:
            outv_ref[0] = nv
            oute_ref[0] = ne
            return

        ne = ne + j * w

        @pl.when(j == 0)
        def _():
            outv_ref[0] = nv
            oute_ref[0] = ne

        @pl.when(j > 0)
        def _():
            cv = jnp.concatenate([outv_ref[0], nv], axis=1)   # (C, 2kf)
            ce = jnp.concatenate([oute_ref[0], ne], axis=1)
            mv, me = ss._extract_topk(cv, ce, kf)
            outv_ref[0] = mv
            oute_ref[0] = me

    c = outv_ref.shape[1]
    first = (j == 0) if n_sub > 1 else True

    @pl.when((slv >= 0) & (lvv == 0) & first)
    def _dead_first():
        outv_ref[0] = jnp.full((c, kf), jnp.inf, jnp.float32)
        oute_ref[0] = lax.broadcasted_iota(jnp.int32, (c, kf), 1)


@functools.partial(
    jax.jit,
    static_argnames=("w_blocks", "n_sub", "alpha", "kf", "interpret",
                     "approx_ok"),
)
def _bq_class_call(strip_list, a_grouped, list_codes, scale3, bias3,
                   w_blocks: int, n_sub: int, alpha: float, kf: int,
                   interpret: bool, approx_ok: bool = False):
    """Run one length-class through the Pallas kernel: grid (S,) or
    (S, n_sub) over (C, W) strips (strip_scan._strip_class_call shape, with
    the packed B block and the scale operand)."""
    s_pad, c, rot_dim = a_grouped.shape
    w = w_blocks * MC
    nb = list_codes.shape[-1]
    n_lists = bias3.shape[0]

    # per-(list, sub-block) liveness words: all-+inf-bias sub-blocks skip
    # their DMAs and compute (strip_scan._strip_class_call convention)
    fin = jnp.isfinite(bias3[:, 0, : n_sub * w]).reshape(n_lists, n_sub, w)
    sub_live = jnp.any(fin, axis=2).astype(jnp.int32).reshape(-1)

    # padding strips: block maps collapse to constants (no refetch), outputs
    # route to the trash row — the fp kernel's exact convention; dead
    # sub-blocks collapse their code/scale/bias maps the same way but keep
    # their output row (the kernel writes the all-dead constant)
    if n_sub > 1:
        grid = (s_pad, n_sub)
        pad_ = lambda i, sl: sl[i] < 0
        dead_ = lambda i, j, sl, lv: pad_(i, sl) | (
            lv[jnp.maximum(sl[i], 0) * n_sub + j] == 0)
        a_map = lambda i, j, sl, lv: (jnp.where(pad_(i, sl), 0, i), 0, 0)
        b_map = lambda i, j, sl, lv: (
            jnp.where(dead_(i, j, sl, lv), 0, jnp.maximum(sl[i], 0)),
            jnp.where(dead_(i, j, sl, lv), 0, j), 0)
        sb_map = lambda i, j, sl, lv: (
            jnp.where(dead_(i, j, sl, lv), 0, jnp.maximum(sl[i], 0)), 0,
            jnp.where(dead_(i, j, sl, lv), 0, j))
        o_map = lambda i, j, sl, lv: (jnp.where(pad_(i, sl), s_pad, i), 0, 0)
    else:
        grid = (s_pad,)
        pad_ = lambda i, sl: sl[i] < 0
        dead_ = lambda i, sl, lv: pad_(i, sl) | (
            lv[jnp.maximum(sl[i], 0)] == 0)
        a_map = lambda i, sl, lv: (jnp.where(pad_(i, sl), 0, i), 0, 0)
        b_map = lambda i, sl, lv: (
            jnp.where(dead_(i, sl, lv), 0, jnp.maximum(sl[i], 0)), 0, 0)
        sb_map = lambda i, sl, lv: (
            jnp.where(dead_(i, sl, lv), 0, jnp.maximum(sl[i], 0)), 0, 0)
        o_map = lambda i, sl, lv: (jnp.where(pad_(i, sl), s_pad, i), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, c, rot_dim), a_map),
            pl.BlockSpec((1, w, nb), b_map),
            pl.BlockSpec((1, 1, w), sb_map),
            pl.BlockSpec((1, 1, w), sb_map),
        ],
        out_specs=[pl.BlockSpec((1, c, kf), o_map)] * 2,
    )
    ov, oe = pl.pallas_call(
        functools.partial(_bq_strip_kernel, alpha=alpha, kf=kf, w=w,
                          n_sub=n_sub, approx_ok=approx_ok),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.float32),
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.int32),
        ),
        interpret=interpret,
    )(strip_list, sub_live, a_grouped, list_codes, scale3, bias3)
    return (lax.slice_in_dim(ov, 0, s_pad, axis=0),
            lax.slice_in_dim(oe, 0, s_pad, axis=0))


@functools.partial(
    jax.jit,
    static_argnames=("w_blocks", "n_sub", "alpha", "kf", "approx_ok"),
)
def _bq_class_jnp(strip_list, a_grouped, list_codes, scale3, bias3,
                  w_blocks: int, n_sub: int, alpha: float, kf: int,
                  approx_ok: bool = False):
    """Pure-jnp reference for one length-class: the SAME per-(strip,
    sub-block) op sequence as the kernel (shared :func:`_score_topk`, same
    ``_extract_topk`` sub-block merge), driven by a sequential ``lax.map``
    over strips so memory stays bounded. Padding strips (sl < 0) compute
    against list 0 — their outputs, like the kernel's unwritten garbage,
    are never read by the merge."""
    w = w_blocks * MC

    def one_strip(args):
        sl, a = args
        l = jnp.maximum(sl, 0)

        def sub(j, carry):
            ov, oe = carry
            blk = lax.dynamic_slice_in_dim(list_codes[l], j * w, w, axis=0)
            sc = lax.dynamic_slice_in_dim(scale3[l, 0], j * w, w)[None, :]
            bi = lax.dynamic_slice_in_dim(bias3[l, 0], j * w, w)[None, :]
            nv, ne = _score_topk(a, blk, sc, bi, alpha, kf, w, approx_ok)
            ne = ne + j * w
            if n_sub == 1:
                return nv, ne
            cv = jnp.concatenate([ov, nv], axis=1)
            ce = jnp.concatenate([oe, ne], axis=1)
            mv, me = ss._extract_topk(cv, ce, kf)
            # j == 0 initializes exactly like the kernel's first write —
            # never through the merge (bit parity of the merged offsets)
            return (jnp.where(j == 0, nv, mv), jnp.where(j == 0, ne, me))

        init = (jnp.full((C, kf), jnp.inf, jnp.float32),
                jnp.zeros((C, kf), jnp.int32))
        return lax.fori_loop(0, n_sub, sub, init)

    return lax.map(one_strip, (strip_list, a_grouped))


def _bq_tile_body(queries_rot, qids, strip_list, pair_strip, pair_slot,
                  list_codes, scale, bias, list_ids, class_layout,
                  k: int, kf: int, alpha: float, interpret: bool,
                  pair_const=None, approx_ok: bool = False,
                  impl: str = "pallas"):
    """One query tile of the packed scan: group the query side per strip,
    run every length class through the chosen implementation, then the
    shared two-gather merge (strip_scan.merge_strip_candidates). Plain
    traceable function so SPMD callers can run it inside shard_map
    (distributed/ivf_bq)."""
    n_lists, m = list_codes.shape[0], list_codes.shape[1]
    a_grouped = jnp.where(
        (qids >= 0)[:, :, None],
        queries_rot[jnp.clip(qids, 0), :],
        0,
    ).astype(jnp.bfloat16)                           # (S_pad, C, rot_dim)
    bias3 = bias.reshape(n_lists, 1, m)
    scale3 = scale.reshape(n_lists, 1, m)

    outs_v, outs_e = [], []
    for (w_blocks, n_sub, start, count) in class_layout:
        sl = lax.slice_in_dim(strip_list, start, start + count, axis=0)
        ag = lax.slice_in_dim(a_grouped, start, start + count, axis=0)
        if impl == "pallas":
            ov, oe = _bq_class_call(sl, ag, list_codes, scale3, bias3,
                                    w_blocks, n_sub, alpha, kf, interpret,
                                    approx_ok)
        else:
            ov, oe = _bq_class_jnp(sl, ag, list_codes, scale3, bias3,
                                   w_blocks, n_sub, alpha, kf, approx_ok)
        outs_v.append(ov)
        outs_e.append(oe)
    out_v = jnp.concatenate(outs_v, axis=0) if len(outs_v) > 1 else outs_v[0]
    out_e = jnp.concatenate(outs_e, axis=0) if len(outs_e) > 1 else outs_e[0]
    return ss.merge_strip_candidates(out_v, out_e, strip_list, pair_strip,
                                     pair_slot, list_ids, class_layout, k,
                                     kf, interpret, pair_const)


def bq_strip_search_traced(queries_rot, probes, list_codes, scale, bias,
                           list_ids, cls_ord, classes, class_counts,
                           k: int, kf: int, alpha: float, q_tile: int,
                           interpret: bool, pair_const=None,
                           approx_ok: bool = False, impl: str = "pallas"):
    """Sync-free packed strip search — fully traceable so callers fuse
    coarse quantizer + device planning + scan + finalize into ONE dispatch
    (the strip_scan.strip_search_traced protocol, packed-B edition).

    queries_rot: (q, rot_dim) ROTATED queries. list_codes: (n_lists, m,
    rot_dim/8) packed sign codes. scale / bias: (n_lists, m) per-entry
    fp32 correction scalar and additive term (+inf bias at padding).
    ``impl`` picks the Pallas kernel or the pure-jnp reference — identical
    results either way (tests/test_bq_scan.py asserts bit parity)."""
    q, p = probes.shape
    n_lists = list_codes.shape[0]
    out_v, out_i = [], []
    for start in range(0, q, q_tile):
        qt = min(q_tile, q - start)
        region_starts, s_tot, layout = ss.static_layout(
            classes, class_counts, qt, p)
        qids, strip_list, pair_strip, pair_slot, _ = ss._plan_device(
            lax.slice_in_dim(probes, start, start + qt, axis=0),
            cls_ord, n_lists, region_starts, s_tot,
        )
        v, i = _bq_tile_body(
            lax.slice_in_dim(queries_rot, start, start + qt, axis=0),
            qids, strip_list, pair_strip, pair_slot, list_codes, scale,
            bias, list_ids, layout, int(k), kf, float(alpha),
            bool(interpret),
            None if pair_const is None
            else lax.slice_in_dim(pair_const, start, start + qt, axis=0),
            approx_ok, impl,
        )
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, 0), jnp.concatenate(out_i, 0)


# ---------------------------------------------------------------------------
# Paged packed scan (serving): the ±1 engine over PagedListStore page chains
# ---------------------------------------------------------------------------


def _paged_bq_score_topk(a, packed_block, scale_row, bias_row, live_rows,
                         alpha: float, kf: int, w: int, approx_ok: bool):
    """One paged packed block's scores + fused top-kf — shared by the
    kernel and the jnp reference (bit parity by construction, the
    :func:`_score_topk` pattern with the paged live-lane mask)."""
    b = _unpack_pm1(packed_block).astype(jnp.bfloat16)
    s = lax.dot_general(a.astype(jnp.bfloat16), b, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = alpha * s * scale_row + bias_row
    lanes = lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(lanes < live_rows, s, jnp.inf)
    return ss._topk_block(s, kf, w, approx_ok)


def _paged_bq_kernel(sl_ref, tbl_ref, chain_ref, lv_ref, a_ref, codes_hbm,
                     scale_hbm, bias_hbm, outv_ref, oute_ref, code_s,
                     scale_s, bias_s, csem, ssem, bsem, *, alpha, kf, w,
                     n_sub, ppf, page_rows, table_width, approx_ok):
    """One (strip × page sub-block) of the paged ±1 scan: DMA the live
    code/scale/bias pages HBM→VMEM, unpack to ±1 in VMEM, one MXU matmul +
    fused top-kf (strip_scan._paged_strip_kernel with the packed B operand
    and the per-row scale). ``lv_ref`` carries the per-(list, sub-block)
    filter-liveness words — a dead sub-block (every row +inf-biased)
    issues no DMAs and skips ranking, same contract as the fp paged
    kernel."""
    i = pl.program_id(0)
    slv = sl_ref[i]
    j = pl.program_id(1) if n_sub > 1 else 0
    l = jnp.maximum(slv, 0)
    chain = jnp.where(slv >= 0, chain_ref[l], 0)
    lvv = lv_ref[l * n_sub + (j if n_sub > 1 else 0)]
    base = j * ppf
    nv = jnp.clip(chain - base, 0, ppf) * lvv
    R = page_rows

    def issue(t, _):
        pid = tbl_ref[l * table_width + base + t]
        pltpu.make_async_copy(codes_hbm.at[pid],
                              code_s.at[pl.ds(t * R, R)], csem).start()
        pltpu.make_async_copy(scale_hbm.at[pid],
                              scale_s.at[0, pl.ds(t * R, R)], ssem).start()
        pltpu.make_async_copy(bias_hbm.at[pid],
                              bias_s.at[0, pl.ds(t * R, R)], bsem).start()
        return 0

    def drain(t, _):
        pid = tbl_ref[l * table_width + base + t]
        pltpu.make_async_copy(codes_hbm.at[pid],
                              code_s.at[pl.ds(t * R, R)], csem).wait()
        pltpu.make_async_copy(scale_hbm.at[pid],
                              scale_s.at[0, pl.ds(t * R, R)], ssem).wait()
        pltpu.make_async_copy(bias_hbm.at[pid],
                              bias_s.at[0, pl.ds(t * R, R)], bsem).wait()
        return 0

    lax.fori_loop(0, nv, issue, 0)
    lax.fori_loop(0, nv, drain, 0)

    @pl.when((slv >= 0) & ((j == 0) | ((base < chain) & (lvv > 0))))
    def _compute():
        bv, be = _paged_bq_score_topk(a_ref[0], code_s[...], scale_s[...],
                                      bias_s[...], nv * R, alpha, kf, w,
                                      approx_ok)
        be = be + j * w

        if n_sub == 1:
            outv_ref[0] = bv
            oute_ref[0] = be
            return

        @pl.when(j == 0)
        def _():
            outv_ref[0] = bv
            oute_ref[0] = be

        @pl.when(j > 0)
        def _():
            cv = jnp.concatenate([outv_ref[0], bv], axis=1)
            ce = jnp.concatenate([oute_ref[0], be], axis=1)
            mv, me = ss._extract_topk(cv, ce, kf)
            outv_ref[0] = mv
            oute_ref[0] = me


@functools.partial(
    jax.jit,
    static_argnames=("ppf", "n_sub", "page_rows", "table_width", "alpha",
                     "kf", "interpret", "approx_ok"),
)
def _paged_bq_class_call(strip_list, table_flat, chain_pages, sub_live,
                         a_grouped, codes, scale_pool, bias_pool, ppf: int,
                         n_sub: int, page_rows: int, table_width: int,
                         alpha: float, kf: int, interpret: bool,
                         approx_ok: bool = False):
    s_pad, c, rot_dim = a_grouped.shape
    w = ppf * page_rows

    if n_sub > 1:
        grid = (s_pad, n_sub)
        a_map = lambda i, j, sl, tb, ch, lv: (jnp.where(sl[i] < 0, 0, i),
                                              0, 0)
        o_map = lambda i, j, sl, tb, ch, lv: (jnp.where(sl[i] < 0, s_pad, i),
                                              0, 0)
    else:
        grid = (s_pad,)
        a_map = lambda i, sl, tb, ch, lv: (jnp.where(sl[i] < 0, 0, i), 0, 0)
        o_map = lambda i, sl, tb, ch, lv: (jnp.where(sl[i] < 0, s_pad, i),
                                           0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, c, rot_dim), a_map),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[pl.BlockSpec((1, c, kf), o_map)] * 2,
        scratch_shapes=[
            pltpu.VMEM((w, codes.shape[-1]), codes.dtype),
            pltpu.VMEM((1, w), jnp.float32),
            pltpu.VMEM((1, w), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    ov, oe = pl.pallas_call(
        functools.partial(_paged_bq_kernel, alpha=alpha, kf=kf, w=w,
                          n_sub=n_sub, ppf=ppf, page_rows=page_rows,
                          table_width=table_width, approx_ok=approx_ok),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.float32),
            jax.ShapeDtypeStruct((s_pad + 1, c, kf), jnp.int32),
        ),
        interpret=interpret,
    )(strip_list, table_flat, chain_pages, sub_live, a_grouped, codes,
      scale_pool, bias_pool)
    return (lax.slice_in_dim(ov, 0, s_pad, axis=0),
            lax.slice_in_dim(oe, 0, s_pad, axis=0))


@functools.partial(
    jax.jit,
    static_argnames=("ppf", "n_sub", "page_rows", "table_width", "alpha",
                     "kf", "approx_ok"),
)
def _paged_bq_class_jnp(strip_list, table_flat, chain_pages, sub_live,
                        a_grouped, codes, scale_pool, bias_pool, ppf: int,
                        n_sub: int, page_rows: int, table_width: int,
                        alpha: float, kf: int, approx_ok: bool = False):
    """jnp reference of the paged packed scan (shared
    :func:`_paged_bq_score_topk`; the bit-parity oracle — same skip
    predicate as the kernel for chain-exhausted or filter-dead
    sub-blocks)."""
    w = ppf * page_rows
    table2 = table_flat.reshape(-1, table_width)
    live2 = sub_live.reshape(-1, n_sub)

    def one_strip(args):
        sl, a = args
        l = jnp.maximum(sl, 0)
        chain = jnp.where(sl >= 0, chain_pages[l], 0)
        trow = table2[l]
        lrow = live2[l]

        def sub(j, carry):
            ov, oe = carry
            lw = lax.dynamic_index_in_dim(lrow, j, keepdims=False)
            pidx = jnp.maximum(
                lax.dynamic_slice_in_dim(trow, j * ppf, ppf), 0)
            blk = codes[pidx].reshape(w, codes.shape[-1])
            srow = scale_pool[pidx].reshape(1, w)
            brow = bias_pool[pidx].reshape(1, w)
            live = jnp.clip(chain - j * ppf, 0, ppf) * lw * page_rows
            bv, be = _paged_bq_score_topk(a, blk, srow, brow, live, alpha,
                                          kf, w, approx_ok)
            be = be + j * w
            if n_sub == 1:
                return bv, be
            cv = jnp.concatenate([ov, bv], axis=1)
            ce = jnp.concatenate([oe, be], axis=1)
            mv, me = ss._extract_topk(cv, ce, kf)
            first = j == 0
            dead = jnp.logical_and(jnp.logical_not(first),
                                   jnp.logical_or(j * ppf >= chain, lw == 0))
            out_v = jnp.where(first, bv, jnp.where(dead, ov, mv))
            out_e = jnp.where(first, be, jnp.where(dead, oe, me))
            return out_v, out_e

        init = (jnp.full((C, kf), jnp.inf, jnp.float32),
                jnp.zeros((C, kf), jnp.int32))
        return lax.fori_loop(0, n_sub, sub, init)

    return lax.map(one_strip, (strip_list, a_grouped))


def paged_bq_search_traced(queries_rot, probes, codes, scale_pool,
                           bias_pool, page_ids, table, chain_pages, k: int,
                           kf: int, alpha: float, q_tile: int,
                           interpret: bool, pair_const=None,
                           approx_ok: bool = False, impl: str = "pallas"):
    """Sync-free paged packed strip search — the
    :func:`strip_scan.paged_strip_search_traced` protocol with the packed
    B operand and the per-row RaBitQ scale pool. All operands are
    capacity-shaped (zero-recompile serving contract)."""
    from raft_tpu.ops.strip_scan import (PagedIds, _plan_device, paged_plan,
                                         static_layout)

    q, p = probes.shape
    n_lists, table_width = table.shape
    page_rows = codes.shape[1]
    ppf, n_sub, w = paged_plan(table_width, page_rows,
                               int(codes.shape[-1]), kf)
    if kf > w:
        raise ValueError(
            f"paged packed scan needs kf <= fetch block ({w} rows), got "
            f"{kf}")
    classes = ((ppf, n_sub),)
    class_counts = (n_lists,)
    cls_ord = jnp.zeros((n_lists,), jnp.int32)
    table_flat = table.reshape(-1)
    translator = PagedIds(page_ids, table, page_rows)

    # per-(list, sub-block) filter-liveness words — the
    # strip_scan.paged_strip_search_traced convention (all-+inf-bias pages
    # contribute nothing; dead sub-blocks skip their DMAs and compute)
    span = n_sub * ppf
    page_live = jnp.any(jnp.isfinite(bias_pool), axis=1)
    slot_live = page_live[jnp.maximum(table, 0)] & (table >= 0)
    if span > table_width:
        slot_live = jnp.pad(slot_live, ((0, 0), (0, span - table_width)))
    elif span < table_width:
        slot_live = slot_live[:, :span]
    pos = jnp.arange(span, dtype=jnp.int32)[None, :]
    slot_live = slot_live & (pos < chain_pages[:, None])
    sub_live = jnp.any(slot_live.reshape(n_lists, n_sub, ppf),
                       axis=2).astype(jnp.int32).reshape(-1)

    out_v, out_i = [], []
    for start in range(0, q, q_tile):
        qt = min(q_tile, q - start)
        region_starts, s_tot, layout = static_layout(
            classes, class_counts, qt, p)
        qids, strip_list, pair_strip, pair_slot, _ = _plan_device(
            lax.slice_in_dim(probes, start, start + qt, axis=0),
            cls_ord, n_lists, region_starts, s_tot,
        )
        a_grouped = jnp.where(
            (qids >= 0)[:, :, None],
            lax.slice_in_dim(queries_rot, start, start + qt,
                             axis=0)[jnp.clip(qids, 0), :],
            0,
        ).astype(jnp.bfloat16)
        fn = (_paged_bq_class_call if impl == "pallas"
              else _paged_bq_class_jnp)
        kwargs = {"interpret": interpret} if impl == "pallas" else {}
        ov, oe = fn(strip_list, table_flat, chain_pages, sub_live,
                    a_grouped, codes, scale_pool, bias_pool, ppf, n_sub,
                    page_rows, table_width, alpha, kf, approx_ok=approx_ok,
                    **kwargs)
        v, i = ss.merge_strip_candidates(
            ov, oe, strip_list, pair_strip, pair_slot, translator, layout,
            k, kf, interpret,
            None if pair_const is None
            else lax.slice_in_dim(pair_const, start, start + qt, axis=0))
        out_v.append(v)
        out_i.append(i)
    if len(out_v) == 1:
        return out_v[0], out_i[0]
    return jnp.concatenate(out_v, 0), jnp.concatenate(out_i, 0)


def occupancy_stats(lens, m: int, q: int, p: int, rot_dim: int,
                    workspace_bytes: int = 1 << 30, kf: int = 10,
                    bits: int = 1) -> dict:
    """Static occupancy diagnostics of one packed-scan dispatch: the strip
    planner's numbers (:func:`strip_scan.occupancy_stats`) at the scan's
    REAL planning width (the bf16 unpacked block is ``bits·rot_dim`` wide —
    the width ivf_bq's ``_ragged_plan_static`` plans with), plus the
    packed-code byte width the DMAs actually move."""
    out = ss.occupancy_stats(lens, m, q, p, dim=rot_dim * int(bits),
                             workspace_bytes=workspace_bytes, kf=kf)
    out["code_bytes_per_entry"] = multibit_width(rot_dim, bits)
    return out


def bq_dense_scan(queries_rot, probes, list_codes, scale, bias, list_ids,
                  k: int, alpha: float, pair_const=None):
    """Jittable dense packed scan — the distributed layer's off-TPU / small-
    shard path (the bq analog of _sharding.dense_local_scan): probe-tiled
    ``lax.map`` so one probe's (q, mls, rot_dim) unpacked block is the peak
    intermediate, fp32 accumulation."""
    q = queries_rot.shape[0]
    qf = queries_rot.astype(jnp.float32)

    def one_probe(j):
        lids = probes[:, j]                              # (q,)
        cand = _unpack_pm1(list_codes[lids]).astype(jnp.float32)
        ip = jnp.einsum("qd,qmd->qm", qf, cand,
                        preferred_element_type=jnp.float32)
        d = alpha * ip * scale[lids] + bias[lids]
        if pair_const is not None:
            d = d + pair_const[:, j, None]
        return d, list_ids[lids]

    p = probes.shape[1]
    d_all, ids_all = lax.map(one_probe, jnp.arange(p))   # (p, q, mls)
    d = jnp.transpose(d_all, (1, 0, 2)).reshape(q, -1)
    flat_ids = jnp.transpose(ids_all, (1, 0, 2)).reshape(q, -1)
    from raft_tpu.ops.select_k import select_k

    vals, sel = select_k(d, min(k, d.shape[1]), select_min=True)
    ids = jnp.where(jnp.isinf(vals), -1,
                    jnp.take_along_axis(flat_ids, sel, axis=1))
    if ids.shape[1] < k:
        vals = jnp.pad(vals, ((0, 0), (0, k - ids.shape[1])),
                       constant_values=jnp.inf)
        ids = jnp.pad(ids, ((0, 0), (0, k - ids.shape[1])),
                      constant_values=-1)
    return vals, ids
