"""Cluster bootstrap — the raft-dask ``Comms.init()`` analog.

Reference: python/raft-dask/raft_dask/common/comms.py:40-140 generates an NCCL
uniqueId at the root, broadcasts it over Dask, and every worker runs
``ncclCommInitRank`` + installs a ``comms_t`` into its ``device_resources``.

On TPU the runtime owns rendezvous: ``jax.distributed.initialize`` performs
the coordinator handshake (the uid-rendezvous analog), after which
``jax.devices()`` spans every chip in the slice and a global ``Mesh`` is the
installed communicator. Single-process multi-device (the LocalCUDACluster
test analog, SURVEY.md §4.3) needs no bootstrap at all — just a mesh over
``jax.local_devices()``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> bool:
    """Initialize multi-host JAX (ncclCommInitRank rendezvous analog).

    Rendezvous sources, in order: explicit args, the standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``),
    or — when ``auto=True`` — ``jax.distributed.initialize()`` with no args,
    which self-detects cloud-TPU pod metadata. ``auto`` is opt-in because on
    a non-pod machine the no-arg call can block looking for a coordinator.
    Returns False (no-op) when no source is available and ``auto`` is off.
    Idempotent: a second call returns True without re-initializing.
    """
    if getattr(init_distributed, "_done", False):
        return True
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else os.environ.get("JAX_NUM_PROCESSES")
    pid = process_id if process_id is not None else os.environ.get("JAX_PROCESS_ID")
    if addr is None and nproc is None:
        if not auto:
            return False
        jax.distributed.initialize()
        init_distributed._done = True
        return True
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(nproc) if nproc is not None else None,
        process_id=int(pid) if pid is not None else None,
    )
    init_distributed._done = True
    return True


def local_mesh(
    n_devices: Optional[int] = None, axis_names: Tuple[str, ...] = ("data",),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    """A mesh over this process's devices (LocalCUDACluster fixture analog).

    ``shape`` reshapes the device list for multi-axis meshes; defaults to 1-D
    over the first ``n_devices`` local devices.
    """
    devs = jax.local_devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    import numpy as np

    grid = np.array(devs, dtype=object)
    if shape is not None:
        grid = grid.reshape(tuple(shape))
    if grid.ndim != len(axis_names):
        raise ValueError(f"mesh shape {grid.shape} vs axis_names {axis_names}")
    return Mesh(grid, axis_names)
