"""Cluster bootstrap — the raft-dask ``Comms.init()`` analog.

Reference: python/raft-dask/raft_dask/common/comms.py:40-140 generates an NCCL
uniqueId at the root, broadcasts it over Dask, and every worker runs
``ncclCommInitRank`` + installs a ``comms_t`` into its ``device_resources``.

On TPU the runtime owns rendezvous: ``jax.distributed.initialize`` performs
the coordinator handshake (the uid-rendezvous analog), after which
``jax.devices()`` spans every chip in the slice and a global ``Mesh`` is the
installed communicator. Single-process multi-device (the LocalCUDACluster
test analog, SURVEY.md §4.3) needs no bootstrap at all — just a mesh over
``jax.local_devices()``.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh

# hard bound on the subprocess-isolated coordinator probe (the obs/health.py
# pattern: the verdict must arrive in seconds, whatever the child does)
PROBE_MAX_TIMEOUT = 20.0

_PROBE_SENTINEL = "RAFT_TPU_COMMS_OK"


def _probe_coordinator(addr: str, timeout: float) -> None:
    """Subprocess-isolated reachability check of ``host:port`` before the
    in-process rendezvous commits (ISSUE 3, the obs/health.py pattern: on
    this machine backend/coordinator init can wedge *unkillably* inside
    the process, so the only safe probe is a bounded child). Raises a
    TRANSIENT-classified error when the coordinator is unreachable; a
    wedged or absent coordinator now costs seconds, not the round."""
    host, sep, port = addr.rpartition(":")
    if not sep or not port.isdigit():
        return  # unparseable address: let jax.distributed report it
    timeout = min(float(timeout), PROBE_MAX_TIMEOUT)
    code = (
        "import socket\n"
        f"s = socket.create_connection(({host!r}, {int(port)}), timeout={timeout})\n"
        "s.close()\n"
        f"print({_PROBE_SENTINEL!r}, flush=True)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout + 5.0,
        )
    except subprocess.TimeoutExpired:
        # wording matters: "timed out" would classify DEADLINE (no retry);
        # an unreachable coordinator is the TRANSIENT, retry-worthy case
        raise RuntimeError(
            f"UNAVAILABLE: coordinator probe to {addr} got no connection "
            f"within {timeout:g}s") from None
    if _PROBE_SENTINEL not in (proc.stdout or ""):
        raise RuntimeError(
            f"UNAVAILABLE: coordinator {addr} unreachable "
            f"(probe rc={proc.returncode}: {(proc.stderr or '')[-300:]})")


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
    timeout_s: float = 60.0,
    probe: bool = True,
) -> bool:
    """Initialize multi-host JAX (ncclCommInitRank rendezvous analog).

    Rendezvous sources, in order: explicit args, the standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``),
    or — when ``auto=True`` — ``jax.distributed.initialize()`` with no args,
    which self-detects cloud-TPU pod metadata. ``auto`` is opt-in because on
    a non-pod machine the no-arg call can block looking for a coordinator.
    Returns False (no-op) when no source is available and ``auto`` is off.
    Idempotent: a second call returns True without re-initializing.

    Robustness (ISSUE 3): before committing to the in-process handshake, a
    subprocess-isolated reachability probe (``probe=True``) bounds the
    unreachable-coordinator wedge to seconds; the probe and the handshake
    each get one classified TRANSIENT retry with deterministic backoff,
    and ``timeout_s`` is forwarded as the rendezvous
    ``initialization_timeout`` where the jax version supports it.
    """
    if getattr(init_distributed, "_done", False):
        return True
    from raft_tpu.resilience import RetryPolicy, faultpoint, with_retries

    retry_once = RetryPolicy(max_retries=1, base_delay_s=0.5, max_delay_s=2.0)
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else os.environ.get("JAX_NUM_PROCESSES")
    pid = process_id if process_id is not None else os.environ.get("JAX_PROCESS_ID")

    def _initialize(**kwargs) -> None:
        import inspect

        # inside the retried callable, so an armed fault exercises the
        # same recovery path a real transient handshake failure takes
        faultpoint("comms.init_distributed")

        try:
            params = inspect.signature(jax.distributed.initialize).parameters
        except (TypeError, ValueError):  # pragma: no cover - C-level signature
            params = {}
        if "initialization_timeout" in params:
            kwargs["initialization_timeout"] = max(1, int(timeout_s))
        jax.distributed.initialize(**kwargs)

    if addr is None and nproc is None:
        if not auto:
            return False
        with_retries(_initialize, retry_once, site="comms.init_distributed")
        init_distributed._done = True
        return True
    if probe and addr:
        with_retries(lambda: _probe_coordinator(addr, timeout_s / 4.0),
                     retry_once, site="comms.init_distributed.probe")
    with_retries(
        lambda: _initialize(
            coordinator_address=addr,
            num_processes=int(nproc) if nproc is not None else None,
            process_id=int(pid) if pid is not None else None,
        ),
        retry_once, site="comms.init_distributed")
    init_distributed._done = True
    return True


def local_mesh(
    n_devices: Optional[int] = None, axis_names: Tuple[str, ...] = ("data",),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    """A mesh over this process's devices (LocalCUDACluster fixture analog).

    ``shape`` reshapes the device list for multi-axis meshes; defaults to 1-D
    over the first ``n_devices`` local devices.
    """
    devs = jax.local_devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    import numpy as np

    grid = np.array(devs, dtype=object)
    if shape is not None:
        grid = grid.reshape(tuple(shape))
    if grid.ndim != len(axis_names):
        raise ValueError(f"mesh shape {grid.shape} vs axis_names {axis_names}")
    return Mesh(grid, axis_names)
