"""Per-collective boolean self-tests — the ``comms_test.hpp`` analog.

Reference: cpp/include/raft/comms/comms_test.hpp:34-144 — one boolean test per
collective/p2p op (``test_collective_allreduce``, ``_broadcast``, ``_reduce``,
``_allgather``, ``_gather``, ``_reducescatter``, ``test_pointToPoint_*``,
``test_commsplit``), callable from any bootstrap so one code path validates
every transport. raft-dask runs exactly these from Python
(python/raft-dask/raft_dask/common/comms_utils.pyx:78+,
test_comms.py:220-268).

Here each test jits one shard_map region over the given mesh axis, compares
against a host-computed expectation, and returns a bool; ``comms_self_test``
runs them all and returns ``{name: ok}``.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from raft_tpu.comms import comms as C
from raft_tpu.core.compat import shard_map


def _run(mesh, axis, fn, x, in_spec, out_spec):
    return shard_map(
        fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )(x)


def test_allreduce(mesh: Mesh, axis: str) -> bool:
    n = mesh.shape[axis]
    x = jnp.arange(n, dtype=jnp.float32)  # shard i holds value i
    out = _run(mesh, axis, lambda s: C.allreduce(s, "sum", axis), x, P(axis), P(axis))
    want = np.full(n, n * (n - 1) / 2.0, np.float32)
    ok_sum = np.allclose(np.asarray(out), want)
    out_max = _run(mesh, axis, lambda s: C.allreduce(s, "max", axis), x, P(axis), P(axis))
    ok_max = np.allclose(np.asarray(out_max), np.full(n, n - 1.0, np.float32))
    return bool(ok_sum and ok_max)


def test_bcast(mesh: Mesh, axis: str, root: int = 0) -> bool:
    n = mesh.shape[axis]
    x = jnp.arange(1, n + 1, dtype=jnp.float32) * 10.0
    out = _run(mesh, axis, lambda s: C.bcast(s, root, axis), x, P(axis), P(axis))
    want = np.full(n, float((root + 1) * 10.0), np.float32)
    return bool(np.allclose(np.asarray(out), want))


def test_reduce(mesh: Mesh, axis: str, root: int = 0) -> bool:
    n = mesh.shape[axis]
    x = jnp.ones(n, jnp.float32)
    out = _run(mesh, axis, lambda s: C.reduce(s, root, "sum", axis), x, P(axis), P(axis))
    # contract: root's copy is the reduction
    return bool(np.asarray(out)[root] == n)


def test_allgather(mesh: Mesh, axis: str) -> bool:
    n = mesh.shape[axis]
    x = jnp.arange(n, dtype=jnp.float32)
    out = _run(
        mesh, axis, lambda s: C.allgather(s, axis, tiled=True), x, P(axis), P()
    )
    return bool(np.allclose(np.asarray(out), np.arange(n, dtype=np.float32)))


def test_gather(mesh: Mesh, axis: str, root: int = 0) -> bool:
    n = mesh.shape[axis]
    x = jnp.arange(n, dtype=jnp.float32) * 2.0
    out = _run(
        mesh, axis, lambda s: C.gather(s, root, axis, tiled=True), x, P(axis), P()
    )
    return bool(np.allclose(np.asarray(out), np.arange(n, dtype=np.float32) * 2.0))


def test_reducescatter(mesh: Mesh, axis: str) -> bool:
    n = mesh.shape[axis]
    # every shard holds the full [0..n) vector; reduce-scatter leaves shard i
    # with n * i
    x = jnp.tile(jnp.arange(n, dtype=jnp.float32), n)
    out = _run(
        mesh, axis, lambda s: C.reducescatter(s, "sum", axis), x, P(axis), P(axis)
    )
    want = np.arange(n, dtype=np.float32) * n
    return bool(np.allclose(np.asarray(out), want))


def test_sendrecv(mesh: Mesh, axis: str) -> bool:
    """Ring exchange: shard i sends its value to i+1 (test_pointToPoint_simple
    analog, comms_test.hpp:215)."""
    n = mesh.shape[axis]
    x = jnp.arange(n, dtype=jnp.float32)
    out = _run(mesh, axis, lambda s: C.shift(s, 1, axis), x, P(axis), P(axis))
    want = np.roll(np.arange(n, dtype=np.float32), 1)
    return bool(np.allclose(np.asarray(out), want))


def test_barrier(mesh: Mesh, axis: str) -> bool:
    n = mesh.shape[axis]
    x = jnp.zeros(n, jnp.int32)
    out = _run(mesh, axis, lambda s: s + C.barrier(axis), x, P(axis), P(axis))
    return bool((np.asarray(out) == n).all())


def test_comm_split(mesh: Mesh, axis: str) -> bool:
    """comm_split analog (test_commsplit, comms_test.hpp:250): split the 1-D
    communicator 2 x (n/2) and allreduce along each sub-axis independently."""
    comm = C.Comms(mesh, axis)
    n = comm.size
    if n % 2 != 0:
        return True  # not splittable; vacuous like the reference's skip
    row, col = comm.split(2, n // 2)
    x = jnp.arange(n, dtype=jnp.float32).reshape(2, n // 2)

    def body(s):
        r = C.allreduce(s, "sum", row.axis)   # sum down columns (2 entries)
        c = C.allreduce(s, "sum", col.axis)   # sum across rows (n/2 entries)
        return r, c

    r, c = shard_map(
        body,
        mesh=row.mesh,
        in_specs=(P(row.axis, col.axis),),
        out_specs=(P(row.axis, col.axis), P(row.axis, col.axis)),
        check_vma=False,
    )(x)
    a = np.arange(n, dtype=np.float32).reshape(2, n // 2)
    ok_r = np.allclose(np.asarray(r), np.broadcast_to(a.sum(0, keepdims=True), a.shape))
    ok_c = np.allclose(np.asarray(c), np.broadcast_to(a.sum(1, keepdims=True), a.shape))
    return bool(ok_r and ok_c)


_ALL_TESTS = {
    "allreduce": test_allreduce,
    "bcast": test_bcast,
    "reduce": test_reduce,
    "allgather": test_allgather,
    "gather": test_gather,
    "reducescatter": test_reducescatter,
    "sendrecv": test_sendrecv,
    "barrier": test_barrier,
    "comm_split": test_comm_split,
}


def comms_self_test(mesh: Mesh, axis: str = "data") -> Dict[str, bool]:
    """Run every per-collective self-test over ``mesh[axis]``; returns
    ``{collective: passed}`` (the comms_test.hpp harness, callable under any
    bootstrap — virtual CPU devices, one TPU host, or a multi-host slice)."""
    return {name: fn(mesh, axis) for name, fn in _ALL_TESTS.items()}
