"""``comms_t``-shaped collectives over ``shard_map``.

Reference surface being mirrored (cpp/include/raft/core/comms.hpp:143-230):
``get_size/get_rank/comm_split/barrier``, device collectives
``allreduce/bcast/reduce/allgather/gather/reducescatter``, and p2p
``device_send/device_recv/device_sendrecv``. The reference injects a
``comms_t`` into ``resources`` (core/resource/comms.hpp:64); here the analog
is a :class:`Comms` bound to a mesh axis, installable on
``Resources.mesh``.

Two layers:

* **In-SPMD functions** (module level): usable inside any ``shard_map``-ed
  function, addressing the communicator by axis name exactly like the
  reference addresses ``comms_t`` methods — these are thin, typed wrappers
  over ``lax`` collectives so MNMG algorithm code reads like the reference's.
* **:class:`Comms`**: the host-side handle — knows the mesh + axis, launches
  SPMD regions (``run``), and supports ``split`` into row/col
  sub-communicators (comm_split analog, 2-D mesh).

Semantics notes (documented deviations, by design):

* ``reduce``/``gather`` deliver the true result on ``root`` and the same
  value on all ranks (XLA collectives are symmetric; there is no cheaper
  root-only variant on ICI). Callers that need root-only semantics mask on
  ``get_rank() == root``.
* There is no ``allgatherv`` — XLA requires static shapes. Variable-length
  gathers are expressed as pad-to-max + validity mask by callers (the same
  padded-dense convention used throughout this framework).
* ``device_send``/``device_recv`` pairs collapse into :func:`sendrecv`
  (``lax.ppermute``), which only supports static permutations — sufficient
  for every algorithm in the reference (SURVEY.md §7 hard-parts note 5).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core.compat import axis_size, shard_map

_REDUCE_OPS = ("sum", "max", "min")


# ---------------------------------------------------------------------------
# In-SPMD collectives (call inside shard_map, addressed by axis name)
# ---------------------------------------------------------------------------

def get_size(axis: str = "data") -> int:
    """Communicator size (reference comms_t::get_size, core/comms.hpp:254)."""
    return axis_size(axis)


def get_rank(axis: str = "data") -> jax.Array:
    """This shard's rank along ``axis`` (comms_t::get_rank)."""
    return lax.axis_index(axis)


def allreduce(x, op: str = "sum", axis: str = "data") -> jax.Array:
    """All-reduce ``x`` with ``op`` in {sum,max,min} (comms_t::allreduce,
    core/comms.hpp:143; NCCL ncclAllReduce → psum/pmax/pmin on ICI)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"allreduce op must be one of {_REDUCE_OPS}, got {op!r}")


def reduce(x, root: int = 0, op: str = "sum", axis: str = "data") -> jax.Array:
    """Reduce to ``root`` (comms_t::reduce). See module docstring: the reduced
    value is computed on all ranks; only ``root``'s copy is meaningful by
    contract."""
    return allreduce(x, op=op, axis=axis)


def bcast(x, root: int = 0, axis: str = "data") -> jax.Array:
    """Broadcast ``root``'s shard value to all ranks (comms_t::bcast,
    core/comms.hpp:151). Implemented as mask + psum (one ICI collective)."""
    rank = lax.axis_index(axis)
    masked = jnp.where(rank == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def allgather(x, axis: str = "data", tiled: bool = False, gather_axis: int = 0):
    """Concatenate shards along ``gather_axis`` (comms_t::allgather,
    core/comms.hpp:159). ``tiled=False`` stacks a new leading axis."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def gather(x, root: int = 0, axis: str = "data", tiled: bool = False):
    """Gather to ``root`` (comms_t::gather, core/comms.hpp:173). The gathered
    array materializes on all ranks; ``root``'s copy is the contract."""
    return lax.all_gather(x, axis, axis=0, tiled=tiled)


def reducescatter(x, op: str = "sum", axis: str = "data", scatter_axis: int = 0):
    """Reduce-scatter (comms_t::reducescatter, core/comms.hpp:195 →
    lax.psum_scatter). ``x``'s ``scatter_axis`` must divide by axis size."""
    if op != "sum":
        raise ValueError("reducescatter supports op='sum' (ncclSum analog) only")
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def sendrecv(x, perm: Sequence[Tuple[int, int]], axis: str = "data") -> jax.Array:
    """Static-pattern point-to-point exchange (comms_t::device_sendrecv,
    core/comms.hpp:216 → lax.ppermute). ``perm`` is (src, dst) pairs; ranks
    that receive nothing get zeros."""
    return lax.ppermute(x, axis, perm=list(perm))


def shift(x, offset: int = 1, axis: str = "data") -> jax.Array:
    """Ring shift by ``offset`` (the ring-pass building block for
    ring-allreduce-style algorithms and ring attention)."""
    n = axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


def barrier(axis: str = "data") -> jax.Array:
    """Synchronization point (comms_t::barrier, core/comms.hpp:137): a psum
    of ones — every rank must arrive before any proceeds past the collective.
    Returns the communicator size (useful as a data dependency)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


# ---------------------------------------------------------------------------
# Host-side communicator handle
# ---------------------------------------------------------------------------

class Comms:
    """Host-side communicator: a mesh axis + SPMD launcher.

    The analog of ``comms_t`` held by ``resources`` (core/resource/comms.hpp:64).
    ``run`` plays the role of "issue collectives on the stream": it wraps a
    function containing in-SPMD collectives with ``shard_map`` over this
    communicator's mesh.
    """

    def __init__(self, mesh: Mesh, axis: Optional[str] = None):
        self.mesh = mesh
        if axis is None:
            if len(mesh.axis_names) != 1:
                raise ValueError(
                    f"mesh has axes {mesh.axis_names}; pass axis= explicitly"
                )
            axis = mesh.axis_names[0]
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.axis = axis

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding over this mesh for the given PartitionSpec entries."""
        return NamedSharding(self.mesh, P(*spec))

    def shard_rows(self, x) -> jax.Array:
        """Place ``x`` row-sharded over the communicator axis."""
        return jax.device_put(x, self.sharding(self.axis, *([None] * (jnp.ndim(x) - 1))))

    def replicate(self, x) -> jax.Array:
        """Place ``x`` replicated over the mesh."""
        return jax.device_put(x, NamedSharding(self.mesh, P()))

    def run(
        self,
        fn: Callable,
        *args,
        in_specs,
        out_specs,
        check_vma: bool = False,
    ):
        """Launch ``fn`` as an SPMD region over this communicator's mesh.

        ``fn`` sees per-shard views and may call the module-level collectives
        with ``axis=self.axis``.
        """
        return shard_map(
            fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )(*args)

    def split(self, rows: int, cols: int, names: Tuple[str, str] = ("row", "col")) -> Tuple["Comms", "Comms"]:
        """comm_split analog (core/comms.hpp:131): reshape this 1-D
        communicator into a (rows, cols) 2-D mesh and return the row- and
        col-axis sub-communicators. Every device belongs to one row comm and
        one col comm, like NCCL comm_split by color."""
        if rows * cols != self.size:
            raise ValueError(f"rows*cols = {rows * cols} != communicator size {self.size}")
        devs = list(self.mesh.devices.reshape(-1))
        import numpy as np

        grid = np.array(devs, dtype=object).reshape(rows, cols)
        mesh2 = Mesh(grid, names)
        return Comms(mesh2, names[0]), Comms(mesh2, names[1])


def shard_padded(x, comms: Comms, fill=0.0) -> Tuple[jax.Array, int]:
    """Pad ``x`` rows to a multiple of the communicator size and place it
    row-sharded over the mesh axis. Returns ``(sharded_x, n_padded)``. The
    single padding convention shared by every MNMG algorithm (callers mask
    pad rows by global id or zero weight)."""
    x = jnp.asarray(x)
    n = x.shape[0]
    world = comms.size
    n_padded = -(-n // world) * world
    if n_padded != n:
        pad_shape = (n_padded - n,) + x.shape[1:]
        x = jnp.concatenate([x, jnp.full(pad_shape, fill, x.dtype)], axis=0)
    spec = (comms.axis,) + (None,) * (x.ndim - 1)
    return jax.device_put(x, comms.sharding(*spec)), n_padded


def make_comms(res=None, axis: str = "data") -> Comms:
    """Build a Comms from the current Resources' mesh (set_comms/get_comms
    analog: the mesh slot on Resources is the installed communicator)."""
    from raft_tpu.core.resources import current_resources

    res = res or current_resources()
    return Comms(res.default_mesh(axis), axis)
