"""Distributed communication layer — the TPU-native ``comms_t``.

Reference: cpp/include/raft/core/comms.hpp:125-242 (``comms_iface``/``comms_t``),
comms/detail/std_comms.hpp:57-109 (NCCL/UCX impl), comms/comms_test.hpp:34-144
(per-collective verification harness), raft-dask bootstrap
python/raft-dask/raft_dask/common/comms.py:40.

TPU mapping (SURVEY.md §2.8): the communicator is a ``jax.sharding.Mesh`` axis;
collectives are XLA collectives issued inside ``shard_map`` and compiled onto
ICI/DCN — allreduce→psum, allgather→all_gather, reducescatter→psum_scatter,
sendrecv→ppermute, comm_split→sub-mesh axes. Bootstrap is
``jax.distributed.initialize`` instead of an NCCL-uid rendezvous.
"""

from raft_tpu.comms.comms import (
    Comms,
    allgather,
    allreduce,
    barrier,
    bcast,
    gather,
    get_rank,
    get_size,
    reduce,
    reducescatter,
    sendrecv,
)
from raft_tpu.comms.bootstrap import init_distributed, local_mesh
from raft_tpu.comms.self_test import comms_self_test

__all__ = [
    "Comms",
    "allreduce",
    "allgather",
    "barrier",
    "bcast",
    "gather",
    "get_rank",
    "get_size",
    "reduce",
    "reducescatter",
    "sendrecv",
    "comms_self_test",
    "init_distributed",
    "local_mesh",
]
