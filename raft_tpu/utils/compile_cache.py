"""Persistent XLA compilation cache.

Round-3 finding: on the tunneled TPU platform, cold compiles dominate index
build wall-clock (~60 s for the balanced-kmeans EM program alone vs 270 ms
of execution). The standard JAX fix is the persistent compilation cache —
one-line opt-in, compiled executables reused across processes. bench.py,
the test suite and __graft_entry__ enable it; library code never does
(user policy, like the reference leaving cudaDeviceSetCacheConfig to apps).
"""

from __future__ import annotations

import os


def enable_persistent_cache(path: str | None = None) -> None:
    """Turn on JAX's on-disk compilation cache (idempotent, best-effort)."""
    import jax

    path = path or os.environ.get(
        "RAFT_TPU_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu_xla"),
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # cache is an optimization, never a failure mode
        pass
