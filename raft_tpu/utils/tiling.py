"""Row-tiling helpers — the one place the pad/reshape pattern lives.

Every tiled algorithm (elementwise distances, fused L2 argmin, brute-force
search) pads its row dimension to a tile multiple and reshapes to
(n_tiles, tile, ...); centralized so budget fixes propagate (the memory-aware
tiling role of reference neighbors/detail/knn_brute_force.cuh:78-91).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def pad_rows(x: jax.Array, multiple: int, fill=0) -> jax.Array:
    """Pad axis 0 up to the next multiple (no-op if already aligned)."""
    m = x.shape[0]
    pad = ceil_div(m, multiple) * multiple - m
    if pad == 0:
        return x
    pad_shape = (pad,) + x.shape[1:]
    return jnp.concatenate([x, jnp.full(pad_shape, fill, x.dtype)])


def pad_and_tile(x: jax.Array, tile: int, fill=0) -> Tuple[jax.Array, int]:
    """Pad axis 0 to a multiple of ``tile`` and reshape to
    (n_tiles, tile, *rest). Returns (tiles, n_tiles)."""
    xp = pad_rows(x, tile, fill)
    n_tiles = xp.shape[0] // tile
    return xp.reshape((n_tiles, tile) + x.shape[1:]), n_tiles
