"""Row-tiling helpers — the one place the pad/reshape pattern lives.

Every tiled algorithm (elementwise distances, fused L2 argmin, brute-force
search) pads its row dimension to a tile multiple and reshapes to
(n_tiles, tile, ...); centralized so budget fixes propagate (the memory-aware
tiling role of reference neighbors/detail/knn_brute_force.cuh:78-91).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def pad_rows(x: jax.Array, multiple: int, fill=0) -> jax.Array:
    """Pad axis 0 up to the next multiple (no-op if already aligned)."""
    m = x.shape[0]
    pad = ceil_div(m, multiple) * multiple - m
    if pad == 0:
        return x
    pad_shape = (pad,) + x.shape[1:]
    return jnp.concatenate([x, jnp.full(pad_shape, fill, x.dtype)])


def pad_and_tile(x: jax.Array, tile: int, fill=0) -> Tuple[jax.Array, int]:
    """Pad axis 0 to a multiple of ``tile`` and reshape to
    (n_tiles, tile, *rest). Returns (tiles, n_tiles)."""
    xp = pad_rows(x, tile, fill)
    n_tiles = xp.shape[0] // tile
    return xp.reshape((n_tiles, tile) + x.shape[1:]), n_tiles


def map_row_tiles(fn, args: Tuple, tile: int, fills: Tuple = None,
                  min_tile: int = 128):
    """Run ``fn`` over row tiles of several same-leading-dim arrays and
    restitch the row dimension.

    ``fn`` takes a tuple of (tile, ...) blocks and returns an array or tuple
    of arrays with leading dim ``tile``. If the row count fits one tile, fn is
    called directly (no pad/reshape). ``fills`` optionally gives the padding
    value per arg (default 0 — searches that must ignore padded rows should
    pass sentinel fills, e.g. -1 for id arrays).

    When called EAGERLY (no argument is a tracer), the tile size is
    OOM-adaptive (ISSUE 3): a ``RESOURCE_EXHAUSTED`` dispatch retries at
    half the tile down to ``min_tile`` via ``resilience.degrade_on_oom``
    (the result is forced inside the attempt so the failure surfaces where
    it can be recovered). Under jit tracing the original single-dispatch
    path runs unchanged — recovery then belongs to the caller's host
    wrapper.
    """
    n = args[0].shape[0]
    if tile >= n:
        return fn(args)
    fills = fills or (0,) * len(args)

    def run(tile):
        n_tiles = ceil_div(n, tile)
        tiled = tuple(
            pad_and_tile(a, tile, fill)[0] for a, fill in zip(args, fills)
        )
        out = jax.lax.map(fn, tiled)
        def unstitch(o):
            return o.reshape((n_tiles * tile,) + o.shape[2:])[:n]
        return jax.tree.map(unstitch, out)

    if any(isinstance(a, jax.core.Tracer) for a in args):
        return run(tile)
    from raft_tpu.resilience import degrade_on_oom, force_completion

    def attempt(t):
        # scalar host fetch, not block_until_ready: the latter does NOT
        # synchronize on the tunneled axon runtime, and an unsurfaced
        # async OOM would escape the executor (bench.py timing note)
        return force_completion(run(t))

    return degrade_on_oom(attempt, tile,
                          floor=min(int(tile), max(1, int(min_tile))),
                          site="tiling.map_row_tiles")
