"""Clean-subprocess environment construction for CPU-forced child runs.

The single home of the axon-plugin wedge workaround (VERDICT.md Weak#1/2):
on this machine the TPU tunnel plugin can hang backend init when a platform
is requested via the ``JAX_PLATFORMS`` env var, so child processes that must
run on CPU (the multichip dryrun, bench's CPU fallback) scrub that var and
select the platform via ``jax.config.update('jax_platforms', 'cpu')`` inside
the child instead. Used by ``__graft_entry__.dryrun_multichip`` and
``bench.py``. Import-light on purpose: no jax import here.
"""

from __future__ import annotations

import os
from typing import Optional


def clean_cpu_env(n_devices: Optional[int] = None) -> dict:
    """A copy of os.environ prepared for a CPU-forced jax child process.

    Scrubs ``JAX_PLATFORMS`` (the child must use the config route) and, when
    ``n_devices`` is given, pins ``--xla_force_host_platform_device_count``
    in ``XLA_FLAGS`` (replacing any ambient setting of that flag).
    """
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    if n_devices is not None:
        flags = " ".join(
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        )
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    return env
