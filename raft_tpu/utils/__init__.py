"""Shared utilities: tiling helpers used by every out-of-core algorithm."""

from raft_tpu.utils.tiling import pad_rows, pad_and_tile, ceil_div

__all__ = ["pad_rows", "pad_and_tile", "ceil_div"]
