"""Attribution engine: "why is this operating point slow", as a record.

Five rounds of planes stamp *evidence* into the obs report — the roofline
``bound``/utilization verdicts (obs/roofline.py), occupancy fractions,
the compile ledger's retrace counts, the admission verdict counters, the
queue depth/batch-cap pair, the shadow-recall Wilson CI, the SLO burn
states. :func:`explain` folds one ``obs.report.collect()`` record (plus,
optionally, the previous window's record for cumulative-counter deltas)
into a **ranked, classified diagnosis list**: every entry one of
:data:`KINDS`, scored 0..1, with the evidence fields that produced it
attached. The autotuner's rule table (raft_tpu/tuning/autotune.py) keys
knob moves off the top diagnosis; the burn-rate controller
(serving/controller.py) stamps it into every ``tuning.action`` event —
"why slow" stops being a human reading JSONL.

Diagnosis kinds:

* ``mxu_underfill``  — compute-bound but the MXU sits idle (small batch,
  thin q_block): raise the arithmetic per dispatch.
* ``hbm_bound``      — the scan streams more bytes than the FLOPs justify:
  shrink bytes/vector (lower ``bits``, engine switch).
* ``padding_waste``  — a large padded fraction of each dispatch is dead
  rows: fix tiling/page fill, not clock speed.
* ``recall_limited`` — the recall SLO burns (or the CI sits under its
  floor): spend latency on nprobe/k_fetch, nothing else helps.
* ``queue_limited``  — requests back up behind the batch cap while the
  device is fine: raise the cap / widen batching.
* ``capacity_limited`` — the admission controller queues/rejects: the
  working set does not fit, tier or shrink it.
* ``retrace_tax``    — compile-ledger traces landed inside the window:
  the zero-recompile contract broke and every retrace eats the budget.
* ``unknown``        — pressure without evidence (or the evidence plane
  itself degraded): explicitly classified, never silent.

A HEALTHY window — no SLO burning, no degraded sections, no backlog —
yields an *empty* diagnosis list (``healthy=True``), not ``unknown``;
the acceptance gate counts ``unknown`` on healthy windows as a failure
of this module. ``validate()`` checks the structural contract of an
explain record the same way obs.report/obs.flight validate theirs.
"""

from __future__ import annotations

import math
from typing import Optional

from raft_tpu import obs

__all__ = ["KINDS", "SCHEMA_VERSION", "explain", "validate"]

#: explain record schema (independent of the report's version — the
#: ``report_schema`` field carries the input's stamp)
SCHEMA_VERSION = 1

#: every diagnosis kind explain() may emit, in no particular order —
#: ranking is by score, per record
KINDS = ("mxu_underfill", "hbm_bound", "padding_waste", "recall_limited",
         "queue_limited", "capacity_limited", "retrace_tax", "unknown")

#: MXU utilization below this on a compute-bound entry is underfill
_MXU_FLOOR = 0.5
#: padded fraction at/above this is tiling waste worth a knob move
_PAD_FLOOR = 0.25
#: queue depth beyond this multiple of the batch cap is a backlog
_DEPTH_RATIO = 2.0

#: report sections whose degradation blinds the attribution — a window
#: missing these can only be diagnosed ``unknown``
_EVIDENCE_SECTIONS = ("roofline", "compile", "admission", "queue",
                      "recall", "slo")


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def _clamp(x: float) -> float:
    return max(0.0, min(1.0, float(x)))


def _dominant_roofline(roof: dict) -> Optional[tuple]:
    """The entry that dominates the window's device time: highest
    measured seconds when available, else most dispatches."""
    best_name, best_row, best_key = None, None, (-1.0, -1.0)
    for name, row in (roof.get("entries") or {}).items():
        if not isinstance(row, dict):
            continue
        measured = row.get("measured_s")
        key = (measured if _finite(measured) else -1.0,
               float(row.get("dispatches") or 0))
        if key > best_key:
            best_name, best_row, best_key = name, row, key
    return (best_name, best_row) if best_row is not None else None


def _slo_pressure(slo: dict) -> dict:
    """SLO rows currently burning, by name → state (warn/breach)."""
    out = {}
    for name, row in (slo or {}).items():
        if isinstance(row, dict) and row.get("state") in ("warn", "breach"):
            out[name] = row["state"]
    return out


def _delta(cur, prev) -> Optional[int]:
    """Window-local delta of a cumulative counter (None when either side
    is missing — absence must not masquerade as zero)."""
    if not (_finite(cur) and _finite(prev)):
        return None
    return int(cur) - int(prev)


def explain(report: dict, prev: Optional[dict] = None) -> dict:
    """Fold one obs-report record into a ranked diagnosis record.

    ``prev`` (optional) is the PREVIOUS window's report from the same
    stream: cumulative counters (compile traces, admission verdicts)
    diff into window-local evidence with it; without it those detectors
    fall back to the cumulative values (first window of a recording).
    Raises ``ValueError`` on a non-report input — the explainer explains
    records, it does not invent them.
    """
    if not isinstance(report, dict) or report.get("type") != "obs_report":
        raise ValueError(
            f"explain() wants an obs_report record, got "
            f"{type(report).__name__}"
            + (f" of type {report.get('type')!r}"
               if isinstance(report, dict) else ""))
    with obs.record_span("obs.explain::explain",
                         attrs={"window": report.get("window")}):
        return _explain(report, prev if isinstance(prev, dict) else None)


def _explain(report: dict, prev: Optional[dict]) -> dict:
    diagnoses: list = []
    errors = report.get("errors") or {}
    slo = report.get("slo") if isinstance(report.get("slo"), dict) else {}
    pressure = _slo_pressure(slo)

    # -- retrace_tax: the compile ledger moved inside the window ----------
    comp = report.get("compile")
    if isinstance(comp, dict):
        unexplained = comp.get("unexplained_retraces") or 0
        total = comp.get("total_traces")
        prev_comp = (prev or {}).get("compile")
        d_traces = _delta(total, (prev_comp or {}).get("total_traces")) \
            if isinstance(prev_comp, dict) else None
        if unexplained:
            diagnoses.append({
                "kind": "retrace_tax", "score": 1.0,
                "evidence": {"unexplained_retraces": int(unexplained),
                             "total_traces": total}})
        elif d_traces:
            diagnoses.append({
                "kind": "retrace_tax",
                "score": _clamp(0.5 + 0.1 * d_traces),
                "evidence": {"traces_this_window": d_traces,
                             "total_traces": total}})

    # -- recall_limited: the one diagnosis latency cannot buy back --------
    rec = report.get("recall")
    recall_rows = [(n, r) for n, r in slo.items()
                   if isinstance(r, dict) and r.get("kind") == "recall"]
    for name, row in recall_rows:
        state = row.get("state")
        if state in ("warn", "breach"):
            diagnoses.append({
                "kind": "recall_limited",
                "score": 0.9 if state == "breach" else 0.6,
                "evidence": {"slo": name, "state": state,
                             "target": row.get("target"),
                             "value": row.get("value"),
                             "burn_fast": row.get("burn_fast")}})
            break
    else:
        if isinstance(rec, dict) and recall_rows:
            floor = recall_rows[0][1].get("target")
            ci_high = rec.get("ci_high")
            if _finite(floor) and _finite(ci_high) and ci_high < floor:
                diagnoses.append({
                    "kind": "recall_limited",
                    "score": _clamp(0.5 + (floor - ci_high)),
                    "evidence": {"ci_high": ci_high, "floor": floor,
                                 "recall": rec.get("recall"),
                                 "samples": rec.get("samples")}})

    # -- capacity_limited: the admission controller said no ---------------
    adm = report.get("admission")
    if isinstance(adm, dict):
        prev_adm = (prev or {}).get("admission")
        cur = {k: int(adm.get(k) or 0) for k in ("admit", "queue", "reject")}
        if isinstance(prev_adm, dict):
            for k in cur:
                d = _delta(cur[k], prev_adm.get(k) or 0)
                cur[k] = d if d is not None and d >= 0 else cur[k]
        denied = cur["queue"] + cur["reject"]
        if denied:
            diagnoses.append({
                "kind": "capacity_limited",
                "score": _clamp(denied / max(1, denied + cur["admit"])),
                "evidence": {"queued": cur["queue"],
                             "rejected": cur["reject"],
                             "admitted": cur["admit"]}})

    # -- queue_limited: backlog behind the batch cap ----------------------
    q = report.get("queue")
    if isinstance(q, dict):
        depth = q.get("depth")
        cap = q.get("batch_cap")
        if _finite(depth) and _finite(cap) and cap > 0 \
                and depth >= _DEPTH_RATIO * cap:
            diagnoses.append({
                "kind": "queue_limited",
                "score": _clamp(depth / (8.0 * cap)),
                "evidence": {"depth": int(depth), "batch_cap": int(cap),
                             "requeued": q.get("requeued")}})

    # -- roofline triplet on the dominant entry ---------------------------
    roof = report.get("roofline")
    dom = _dominant_roofline(roof) if isinstance(roof, dict) else None
    if dom is not None:
        name, row = dom
        bound = row.get("bound")
        mxu = row.get("mxu_utilization")
        hbm = row.get("hbm_bw_utilization")
        if bound == "memory":
            diagnoses.append({
                "kind": "hbm_bound",
                "score": _clamp(hbm) if _finite(hbm) else 0.6,
                "evidence": {"entry": name,
                             "hbm_bw_utilization": hbm,
                             "mxu_utilization": mxu,
                             "bytes": row.get("bytes")}})
        elif bound == "compute" and _finite(mxu) and mxu < _MXU_FLOOR:
            occ = row.get("occupancy") or {}
            diagnoses.append({
                "kind": "mxu_underfill",
                "score": _clamp(1.0 - mxu),
                "evidence": {"entry": name, "mxu_utilization": mxu,
                             "tile_fill": occ.get("tile_fill"),
                             "mxu_m_fill": occ.get("mxu_m_fill")}})
        pad = row.get("padded_fraction")
        if _finite(pad) and pad >= _PAD_FLOOR:
            diagnoses.append({
                "kind": "padding_waste", "score": _clamp(pad),
                "evidence": {"entry": name, "padded_fraction": pad}})

    # -- unknown: pressure or blindness without an attribution ------------
    degraded = {s: errors[s] for s in _EVIDENCE_SECTIONS if s in errors}
    if degraded:
        diagnoses.append({
            "kind": "unknown", "score": 0.5,
            "evidence": {"degraded": degraded}})
    elif pressure and not diagnoses:
        diagnoses.append({
            "kind": "unknown", "score": 0.5,
            "evidence": {"burning": pressure}})

    diagnoses.sort(key=lambda d: (-d["score"], d["kind"]))
    return {
        "t": report.get("t"),
        "type": "explain",
        "schema_version": SCHEMA_VERSION,
        "report_schema": report.get("schema_version"),
        "window": report.get("window"),
        "pressure": pressure,
        "healthy": not pressure and not degraded,
        "primary": diagnoses[0]["kind"] if diagnoses else None,
        "diagnoses": diagnoses,
    }


def validate(record: dict) -> list:
    """Structural health of one explain record: the list of problems
    (empty = valid). Checks the contract the tuner/controller depend on:
    every diagnosis a known kind with a finite 0..1 score and an evidence
    dict, the list ranked by score, ``primary`` consistent with it, and
    ``unknown`` never stamped on a window the record itself calls
    healthy."""
    problems = []
    if not isinstance(record, dict) or record.get("type") != "explain":
        return [f"not an explain record: {type(record).__name__}"]
    if record.get("schema_version") != SCHEMA_VERSION:
        problems.append(f"schema_version {record.get('schema_version')!r} "
                        f"!= {SCHEMA_VERSION}")
    diags = record.get("diagnoses")
    if not isinstance(diags, list):
        return problems + ["diagnoses is not a list"]
    prev_score = None
    for i, d in enumerate(diags):
        label = f"diagnoses[{i}]"
        if not isinstance(d, dict):
            problems.append(f"{label} is not a record")
            continue
        kind = d.get("kind")
        if kind not in KINDS:
            problems.append(f"{label}.kind unknown: {kind!r}")
        score = d.get("score")
        if not (_finite(score) and 0.0 <= score <= 1.0):
            problems.append(f"{label}.score not in [0,1]: {score!r}")
        elif prev_score is not None and score > prev_score:
            problems.append(f"{label} not ranked (score {score} after "
                            f"{prev_score})")
        else:
            prev_score = score
        if not isinstance(d.get("evidence"), dict):
            problems.append(f"{label} carries no evidence")
    primary = record.get("primary")
    top = diags[0].get("kind") if diags and isinstance(diags[0], dict) \
        else None
    if primary != top:
        problems.append(f"primary {primary!r} != top diagnosis {top!r}")
    if record.get("healthy") and any(
            isinstance(d, dict) and d.get("kind") == "unknown"
            for d in diags):
        problems.append("unknown diagnosis on a healthy window")
    return problems
