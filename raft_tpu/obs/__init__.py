"""Runtime telemetry: metrics registry, span trees, fleet merge, health probe.

The observability layer the reference ships as NVTX ranges + an spdlog logger
(core/nvtx.hpp, core/logger.hpp), grown into something measurable: a
process-wide registry (obs/registry.py) that hot paths feed counters and
wall-clock spans into behind a single-branch ``obs.enabled()`` gate; a
hierarchical tracing layer (obs/tracing.py) that parents nested spans into
trace trees exportable as Perfetto-loadable Chrome trace JSON; an exact
fleet-wide merge of per-process snapshots (obs/aggregate.py, also
``python -m raft_tpu.obs.aggregate``); and a subprocess-isolated
device-health probe (obs/health.py) that answers "is this backend alive?" in
bounded time — the check bench.py runs before committing its TPU window (the
round-5 wedge ate the whole window with no record; ISSUE 1 / VERDICT.md
round 5).

Usage::

    from raft_tpu import obs

    obs.enable()                      # or RAFT_TPU_OBS=1 in the env
    with obs.record_span("my::phase", attrs={"rows": n}):
        with obs.record_span("my::tile"):   # parented under my::phase
            ...
    obs.add("my.rows", n)             # counter
    obs.observe("my.batch_s", dt)     # pow2 histogram (p50/p90/p99 bounds)
    obs.snapshot()                    # {"counters": .., "timers": .., ..}
    obs.export_jsonl("results/obs.jsonl", {"run": "r06"})  # process-stamped
    obs.export_chrome_trace("results/trace_dev.json")      # open in Perfetto

Instrumented code gates every emission::

    if obs.enabled():
        obs.add("ivf_pq.search.queries", q)

so the telemetry-off cost of a hot path is one function call and one branch.
``RAFT_TPU_OBS_SYNC=1`` (or :func:`enable_sync`) opts spans into device-time
attribution: the dispatch queue is drained at span exit so jitted phases
report committed time, with the raw dispatch wall-clock kept as the
``dispatch_s`` span attribute.
"""

# NOTE: obs.aggregate and obs.report are deliberately NOT imported here —
# preloading either would shadow its `python -m raft_tpu.obs.<mod>` runpy
# execution; reach them as `from raft_tpu.obs import aggregate, report`.
# The SLO plane (obs.slo / obs.shadow / obs.memory / obs.report) is also
# kept off the package import path because it reaches into resilience,
# which imports obs back — import those modules directly when needed.
from raft_tpu.obs import tracing
from raft_tpu.obs.registry import (
    NOOP_SPAN,
    MetricsRegistry,
    add,
    disable,
    enable,
    enabled,
    export_jsonl,
    inc_gauge,
    observe,
    record_span,
    record_timing,
    registry,
    reset,
    set_gauge,
    snapshot,
)
from raft_tpu.obs.tracing import (
    chrome_trace,
    clear_spans,
    disable_sync,
    enable_sync,
    export_chrome_trace,
    process_info,
    spans,
    sync_enabled,
)
from raft_tpu.obs.health import MAX_TIMEOUT, HealthReport, probe

__all__ = [
    "MAX_TIMEOUT",
    "HealthReport",
    "MetricsRegistry",
    "NOOP_SPAN",
    "add",
    "chrome_trace",
    "clear_spans",
    "disable",
    "disable_sync",
    "enable",
    "enable_sync",
    "enabled",
    "export_chrome_trace",
    "export_jsonl",
    "inc_gauge",
    "observe",
    "probe",
    "process_info",
    "record_span",
    "record_timing",
    "registry",
    "reset",
    "set_gauge",
    "snapshot",
    "spans",
    "sync_enabled",
    "tracing",
]
