"""Runtime telemetry: metrics registry, trace spans, device-health probe.

The observability layer the reference ships as NVTX ranges + an spdlog logger
(core/nvtx.hpp, core/logger.hpp), grown into something measurable: a
process-wide registry (obs/registry.py) that hot paths feed counters and
wall-clock spans into behind a single-branch ``obs.enabled()`` gate, and a
subprocess-isolated device-health probe (obs/health.py) that answers "is this
backend alive?" in bounded time — the check bench.py runs before committing
its TPU window (the round-5 wedge ate the whole window with no record;
ISSUE 1 / VERDICT.md round 5).

Usage::

    from raft_tpu import obs

    obs.enable()                      # or RAFT_TPU_OBS=1 in the env
    with obs.record_span("my::phase"):
        ...                           # timed + profiler-annotated
    obs.add("my.rows", n)             # counter
    obs.snapshot()                    # {"counters": .., "timers": .., ..}
    obs.export_jsonl("results/obs.jsonl", {"run": "r06"})

Instrumented code gates every emission::

    if obs.enabled():
        obs.add("ivf_pq.search.queries", q)

so the telemetry-off cost of a hot path is one function call and one branch.
"""

from raft_tpu.obs.registry import (
    NOOP_SPAN,
    MetricsRegistry,
    add,
    disable,
    enable,
    enabled,
    export_jsonl,
    observe,
    record_span,
    record_timing,
    registry,
    reset,
    snapshot,
)
from raft_tpu.obs.health import MAX_TIMEOUT, HealthReport, probe

__all__ = [
    "MAX_TIMEOUT",
    "HealthReport",
    "MetricsRegistry",
    "NOOP_SPAN",
    "add",
    "disable",
    "enable",
    "enabled",
    "export_jsonl",
    "observe",
    "probe",
    "record_span",
    "record_timing",
    "registry",
    "reset",
    "snapshot",
]
