"""Device-health probe: "can JAX init + run one tiny op on this platform?"

Round 5 lost an entire bench round to a wedged TPU tunnel: ``jax.devices()``
hung at 0% CPU inside the measurement child, the TPU attempt burned its full
2,500 s window, and the CPU fallback never got a turn (VERDICT.md round 5,
``BENCH_r05.json`` rc=124). The failure mode is backend *initialization*
hanging — unkillable from inside the process, invisible until the watchdog
fires. So the probe is subprocess-isolated and hard-bounded: a fresh child
imports jax, runs one tiny matmul, and prints a sentinel; the parent waits at
most ``timeout`` seconds (clamped to :data:`MAX_TIMEOUT`) and kills the child
on overrun. A dead tunnel now costs ~20 s instead of a round of evidence.

Import-light on purpose (no jax at module level): bench.py's orchestrator
calls this before it ever touches a backend.

Standalone: ``python -m raft_tpu.obs.health [--platform cpu] [--timeout 20]``
prints the report as JSON and exits 0 (healthy) / 1 (unhealthy).
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from dataclasses import asdict, dataclass
from typing import Optional

# Hard ceiling on any single probe, whatever the caller asks for: the whole
# point is bounding time-to-verdict.
MAX_TIMEOUT = 30.0

_SENTINEL = "RAFT_TPU_HEALTH_OK"

# jax.config route for CPU (NOT the env var: the axon plugin hangs backend
# init when JAX_PLATFORMS is set — utils/subproc.py, VERDICT.md Weak#1/2)
_CPU_PRELUDE = "import jax; jax.config.update('jax_platforms', 'cpu')\n"

_CHILD_CODE = (
    "import jax, jax.numpy as jnp\n"
    "x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)\n"
    "v = float(jnp.sum(x @ x.T))\n"
    "print('" + _SENTINEL + "', jax.devices()[0].platform, v, flush=True)\n"
)


@dataclass
class HealthReport:
    healthy: bool
    platform: str  # platform requested ("default" = ambient)
    backend: str  # platform the child actually initialized ("" if unknown)
    elapsed_s: float
    reason: str  # "" when healthy

    def as_dict(self) -> dict:
        return asdict(self)


def probe(
    platform: str = "default",
    timeout: float = 20.0,
    child_code: Optional[str] = None,
) -> HealthReport:
    """Run the health check in a fresh bounded subprocess.

    ``platform``: "default" probes whatever backend the ambient environment
    selects (the TPU tunnel when present); "cpu" probes the scrubbed-env CPU
    route. ``child_code`` overrides the child program (tests use it to
    simulate a hanging backend).
    """
    timeout = min(float(timeout), MAX_TIMEOUT)
    if platform == "cpu":
        from raft_tpu.utils.subproc import clean_cpu_env

        env = clean_cpu_env()
        code = _CPU_PRELUDE + (child_code if child_code is not None else _CHILD_CODE)
    else:
        import os

        env = dict(os.environ)
        code = child_code if child_code is not None else _CHILD_CODE

    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return HealthReport(
            False, platform, "", round(time.monotonic() - t0, 2),
            f"probe timed out after {timeout:g}s "
            "(backend init or first op hang)",
        )
    elapsed = round(time.monotonic() - t0, 2)
    for line in (proc.stdout or "").splitlines():
        if line.startswith(_SENTINEL):
            parts = line.split()
            backend = parts[1] if len(parts) > 1 else ""
            return HealthReport(True, platform, backend, elapsed, "")
    return HealthReport(
        False, platform, "", elapsed,
        f"probe child rc={proc.returncode}; "
        f"stderr: {(proc.stderr or '')[-500:]}",
    )


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--platform", default="default",
                    help='"default" (ambient backend) or "cpu"')
    ap.add_argument("--timeout", type=float, default=20.0,
                    help=f"seconds before the probe is killed "
                         f"(clamped to {MAX_TIMEOUT:g})")
    args = ap.parse_args(argv)
    report = probe(args.platform, args.timeout)
    print(json.dumps(report.as_dict()))
    return 0 if report.healthy else 1


if __name__ == "__main__":
    sys.exit(main())
