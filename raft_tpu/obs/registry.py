"""Process-wide metrics registry: counters, wall-clock timers, histograms.

The telemetry analog of the reference's NVTX-range + spdlog infrastructure
(cpp/include/raft/core/nvtx.hpp, logger.hpp) — except measured, not just
annotated: every :func:`record_span` feeds BOTH the profiler timeline
(``jax.profiler.TraceAnnotation``, the NVTX-range analog core/trace.py already
provides) and this registry, so hot-path timings survive the process even when
no profiler capture is active.

Zero-dep and thread-safe (one ``threading.Lock`` around the maps; jax.profiler
is imported lazily and only when a span actually opens). Telemetry is OFF by
default: the gate is the ``RAFT_TPU_OBS`` env var (or :func:`enable` /
:func:`disable` at runtime), and every instrumented hot path guards its
emission with ``if obs.enabled():`` so the disabled cost is a single branch.
When disabled, :func:`record_span` returns one shared no-op context manager
(``NOOP_SPAN`` — identity-testable, which is how the overhead contract is
asserted in tests) and never touches the registry.

Span timings are host wall-clock around the instrumented region. JAX dispatch
is asynchronous, so a span around a pure-dispatch region measures dispatch +
trace/compile time, not device execution — that is the useful number for the
wedge-hunting this layer exists for (VERDICT.md round 5: the failure modes are
host-side hangs, not slow kernels).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Optional

from raft_tpu.obs import tracing as _tracing

__all__ = [
    "DISPATCH_HIST_PREFIX",
    "EXEMPLAR_CAP",
    "MetricsRegistry",
    "NOOP_SPAN",
    "add",
    "disable",
    "enable",
    "enabled",
    "export_jsonl",
    "inc_gauge",
    "observe",
    "record_span",
    "record_timing",
    "register_dispatch_span",
    "registry",
    "reset",
    "set_gauge",
    "snapshot",
]

#: exemplars kept per histogram (newest win) — enough to link each
#: percentile bucket of a live latency histogram to a recent trace id
#: without growing the snapshot unboundedly
EXEMPLAR_CAP = 8

#: histogram namespace for sync-mode committed span durations (round 15):
#: ``dispatch.<span name>`` — the per-entry device-time fold obs/roofline
#: pairs with its static FLOP/byte model (the ONE definition; roofline
#: reads histograms back through it)
DISPATCH_HIST_PREFIX = "dispatch."

#: spans whose sync-mode committed durations are worth a dispatch
#: histogram — ONLY registered device-dispatch spans fold (obs/roofline
#: registers its entry spans at import). Folding every span would double
#: histogram cardinality and label host-only telemetry spans as device
#: dispatches.
_DISPATCH_SPANS: set = set()


def register_dispatch_span(name: str) -> None:
    """Opt a span name into the sync-mode ``dispatch.*`` histogram fold
    (obs/roofline does this for every entry it models)."""
    _DISPATCH_SPANS.add(name)

_enabled = os.environ.get("RAFT_TPU_OBS", "").strip().lower() in (
    "1", "true", "on", "yes",
)


def enabled() -> bool:
    """The single-branch hot-path gate: instrumented code runs its emission
    only under ``if obs.enabled():``."""
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


class _TimerStat:
    """count / total / min / max of one named wall-clock timer."""

    __slots__ = ("count", "total_s", "min_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    def add(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        if seconds < self.min_s:
            self.min_s = seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": self.total_s,
            "min_s": self.min_s,
            "max_s": self.max_s,
            "mean_s": self.total_s / self.count if self.count else 0.0,
        }


class _HistStat:
    """Power-of-two-bucketed histogram (+ count/sum/min/max exact).

    Carries a small bounded **exemplar ring**: when an observation lands
    while a trace is open (or the caller passes ``trace_id`` explicitly),
    the ``(bucket, trace_id, value)`` triple is kept so a percentile bucket
    in a snapshot links back to a concrete recent trace — "p99 is 80 ms,
    and HERE is a request that paid it". The ring is ``EXEMPLAR_CAP`` deep
    (newest win) and dies with ``reset()``, so trace ids never leak across
    tests or runs."""

    __slots__ = ("count", "sum", "min", "max", "buckets", "exemplars")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: dict = {}
        self.exemplars: deque = deque(maxlen=EXEMPLAR_CAP)

    def add(self, value: float, trace_id: Optional[str] = None) -> None:
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        # bucket upper bound = smallest power of two >= value (0 for v <= 0).
        # repr, not %g: 6-sig-digit rounding would print 2**21 as
        # 'le_2.09715e+06', and the percentile parser reading that back
        # would report an "upper bound" BELOW the observed max
        bound = 0.0 if value <= 0 else 2.0 ** math.ceil(math.log2(value))
        key = f"le_{bound!r}"
        self.buckets[key] = self.buckets.get(key, 0) + 1
        if trace_id is not None:
            self.exemplars.append(
                {"bucket": key, "trace_id": trace_id, "value": value})

    def as_dict(self) -> dict:
        out = {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "buckets": dict(self.buckets),
        }
        if self.exemplars:
            out["exemplars"] = list(self.exemplars)
        # p50/p90/p99 UPPER bounds derived from the power-of-two buckets:
        # over-estimates the true quantile by ≤2× (the bucket resolution);
        # shared with the fleet merge so per-process and merged views agree.
        # Lazy import: preloading obs.aggregate at package-import time would
        # shadow the `python -m raft_tpu.obs.aggregate` runpy execution.
        from raft_tpu.obs.aggregate import percentile_bounds

        out.update(percentile_bounds(self.buckets, self.count))
        return out


class _GaugeStat:
    """Last-value gauge with exact min/max/count of everything set."""

    __slots__ = ("value", "min", "max", "count")

    def __init__(self):
        self.value = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.count = 0

    def set(self, value: float) -> None:
        self.value = value
        self.count += 1
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def inc(self, delta: float) -> None:
        self.set(self.value + delta)

    def as_dict(self, process_key: str) -> dict:
        # "last" keys the final value by process so the fleet merge can
        # preserve per-process last values exactly (obs/aggregate merges
        # min-of-min / max-of-max and unions these maps)
        return {"value": self.value, "min": self.min, "max": self.max,
                "count": self.count, "last": {process_key: self.value}}


class MetricsRegistry:
    """Thread-safe named counters + timers + histograms with dict snapshots
    and JSONL export. One process-wide default instance lives in this module
    (:func:`registry`); algorithms never construct their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}  # guarded-by: _lock
        self._timers: dict = {}    # guarded-by: _lock
        self._hists: dict = {}     # guarded-by: _lock
        self._gauges: dict = {}    # guarded-by: _lock

    # -- writes -------------------------------------------------------------
    def add(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def record_timing(self, name: str, seconds: float) -> None:
        with self._lock:
            stat = self._timers.get(name)
            if stat is None:
                stat = self._timers[name] = _TimerStat()
            stat.add(seconds)

    def observe(self, name: str, value: float,
                trace_id: Optional[str] = None) -> None:
        """Record one histogram observation. ``trace_id`` (or, when None,
        the innermost open span's trace) lands in the histogram's exemplar
        ring so percentile buckets link to concrete recent traces."""
        if trace_id is None:
            cur = _tracing.current_span()
            if cur is not None:
                trace_id = cur[0]
        with self._lock:
            stat = self._hists.get(name)
            if stat is None:
                stat = self._hists[name] = _HistStat()
            stat.add(value, trace_id)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            stat = self._gauges.get(name)
            if stat is None:
                stat = self._gauges[name] = _GaugeStat()
            stat.set(float(value))

    def inc_gauge(self, name: str, delta: float = 1) -> None:
        with self._lock:
            stat = self._gauges.get(name)
            if stat is None:
                stat = self._gauges[name] = _GaugeStat()
            stat.inc(float(delta))

    # -- reads --------------------------------------------------------------
    def snapshot(self) -> dict:
        """Plain-dict copy: {"counters": .., "timers": .., "histograms": ..,
        "gauges": ..}. Empty sections are included so consumers need no key
        checks."""
        pk = f"p{_tracing.process_info()[0]}"
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timers": {k: v.as_dict() for k, v in self._timers.items()},
                "histograms": {k: v.as_dict() for k, v in self._hists.items()},
                "gauges": {k: v.as_dict(pk) for k, v in self._gauges.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._hists.clear()
            self._gauges.clear()

    def export_jsonl(self, path, extra: Optional[dict] = None) -> dict:
        """Append one timestamped snapshot line to ``path``; returns the
        record written. ``extra`` keys ride at the top level (run ids, phase
        tags). Every record is stamped with ``process_index`` /
        ``process_count`` (obs/tracing.process_info) so per-process files
        merge into a fleet view via ``python -m raft_tpu.obs.aggregate``."""
        pi, pc = _tracing.process_info()
        rec = {"t": round(time.time(), 3), "process_index": pi,
               "process_count": pc, **(extra or {}), **self.snapshot()}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
        return rec


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

_ANN_UNRESOLVED = object()
_ann_cls = _ANN_UNRESOLVED


def _trace_annotation():
    """jax.profiler.TraceAnnotation, resolved lazily (the registry must stay
    importable in jax-free parent processes like bench.py's orchestrator);
    None when jax is unavailable."""
    global _ann_cls
    if _ann_cls is _ANN_UNRESOLVED:
        try:
            import jax.profiler

            _ann_cls = jax.profiler.TraceAnnotation
        # jax-free parents are a supported state — nothing to classify
        except Exception:  # pragma: no cover  # graftlint: ignore[unclassified-except]
            _ann_cls = None
    return _ann_cls


def _classify_error(exc) -> str:
    """Failure kind for a span that raised, via resilience.classify (lazy:
    resilience imports obs, so the import must not run at module load).
    Falls back to the bare class name if the resilience layer is absent."""
    try:
        from raft_tpu.resilience.errors import classify

        return classify(exc)
    # this IS the classify call site; its own fallback (a partially
    # imported resilience package) has only the type name to offer
    except Exception:  # graftlint: ignore[unclassified-except]
        return type(exc).__name__.lower()


class _Span:
    """Context manager: profiler trace annotation + registry wall-clock +
    one node of the span tree (obs/tracing.py).

    Exception-safe by contract: a body that raises still records its
    duration, and the span (plus a ``span.errors.{kind}`` counter) is tagged
    with the ``resilience.classify()`` kind of the failure. Under sync mode
    (``RAFT_TPU_OBS_SYNC=1``) the dispatch queue is force-drained at exit so
    ``dur_s`` is committed device-inclusive time, with the raw dispatch
    wall-clock preserved as the ``dispatch_s`` attribute."""

    __slots__ = ("_name", "_reg", "_t0", "_t0_epoch", "_ann", "_attrs",
                 "_ids", "_token")

    def __init__(self, name: str, reg: MetricsRegistry,
                 attrs: Optional[dict] = None):
        self._name = name
        self._reg = reg
        self._attrs = attrs

    def set_attr(self, key: str, value):
        """Attach one typed attribute (rows/probes/tiles/shard …) to the
        span record; chainable. Values discovered mid-body land here."""
        if self._attrs is None:
            self._attrs = {}
        self._attrs[key] = value
        return self

    def __enter__(self):
        ann_cls = _trace_annotation()
        self._ann = ann_cls(self._name) if ann_cls is not None else None
        if self._ann is not None:
            self._ann.__enter__()
        self._ids, self._token = _tracing.enter_span()
        self._t0_epoch = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        dispatch_s = None
        if exc_type is None and _tracing.sync_enabled() and \
                _tracing.drain_device():
            # device-time attribution: the body's wall-clock measured only
            # dispatch; the queue drained, so re-read — dur_s is committed.
            # A failed/no-op drain (no live backend) records NO dispatch_s:
            # the span must not claim attribution it didn't get
            dispatch_s = dt
            dt = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        error = None
        if exc is not None:
            error = _classify_error(exc)
            self._reg.add(f"span.errors.{error}")
        self._reg.record_timing(self._name, dt)
        if dispatch_s is not None and self._name in _DISPATCH_SPANS:
            # sync-mode device-time attribution (round 15): fold the
            # COMMITTED duration into a per-entry histogram — until now
            # it lived only as a span attr, so nothing could aggregate
            # measured device time per dispatch entry. Exemplar-linked to
            # this span's trace (the request-latency convention), so a
            # percentile bucket dereferences to a concrete dispatch.
            # Registered dispatch spans only (see _DISPATCH_SPANS).
            self._reg.observe(f"{DISPATCH_HIST_PREFIX}{self._name}", dt,
                              trace_id=self._ids[0])
        _tracing.exit_span(self._ids, self._token, name=self._name,
                           t0=self._t0_epoch, dur_s=dt, attrs=self._attrs,
                           error=error, dispatch_s=dispatch_s)
        return False


class _NoopSpan:
    """Shared do-nothing span handed out whenever telemetry is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set_attr(self, key, value):
        return self


NOOP_SPAN = _NoopSpan()

_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _default


def record_span(name: str, reg: Optional[MetricsRegistry] = None,
                attrs: Optional[dict] = None):
    """``with obs.record_span("ivf_pq::search"): ...`` — times the block into
    the registry, marks it on the profiler timeline, AND records one node of
    the span tree (parented on the enclosing span via contextvar —
    obs/tracing.py). ``attrs`` attaches typed attributes (rows/probes/tiles/
    shard); hot paths should build the dict inside their existing
    ``if obs.enabled():`` block so the off path allocates nothing. When
    telemetry is disabled this returns the shared :data:`NOOP_SPAN` (no
    allocation, no registry touch)."""
    if not _enabled:
        return NOOP_SPAN
    return _Span(name, reg if reg is not None else _default, attrs)


def add(name: str, value: float = 1) -> None:
    if _enabled:
        _default.add(name, value)


def record_timing(name: str, seconds: float) -> None:
    if _enabled:
        _default.record_timing(name, seconds)


def observe(name: str, value: float, trace_id: Optional[str] = None) -> None:
    if _enabled:
        _default.observe(name, value, trace_id)


def set_gauge(name: str, value: float) -> None:
    """Set a last-value gauge (queue depth, memory watermark, recall
    estimate). Snapshots carry last value + exact min/max/count; the fleet
    merge keeps per-process last values (obs/aggregate)."""
    if _enabled:
        _default.set_gauge(name, value)


def inc_gauge(name: str, delta: float = 1) -> None:
    """Adjust a gauge relative to its current value (inc semantics)."""
    if _enabled:
        _default.inc_gauge(name, delta)


def snapshot() -> dict:
    return _default.snapshot()


def reset() -> None:
    _default.reset()


def export_jsonl(path, extra: Optional[dict] = None) -> dict:
    return _default.export_jsonl(path, extra)
