"""Unified serving status: one JSON snapshot of the whole observability
plane, streamable as JSONL through the crash-safe bench/progress channel.

``collect()`` folds every signal the plane produces into a single
self-describing record — SLO states with dual-window burn rates
(obs/slo.py), queue depth and adaptive batch cap (serving.QueryQueue),
the live shadow-recall estimate ± its Wilson CI (obs/shadow.py), memory
watermarks (obs/memory.py gauges), shard health (resilience), and the
request verdict counters with an explicit ``unclassified`` residue (which
a healthy run keeps at zero). ``export()`` appends it to a JSONL stream
with the heartbeat file's durability (fsync per record, via
``bench/progress.export_metrics`` — the round-5 crash-safety contract), so
a wedged serving process still leaves its last known status on disk.

CLI::

    python -m raft_tpu.obs.report results/obs_report.jsonl   # newest record
    python -m raft_tpu.obs.report path --validate            # health gate

``--validate`` re-checks the structural invariants (:func:`validate`): all
three SLO classes present with finite burn rates, a populated recall
estimate with CI bounds, a nonzero memory watermark, zero unclassified
verdicts — the check.sh obs-report smoke and the driver both gate on it.
With no path the CLI renders the *current process*'s plane (useful inside
a serving process; standalone it is an empty-but-valid skeleton).
"""

from __future__ import annotations

import argparse
import itertools
import json
import math
import os
import sys
import time
from typing import Optional

from raft_tpu import obs, resilience

__all__ = ["SCHEMA_VERSION", "collect", "export", "main", "render",
           "validate"]

#: Record schema stamped by :func:`collect` — :func:`validate` keys its
#: leniency off this field instead of probing section shapes. History:
#: 1 = SLO/recall/queue/memory/shard_health/verdicts (rounds ≤10);
#: 2 = + compile ledger and admission sections (round 11);
#: 3 = + roofline section (round 15);
#: 4 = + capacity section, explicit version + window stamps (round 19);
#: 5 = + maintenance section (round 19 — drift/re-clustering manager);
#: 6 = + tuning section (round 21 — burn-rate controller actions).
#: Records with NO version field are legacy streams: every later section
#: is lenient-on-absence for them, exactly as before the stamp existed.
SCHEMA_VERSION = 6

#: monotonic window id for records collect() stamps itself (a caller-run
#: windowed sampler — obs/flight.py — passes its own instead)
_WINDOWS = itertools.count()

#: verdict counters summarized into the report (everything the queue stamps)
_VERDICT_PREFIX = "serving.requests."


def _compile_summary() -> dict:
    """The compile ledger's report section (lazy import: obs/compile is
    kept off the package import path like the rest of the SLO plane)."""
    from raft_tpu.obs import compile as obs_compile

    return obs_compile.summary(recent=3)


def _admission_counts(counters: dict) -> dict:
    """Verdict counts via the one shared namespace fold (costmodel owns
    the prefix; lazy import as above)."""
    from raft_tpu.obs import costmodel

    return costmodel.admission_counts(counters)


def _roofline_summary(snapshot: dict) -> dict:
    """The roofline plane's report section (round 15; lazy import like the
    rest of the SLO plane): per-entry static FLOP/byte model + measured
    fold + occupancy, against the platform peak table."""
    from raft_tpu.obs import roofline

    return roofline.summary(snapshot=snapshot)


def _classified(fn, label: str, out_errors: dict):
    """Run one provider; a failure degrades its section to None and lands
    classified in ``errors`` — a status report must report, not raise."""
    try:
        return fn()
    except Exception as e:
        out_errors[label] = resilience.classify(e)
        return None


def collect(engine=None, sampler=None, queue=None, capacity=None,
            maintenance=None, controller=None,
            snapshot: Optional[dict] = None,
            extra: Optional[dict] = None,
            window: Optional[int] = None) -> dict:
    """One status snapshot of the observability plane. Every section
    degrades independently (classified into ``errors``) so a broken
    provider never costs the rest of the report. ``capacity`` (round 18)
    is a :class:`raft_tpu.serving.CapacityController`; its per-tenant
    section (tiers, residency bytes, verdict counts, SLO rows, promote
    latency) rides the report and is structurally gated by
    :func:`validate`. Every record is stamped with :data:`SCHEMA_VERSION`
    and a ``window`` id (round 19: the flight recorder passes its own;
    otherwise a process-local counter — a report STREAM is ordered by more
    than wall-clock t)."""
    with obs.record_span("obs.report::collect"):
        errors: dict = {}
        snap = snapshot if snapshot is not None else \
            _classified(obs.snapshot, "snapshot", errors) or {}
        counters = snap.get("counters") or {}
        verdicts = {k[len(_VERDICT_PREFIX):]: v for k, v in counters.items()
                    if k.startswith(_VERDICT_PREFIX)}
        # "rejected" (round 18): the capacity controller's classified
        # admission rejection is a first-class outcome, never residue
        known = {"ok", "deadline", "fatal", "oom", "transient", "rejected"}
        out = {
            "t": round(time.time(), 3),
            "type": "obs_report",
            "schema_version": SCHEMA_VERSION,
            "window": int(window) if window is not None else next(_WINDOWS),
            "slo": (_classified(engine.evaluate, "slo", errors)
                    if engine is not None else {}),
            "recall": (_classified(sampler.estimate, "recall", errors)
                       if sampler is not None else None),
            "queue": (_classified(
                lambda: {"depth": queue.depth,
                         "batch_cap": queue.batch_cap,
                         "batches": queue.batches,
                         "multi_batches": queue.multi_batches,
                         "requeued": int(counters.get(
                             "serving.queue.requeued", 0))},
                "queue", errors) if queue is not None else None),
            "memory": {k: {"value": g.get("value"), "max": g.get("max")}
                       for k, g in (snap.get("gauges") or {}).items()
                       if k.startswith("memory.")},
            # compile ledger (round 11): total traces, per-entry counts,
            # the unexplained residue (zero on a healthy run) and the
            # newest shape-diffed records — "did anything retrace, and
            # which operand caused it" straight from the status snapshot
            "compile": _classified(_compile_summary, "compile", errors),
            # pre-dispatch admission verdict counters (obs/costmodel.py):
            # a healthy over-subscribed plane queues/rejects CLASSIFIED
            # instead of OOMing — these are the counts the item-4
            # controller consumes
            "admission": _classified(
                lambda: _admission_counts(counters), "admission", errors),
            # roofline plane (round 15): per-dispatch FLOP/byte model vs
            # platform peaks + sync-mode measured durations — "is the
            # hardware actually being used" straight from the snapshot
            "roofline": _classified(
                lambda: _roofline_summary(snap), "roofline", errors),
            "shard_health": _classified(
                lambda: resilience.shard_health().snapshot(),
                "shard_health", errors),
            # capacity plane (round 18): per-tenant residency tiers +
            # budget + verdict counts + SLO rows — the multi-tenant
            # chaos rung's acceptance record
            "capacity": (_classified(capacity.report, "capacity", errors)
                         if capacity is not None else None),
            # maintenance plane (schema v5): drift score + incremental
            # re-clustering cycle counts — the always-live index's
            # "is recall holding without a rebuild" record
            "maintenance": (_classified(maintenance.report, "maintenance",
                                        errors)
                            if maintenance is not None else None),
            # tuning plane (schema v6): the burn-rate controller's action
            # ledger — what the online loop DID to the knobs this stream,
            # and where they sit relative to the tuned operating point
            "tuning": (_classified(controller.report, "tuning", errors)
                       if controller is not None else None),
            "verdicts": {
                **verdicts,
                "unclassified": int(sum(
                    v for k, v in verdicts.items() if k not in known)),
            },
        }
        # round id (driver-stamped): lets a multi-round archive key reports
        # without parsing file names
        round_id = os.environ.get("RAFT_TPU_OBS_ROUND", "").strip()
        if round_id:
            out["round"] = round_id
        if errors:
            out["errors"] = errors
        if extra:
            out.update(extra)
        return out


def export(path: str, report: dict) -> dict:
    """Append one report record to a JSONL stream through the crash-safe
    bench/progress channel (fsync per record; the only sanctioned results/
    write path). Returns the record written."""
    # bench/progress is stdlib-only and imports nothing from raft_tpu —
    # reaching it from obs keeps the one fsync'd JSONL writer shared
    from raft_tpu.bench import progress

    return progress.export_metrics(path, report)


def render(report: Optional[dict] = None, indent: int = 2, **providers) -> str:
    """Pretty-printed JSON of ``report`` (default: a fresh
    :func:`collect` over ``providers``)."""
    with obs.record_span("obs.report::render"):
        if report is None:
            report = collect(**providers)
        return json.dumps(report, indent=indent, sort_keys=True,
                          default=str)


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def validate(report: dict,
             require_classes=("latency", "availability", "recall")) -> list:
    """Structural health of one report record: the list of problems (empty
    = valid). Checks the acceptance invariants: every required SLO class
    present with finite burn rates, recall estimate populated with CI
    bounds, a nonzero memory watermark, zero unclassified verdicts.

    Section presence is keyed off the record's ``schema_version`` stamp
    (:data:`SCHEMA_VERSION` history): a version that declares a section
    (compile ≥ 2, roofline ≥ 3) must carry it — either populated or
    degraded-classified in ``errors``. Unversioned records are legacy
    streams and stay lenient on absence."""
    problems = []
    version = report.get("schema_version")
    version = version if isinstance(version, int) else 0
    errors = report.get("errors") or {}
    slo = report.get("slo") or {}
    kinds = {row.get("kind") for row in slo.values()
             if isinstance(row, dict)}
    for cls in require_classes:
        if cls not in kinds:
            problems.append(f"missing SLO class {cls!r} "
                            f"(declared: {sorted(kinds)})")
    for name, row in slo.items():
        if not isinstance(row, dict):
            problems.append(f"slo[{name}] is not a record")
            continue
        if row.get("state") == "unknown":
            problems.append(f"slo[{name}] source failed (state=unknown)")
            continue
        for key in ("burn_fast", "burn_slow"):
            if not _finite(row.get(key)):
                problems.append(f"slo[{name}].{key} not finite: "
                                f"{row.get(key)!r}")
    rec = report.get("recall")
    if "recall" in require_classes:
        if not isinstance(rec, dict) or rec.get("recall") is None:
            problems.append("recall estimate not populated")
        elif not (_finite(rec.get("ci_low")) and _finite(rec.get("ci_high"))
                  and rec["ci_low"] <= rec["recall"] <= rec["ci_high"]):
            problems.append(f"recall CI malformed: {rec!r}")
    mem = report.get("memory") or {}
    if not any(_finite(g.get("value")) and g["value"] > 0
               for g in mem.values() if isinstance(g, dict)):
        problems.append("no nonzero memory watermark recorded")
    verdicts = report.get("verdicts") or {}
    if verdicts.get("unclassified", 0):
        problems.append(
            f"{verdicts['unclassified']} unclassified verdict(s)")
    # compile ledger: every retrace must carry a shape-diff — an
    # unexplained retrace is a zero-recompile-contract violation. Schema
    # v2+ declares the section, so its absence (without a classified
    # degradation) is itself a problem; unversioned legacy streams pass.
    comp = report.get("compile")
    if isinstance(comp, dict) and comp.get("unexplained_retraces", 0):
        problems.append(
            f"{comp['unexplained_retraces']} unexplained retrace(s) "
            f"in the compile ledger")
    elif not isinstance(comp, dict) and version >= 2 \
            and "compile" not in errors:
        problems.append(
            f"schema v{version} record missing its compile section")
    # roofline plane: every noted entry must carry a finite positive byte
    # model, a sane bound verdict, and FLOPs consistent with its own
    # intensity; peaks must state their provenance (a made-up denominator
    # is worse than an unknown one). Schema v3+ declares the section
    # (absence without a classified degradation is a problem); unversioned
    # legacy streams pass.
    roof = report.get("roofline")
    if not isinstance(roof, dict) and version >= 3 \
            and "roofline" not in errors:
        problems.append(
            f"schema v{version} record missing its roofline section")
    if isinstance(roof, dict):
        peaks = roof.get("peaks") or {}
        if peaks.get("source") not in ("env", "table", "unknown"):
            problems.append(
                f"roofline peaks carry no provenance: {peaks!r}")
        for name, row in (roof.get("entries") or {}).items():
            if not isinstance(row, dict):
                problems.append(f"roofline[{name}] is not a record")
                continue
            if not (_finite(row.get("flops")) and row["flops"] >= 0):
                problems.append(f"roofline[{name}].flops not finite: "
                                f"{row.get('flops')!r}")
            if not (_finite(row.get("bytes")) and row["bytes"] > 0):
                problems.append(f"roofline[{name}].bytes not positive: "
                                f"{row.get('bytes')!r}")
            if row.get("bound") not in ("compute", "memory", "unknown"):
                problems.append(f"roofline[{name}].bound invalid: "
                                f"{row.get('bound')!r}")
            if peaks.get("source") == "unknown" and \
                    row.get("bound") != "unknown":
                problems.append(
                    f"roofline[{name}] claims bound={row['bound']!r} "
                    f"with unknown peaks")
    # capacity plane (schema v4): every tenant must sit in a known tier
    # with sane residency accounting, and the budgeter invariant —
    # predicted resident bytes never exceed a known budget — must hold in
    # the snapshot. Lenient on absence at EVERY version: collect() emits
    # None whenever no capacity controller is wired, which is the normal
    # single-tenant shape, not a legacy artifact.
    cap = report.get("capacity")
    if isinstance(cap, dict):
        budget = cap.get("budget_bytes")
        resident = cap.get("resident_bytes")
        if not (_finite(resident) and resident >= 0):
            problems.append(
                f"capacity.resident_bytes not finite: {resident!r}")
        elif _finite(budget) and budget > 0 and resident > budget:
            problems.append(
                f"capacity budgeter overcommitted: resident "
                f"{resident} > budget {budget}")
        for name, row in (cap.get("tenants") or {}).items():
            if not isinstance(row, dict):
                problems.append(f"capacity.tenants[{name}] is not a record")
                continue
            if row.get("tier") not in ("hot", "warm", "cold"):
                problems.append(
                    f"capacity.tenants[{name}].tier invalid: "
                    f"{row.get('tier')!r}")
            if not (_finite(row.get("resident_bytes"))
                    and row["resident_bytes"] >= 0):
                problems.append(
                    f"capacity.tenants[{name}].resident_bytes not "
                    f"finite: {row.get('resident_bytes')!r}")
            if not isinstance(row.get("slo"), dict):
                problems.append(
                    f"capacity.tenants[{name}] carries no SLO row")
    # maintenance plane (schema v5): a populated section must carry a
    # finite non-negative drift score, integral cycle accounting, and a
    # recall record. Lenient on absence at every version (None = no
    # manager wired — the static-index shape), and lenient on SHAPE below
    # v5: an older stream replaying through a newer validator must not
    # fail on a section its writer never promised.
    maint = report.get("maintenance")
    if isinstance(maint, dict) and version >= 5:
        score = maint.get("drift_score")
        if not (_finite(score) and score >= 0):
            problems.append(
                f"maintenance.drift_score not finite: {score!r}")
        for key in ("cycles", "stale_aborts", "failures"):
            v = maint.get(key)
            if not (isinstance(v, int) and v >= 0):
                problems.append(
                    f"maintenance.{key} not a non-negative int: {v!r}")
        if not isinstance(maint.get("recall"), dict):
            problems.append("maintenance section carries no recall record")
    # tuning plane (schema v6): a populated section must carry integral
    # action accounting (actions = nudges + reverts — an action that is
    # neither is an unclassified knob move) and a knob map. Lenient on
    # absence at every version (None = no controller wired — the
    # uncontrolled shape), lenient on SHAPE below v6 like maintenance.
    tun = report.get("tuning")
    if isinstance(tun, dict) and version >= 6:
        for key in ("actions", "nudges", "reverts", "holds", "failures"):
            v = tun.get(key)
            if not (isinstance(v, int) and v >= 0):
                problems.append(
                    f"tuning.{key} not a non-negative int: {v!r}")
        if isinstance(tun.get("actions"), int) \
                and isinstance(tun.get("nudges"), int) \
                and isinstance(tun.get("reverts"), int) \
                and tun["actions"] != tun["nudges"] + tun["reverts"]:
            problems.append(
                f"tuning action ledger inconsistent: actions "
                f"{tun['actions']} != nudges {tun['nudges']} + reverts "
                f"{tun['reverts']}")
        if not isinstance(tun.get("knobs"), dict):
            problems.append("tuning section carries no knob map")
    return problems


def _load_newest(path: str) -> Optional[dict]:
    """Newest obs_report record in a JSONL stream (torn lines skipped —
    the read_progress tolerance)."""
    newest = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and rec.get("type") == "obs_report":
                    if newest is None or rec.get("t", 0) >= newest.get("t", 0):
                        newest = rec
    except OSError:
        return None
    return newest


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs.report",
        description="Render (and optionally validate) one observability-"
                    "plane status snapshot: SLO burn rates, queue depth, "
                    "shadow-recall estimate, memory watermarks, shard "
                    "health.")
    ap.add_argument("path", nargs="?", default=None,
                    help="obs-report JSONL stream (newest record wins); "
                         "omit to collect from the current process")
    ap.add_argument("--validate", action="store_true",
                    help="exit 1 unless the record passes validate()")
    ap.add_argument("--output", default=None, metavar="PATH",
                    help="write the rendered JSON here instead of stdout")
    ap.add_argument("--indent", type=int, default=2)
    args = ap.parse_args(argv)

    if args.path:
        report = _load_newest(args.path)
        if report is None:
            print(f"report: no obs_report records in {args.path}",
                  file=sys.stderr)
            return 2
    else:
        report = collect()
    text = render(report, indent=args.indent)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
            f.flush()
    else:
        print(text)
    if args.validate:
        problems = validate(report)
        if problems:
            for p in problems:
                print(f"report: INVALID: {p}", file=sys.stderr)
            return 1
        print("report: valid", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
