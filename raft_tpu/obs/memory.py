"""Device-memory accounting: HBM watermarks as gauges and span attrs.

The IVF-BQ capacity rung deliberately fills a chip (15.6M resident rows —
ROADMAP item 3), and "Memory Safe Computations with XLA" (PAPERS.md) argues
memory pressure should be *visible* before it is fatal — yet until now the
only memory signal in the repo was the OOM exception itself. This module
turns residency into telemetry:

* :func:`device_stats` — per-device ``bytes_in_use`` / ``peak_bytes_in_use``
  via ``Device.memory_stats()`` (populated on TPU; the CPU backend returns
  nothing);
* :func:`live_bytes` — the CPU fallback: total ``nbytes`` over
  ``jax.live_arrays()`` (every committed array the process still holds);
* :func:`sample` — one watermark snapshot for a named scope, recorded as
  ``memory.<tag>.*`` gauges (obs/registry) and returned as a plain dict the
  caller can attach to its span (``span.set_attr``) or metric line;
* :func:`index_bytes` / :func:`record_index` — per-index residency: the sum
  of array-leaf ``nbytes`` across an index/store's fields, as a
  ``memory.index.<name>.bytes`` gauge.

Never triggers backend init: like ``tracing.process_info``, every jax touch
is gated on an ALREADY-initialized backend (the round-5 wedge class — a
telemetry read must not pay first-touch init), so this module is safe to
call from the report CLI or a jax-free parent; it just answers zeros there.
"""

from __future__ import annotations

import sys

from raft_tpu import obs

__all__ = [
    "device_stats",
    "index_bytes",
    "live_bytes",
    "record_index",
    "sample",
]


def _live_jax():
    """The jax module ONLY when a backend is already initialized (the
    process_info/drain_device contract: never trigger init from telemetry)."""
    jax = sys.modules.get("jax")
    xb = sys.modules.get("jax._src.xla_bridge")
    if jax is None or xb is None or not getattr(xb, "_backends", None):
        return None
    return jax


def device_stats() -> list:
    """Per-device memory stats: ``[{"device", "platform", "bytes_in_use",
    "peak_bytes_in_use"}, ...]`` for every local device that reports them.
    Empty on CPU (the backend has no allocator stats) and when no backend
    is live."""
    jax = _live_jax()
    if jax is None:
        return []
    out = []
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        # a backend without allocator stats is a supported state, not a
        # failure to classify
        except Exception:  # graftlint: ignore[unclassified-except]
            stats = None
        if not stats:
            continue
        row = {
            "device": str(dev.id),
            "platform": dev.platform,
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get(
                "peak_bytes_in_use", stats.get("bytes_in_use", 0))),
        }
        # allocator capacity, where the backend reports one — the HBM
        # budget denominator obs/costmodel.hbm_budget projects against
        if stats.get("bytes_limit"):
            row["bytes_limit"] = int(stats["bytes_limit"])
        out.append(row)
    return out


def live_bytes() -> int:
    """Total bytes of every live committed array in the process — the CPU
    fallback watermark (the CPU allocator exposes no per-device stats).

    Deduplicated by buffer identity (round-11 audit): ``jax.live_arrays()``
    can hand back several Array objects over the SAME device buffer
    (no-copy ``device_put``, donated-buffer aliasing), and summing their
    ``nbytes`` naively double-counts the buffer. Arrays are keyed by
    ``unsafe_buffer_pointer()`` where the runtime provides it (single-shard
    arrays), falling back to object identity — distinct buffers never share
    a pointer, so the dedup can only remove true aliases."""
    jax = _live_jax()
    if jax is None:
        return 0
    total = 0
    seen = set()
    for arr in jax.live_arrays():
        try:
            try:
                key = ("buf", int(arr.unsafe_buffer_pointer()))
            # sharded/committed-elsewhere arrays expose no single buffer
            # pointer — object identity is the conservative fallback
            # (never merges distinct buffers)
            except Exception:  # graftlint: ignore[unclassified-except]
                key = ("obj", id(arr))
            if key in seen:
                continue
            seen.add(key)
            total += int(arr.nbytes)
        # a deleted-buffer race during iteration must not fail a
        # watermark read
        except Exception:  # graftlint: ignore[unclassified-except,swallowed-exception]
            pass
    return total


def sample(tag: str) -> dict:
    """One memory watermark for scope ``tag`` (a bench section, an index
    name, "serving"): ``{"source", "bytes_in_use", "peak_bytes_in_use",
    "per_device"?}``. Source is ``"device_stats"`` when the backend reports
    allocator stats (TPU) and ``"live_arrays"`` otherwise (CPU). Recorded
    as ``memory.<tag>.bytes_in_use`` / ``.peak_bytes`` gauges; the returned
    dict is what callers attach as span attrs."""
    with obs.record_span("obs.memory::sample", attrs={"tag": tag}):
        per_dev = device_stats()
        if per_dev:
            out = {
                "source": "device_stats",
                "bytes_in_use": sum(d["bytes_in_use"] for d in per_dev),
                "peak_bytes_in_use": sum(
                    d["peak_bytes_in_use"] for d in per_dev),
                "per_device": per_dev,
            }
        else:
            b = live_bytes()
            out = {"source": "live_arrays", "bytes_in_use": b,
                   "peak_bytes_in_use": b}
        if obs.enabled():
            obs.set_gauge(f"memory.{tag}.bytes_in_use", out["bytes_in_use"])
            obs.set_gauge(f"memory.{tag}.peak_bytes",
                          out["peak_bytes_in_use"])
        return out


def index_bytes(index) -> int:
    """Resident bytes of one index/store: the sum of ``nbytes`` over its
    array-valued fields (dataclass fields, __dict__ and __slots__ entries,
    one level deep — the layout every index in this repo uses)."""
    total = 0
    seen = set()
    fields = {}
    for src in (getattr(index, "__dict__", None),):
        if src:
            fields.update(src)
    for name in getattr(type(index), "__dataclass_fields__", ()) or ():
        fields.setdefault(name, getattr(index, name, None))
    for slot in getattr(type(index), "__slots__", ()) or ():
        fields.setdefault(slot, getattr(index, slot, None))
    for val in fields.values():
        nbytes = getattr(val, "nbytes", None)
        if isinstance(nbytes, int) and id(val) not in seen:
            seen.add(id(val))
            total += nbytes
    return total


def record_index(name: str, index) -> int:
    """Record ``index``'s residency as the ``memory.index.<name>.bytes``
    gauge; returns the byte count (0 for array-free objects)."""
    b = index_bytes(index)
    if obs.enabled():
        obs.set_gauge(f"memory.index.{name}.bytes", b)
    return b
