"""Repo-wide compile ledger: every (re)trace recorded with shape provenance.

The zero-recompile serving contract — the load-bearing invariant of the
paged stores and the ``QueryQueue`` — used to be enforced by scattered
ad-hoc trace counters (``_packing.PAGED_TRACES``, ``ivf_bq._BQ_TRACES``):
they could say *how many* retraces a window paid, but not *which operand
shape caused one*. A mid-traffic retrace (the ``reserve()`` headroom
failure mode the round-8 bench caught) shipped as an unexplained number.

This module replaces the counters with one process-wide **ledger**: every
registered jit entry point calls :func:`trace_event` at the top of its
jitted body — host code that runs at TRACE time only, exactly like the old
counter bumps — and each trace lands as a record carrying

* the entry name and a per-entry sequence number,
* every operand's shape/dtype signature (tracer avals) plus the static
  arguments that participate in the jit cache key,
* a **diff against the previous trace of that entry** — which operands
  changed, from what to what — so a growth retrace reads "``table``
  widened ``i32[16,4]`` → ``i32[16,8]``", not "count went up",
* the ambient ``trace_id`` (obs.tracing), linking the retrace to the
  request/span that paid it,
* and, when the dispatch site wraps itself in :func:`watch`, the host
  wall-clock of the dispatch that traced (trace + compile + first run —
  the latency a mid-traffic retrace actually costs).

A retrace whose signature did NOT change (same shapes, same statics, yet
traced again — jit cache eviction, a fresh jit object) is **unexplained**;
:func:`unexplained_retraces` counts them and the bench/check smokes gate
the count at zero. "recompiles_during_search == 0" claims become "zero
retraces, and here is the shape-diff for each one that ever happened".

The ledger is a bounded ring (``RAFT_TPU_OBS_LEDGER_CAP``, default 512
records) and records **unconditionally** — the zero-recompile tier-1
assertions run with telemetry off, so counting cannot ride the
``obs.enabled()`` gate; only the derived counters/gauges do. Per-entry
counts survive ring eviction (they are a separate map), so
:func:`trace_count` deltas stay exact over arbitrarily long windows.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Optional

# the obs package re-exports `registry` as a FUNCTION, so the submodule
# must be imported by its dotted path
from raft_tpu.obs import tracing as _tracing
from raft_tpu.obs.registry import add as _metric_add
from raft_tpu.obs.registry import enabled as _metrics_enabled
from raft_tpu.obs.registry import record_span

__all__ = [
    "LEDGER_CAP_ENV",
    "entries",
    "ledger",
    "reset",
    "set_ledger_cap",
    "summary",
    "suppress_analysis",
    "trace_count",
    "trace_event",
    "unexplained_retraces",
    "watch",
]

LEDGER_CAP_ENV = "RAFT_TPU_OBS_LEDGER_CAP"
_DEFAULT_CAP = 512


def _ledger_cap() -> int:
    raw = os.environ.get(LEDGER_CAP_ENV, "").strip()
    if raw.isdigit() and int(raw) > 0:
        return int(raw)
    return _DEFAULT_CAP


_LOCK = threading.Lock()
_LEDGER: deque = deque(maxlen=_ledger_cap())
_COUNTS: dict = {}      # entry -> traces ever (survives ring eviction)
_LAST_SIG: dict = {}    # entry -> {operand: signature str}
_UNEXPLAINED = {"count": 0}  # retraces with an empty diff, ever

# analysis-only lowerings (costmodel.xla_memory_analysis re-lowers a
# registered entry's body to ask the COMPILER for its byte accounting)
# must not land in the ledger: the signature is unchanged by construction,
# so recording would fabricate an "unexplained retrace" and corrupt the
# zero-recompile trace-count deltas. Thread-local: a concurrent dispatch
# on another thread keeps recording normally.
_SUPPRESS = threading.local()


def set_ledger_cap(cap: int) -> None:
    """Resize the ledger ring at runtime (newest records kept) — the
    ``RAFT_TPU_OBS_LEDGER_CAP`` env var is read once at import, like the
    span ring's cap."""
    global _LEDGER
    with _LOCK:
        _LEDGER = deque(_LEDGER, maxlen=max(1, int(cap)))


def _sig_of(value) -> str:
    """``dtype[d0,d1,...]`` signature of one operand (tracers and concrete
    arrays both answer shape/dtype); ``none`` for absent optionals. A
    container operand (a Bitset filter, any pytree) flattens to its leaf
    signatures — its repr would embed tracer identities that differ
    between otherwise-identical traces and fake a shape diff. Plain
    Python values fall back to repr."""
    if value is None:
        return "none"
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(int(d)) for d in shape)}]"
    # sys.modules lookup, never an import: a signature read must not pull
    # (or first-touch-init) jax — the tracing.process_info contract
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            leaves = jax.tree_util.tree_leaves(value)
            if leaves and any(getattr(lf, "shape", None) is not None
                              for lf in leaves):
                inner = "/".join(_sig_of(lf) for lf in leaves)
                return f"{type(value).__name__}({inner})"
        # an unflattenable value is signed by its repr below — the
        # signature is provenance decoration, never a failure class
        except Exception:  # graftlint: ignore[unclassified-except,swallowed-exception]
            pass
    return repr(value)


def _diff(prev: dict, cur: dict) -> list:
    """Operand-level provenance: which operands changed between two traces
    of the same entry (``from`` None = operand is new, ``to`` None =
    operand gone)."""
    out = []
    for name in list(prev) + [n for n in cur if n not in prev]:
        a, b = prev.get(name), cur.get(name)
        if a != b:
            out.append({"operand": name, "from": a, "to": b})
    return out


def trace_event(entry: str, static: Optional[dict] = None,
                **operands) -> None:
    """Record one trace of ``entry``. Call at the TOP of a jitted body —
    it runs at trace time only (the ``PAGED_TRACES`` pattern), so a delta
    of :func:`trace_count` over a serving window counts recompiles.

    ``operands`` are the jit function's array arguments (tracers are
    fine — only shape/dtype are read); ``static`` carries the static
    arguments that participate in the cache key, so a retrace caused by a
    static flip (new ``k``, new ``n_probes``) is attributed too.
    """
    if getattr(_SUPPRESS, "on", False):
        return  # analysis-only lowering (see suppress_analysis)
    sig = {name: _sig_of(v) for name, v in operands.items()}
    if static:
        for key, v in static.items():
            sig[f"static.{key}"] = repr(v)
    cur = _tracing.current_span()
    rec = {
        "entry": entry,
        "t": round(time.time(), 3),
        "shapes": sig,
        "trace_id": cur[0] if cur is not None else None,
        # tracing thread: watch() stamps wall-clock only onto records its
        # OWN thread traced (a shadow-thread retrace inside another
        # dispatch's window must not inherit that dispatch's duration)
        "tid": threading.get_ident(),
    }
    with _LOCK:
        prev = _LAST_SIG.get(entry)
        seq = _COUNTS.get(entry, 0) + 1
        _COUNTS[entry] = seq
        _LAST_SIG[entry] = sig
        rec["seq"] = seq
        rec["first"] = prev is None
        rec["changed"] = [] if prev is None else _diff(prev, sig)
        if prev is not None and not rec["changed"]:
            _UNEXPLAINED["count"] += 1
            rec["unexplained"] = True
        _LEDGER.append(rec)
    if _metrics_enabled():
        _metric_add(f"compile.traces.{entry}")
        if rec.get("unexplained"):
            _metric_add("compile.unexplained_retraces")


class _Watch:
    """Context manager stamping the dispatch wall-clock onto any ledger
    records created inside it — the host-observed cost of the call that
    (re)traced. Steady-state dispatches create no records and stamp
    nothing; nested watches stamp innermost-first (already-stamped records
    are left alone). New records are detected by the TOTAL trace count,
    not the ring length — a ring already at capacity keeps its length
    constant while still appending, and the stamp must survive that.
    Only records traced by THIS thread are stamped: another thread's
    concurrent retrace (the shadow sampler re-tracing inside a queue
    dispatch's window) carries its own cost, not this dispatch's."""

    __slots__ = ("_t0", "_c0", "_tid")

    def __enter__(self):
        self._tid = threading.get_ident()
        with _LOCK:
            self._c0 = sum(_COUNTS.values())
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        with _LOCK:
            new = sum(_COUNTS.values()) - self._c0
            if new > 0:
                # the newest `new` records are the window's (ring eviction
                # can only have dropped OLDER ones); stamp own-thread only
                for rec in list(_LEDGER)[-min(new, len(_LEDGER)):]:
                    if rec.get("tid") == self._tid:
                        rec.setdefault("wall_s", round(dt, 6))
        return False


def watch() -> _Watch:
    """``with compile.watch(): jitted(...)`` around a dispatch site —
    records that trace inside the block gain ``wall_s``, the wall-clock of
    the dispatch that paid the compile."""
    return _Watch()


class _SuppressAnalysis:
    """Ledger mute for analysis-only lowerings on THIS thread (re-entrant:
    nesting keeps the outermost scope's restore value)."""

    __slots__ = ("_prev",)

    def __enter__(self):
        self._prev = getattr(_SUPPRESS, "on", False)
        _SUPPRESS.on = True
        return self

    def __exit__(self, exc_type, exc, tb):
        _SUPPRESS.on = self._prev
        return False


def suppress_analysis() -> _SuppressAnalysis:
    """``with compile.suppress_analysis(): jitted.lower(...)`` around a
    lowering done to ANALYZE a program, not to run it
    (``costmodel.xla_memory_analysis``): the re-trace's signature is
    unchanged by construction, so letting it record would fabricate an
    unexplained retrace and inflate the zero-recompile trace-count deltas
    the shims assert on. Thread-local — concurrent real dispatches keep
    recording."""
    return _SuppressAnalysis()


def trace_count(entry: Optional[str] = None, prefix: Optional[str] = None) -> int:
    """Traces ever recorded: for one ``entry``, for every entry under a
    ``prefix``, or in total. Exact over ring eviction (counts live in
    their own map). This is what the zero-recompile shims
    (``serving.scan_trace_count`` / ``ivf_bq.scan_trace_count``) delta."""
    with _LOCK:
        if entry is not None:
            return _COUNTS.get(entry, 0)
        if prefix is not None:
            return sum(v for k, v in _COUNTS.items() if k.startswith(prefix))
        return sum(_COUNTS.values())


def unexplained_retraces() -> int:
    """Retraces whose operand/static signature did not change — every one
    of these is a contract violation to chase (jit cache eviction, a fresh
    jit object per call, a non-hashable static). Zero on a healthy run."""
    with _LOCK:
        return _UNEXPLAINED["count"]


def entries() -> dict:
    """{entry: trace count} for every entry point that ever traced."""
    with _LOCK:
        return dict(_COUNTS)


def ledger(entry: Optional[str] = None, prefix: Optional[str] = None) -> list:
    """Snapshot of the ledger ring, oldest first; optionally filtered to
    one entry or an entry-name prefix."""
    with _LOCK:
        recs = list(_LEDGER)
    if entry is not None:
        recs = [r for r in recs if r["entry"] == entry]
    if prefix is not None:
        recs = [r for r in recs if r["entry"].startswith(prefix)]
    return recs


def reset() -> None:
    """Clear the ledger, counts and signatures (tests)."""
    with _LOCK:
        _LEDGER.clear()
        _COUNTS.clear()
        _LAST_SIG.clear()
        _UNEXPLAINED["count"] = 0


def summary(recent: int = 5) -> dict:
    """One report-ready section: total traces, per-entry counts, the
    unexplained residue, and the newest ``recent`` records (shape diffs
    included) — what ``obs.report.collect`` folds in, so a status snapshot
    answers "did anything retrace, and why" directly."""
    with record_span("obs.compile::summary"), _LOCK:
        # recent <= 0 means NO records ([-0:] would invert to ALL of them)
        recent = int(recent)
        recs = list(_LEDGER)[-recent:] if recent > 0 else []
        return {
            "total_traces": sum(_COUNTS.values()),
            "entries": dict(_COUNTS),
            "unexplained_retraces": _UNEXPLAINED["count"],
            "recent": [dict(r) for r in recs],
        }
