"""SLO engine: objectives + sliding-window error-budget burn rates.

Rounds 6–9 gave the serving layer latency histograms, verdict counters and
a live recall sampler — raw signals with no *objective* rolled on top, so
"is the service healthy right now?" had no mechanical answer. This module
is the rollup: declare objectives over the three serving SLO classes —

* **latency** — "p-quantile ≤ target seconds", scored from an existing
  ``_HistStat`` power-of-two histogram (a bucket whose upper bound exceeds
  the target counts as a violation — the same conservative upper-bound
  convention as ``p99_ub``); the error budget is ``1 − quantile``;
* **availability** — "fraction of non-error verdicts ≥ target", scored
  from the ``QueryQueue`` verdict counters (``serving.requests.ok`` vs the
  classified failure kinds). Verdict counters fire exactly once per
  request, so requeued-once survivors (OOM cap-halving, partial deadline
  drains — the ``serving.queue.requeued`` counter) never double-count
  their first admission;
* **recall** — "live recall@k ≥ floor", scored from the shadow sampler's
  cumulative ``(matched, total)`` slot counts (obs/shadow.py).

Burn rate is the SRE error-budget formulation: ``bad_rate / budget`` over
a window — burn 1.0 spends the budget exactly at the objective's rate,
burn N spends it N× too fast. The engine keeps a ring of cumulative
samples and evaluates **dual windows** (fast = ``RAFT_TPU_OBS_BURN_FAST_S``,
slow = ``RAFT_TPU_OBS_BURN_SLOW_S``): a breach requires BOTH windows above
the threshold (fast-only is "warn"), which filters blips without missing
sustained burns. Windows older than the engine degrade to since-start.

Failure contract: burn-rate breaches emit **classified events** through
the resilience ring (``slo_breach``) plus ``slo.breach.*`` counters —
never exceptions; a broken signal source degrades that one objective to
``state="unknown"`` with its ``resilience.classify`` kind while the rest
keep evaluating.

This is the operating-point record ROADMAP item 5's closed-loop autotuner
consumes: each :meth:`SloEngine.evaluate` result pairs a configuration's
measured burn rates with its live recall estimate.
"""

from __future__ import annotations

import math
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

from raft_tpu import obs, resilience
from raft_tpu.resilience.retry import record_event

__all__ = [
    "AVAILABILITY",
    "FAST_WINDOW_ENV",
    "LATENCY",
    "RECALL",
    "SLOW_WINDOW_ENV",
    "Slo",
    "SloEngine",
    "THRESHOLD_ENV",
    "availability_slo",
    "default_serving_slos",
    "latency_slo",
    "recall_slo",
]

LATENCY = "latency"
AVAILABILITY = "availability"
RECALL = "recall"

FAST_WINDOW_ENV = "RAFT_TPU_OBS_BURN_FAST_S"
SLOW_WINDOW_ENV = "RAFT_TPU_OBS_BURN_SLOW_S"
THRESHOLD_ENV = "RAFT_TPU_OBS_BURN_THRESHOLD"

#: verdict counters that are NOT availability errors (DEADLINE verdicts are
#: counted against availability: a deadline miss is a failed request from
#: the caller's seat, which is what the availability SLO promises about)
_DEFAULT_GOOD = "serving.requests.ok"
_DEFAULT_BAD = ("serving.requests.deadline", "serving.requests.fatal",
                "serving.requests.oom", "serving.requests.transient")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


@dataclass(frozen=True)
class Slo:
    """One declared objective. Use the :func:`latency_slo` /
    :func:`availability_slo` / :func:`recall_slo` constructors — they pick
    the right ``kind``/``budget`` pairing and validate it."""

    name: str
    kind: str  # LATENCY | AVAILABILITY | RECALL
    target: float          # latency: seconds bound; others: min fraction
    budget: float          # allowed bad fraction (> 0, the burn denominator)
    hist: str = ""                                 # latency source
    good_counter: str = _DEFAULT_GOOD              # availability source
    bad_counters: Tuple[str, ...] = _DEFAULT_BAD   # availability source
    counts: Optional[Callable] = None              # recall source

    def __post_init__(self):
        if self.kind not in (LATENCY, AVAILABILITY, RECALL):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not (0.0 < self.budget <= 1.0):
            raise ValueError(
                f"SLO {self.name!r}: budget must be in (0, 1], got "
                f"{self.budget} — a zero budget makes every burn infinite")


def latency_slo(name: str, hist: str, target_s: float,
                quantile: float = 0.99) -> Slo:
    """"``quantile`` of ``hist`` observations ≤ ``target_s``" — e.g. p99
    of ``serving.request_latency_s`` under the serving SLO."""
    if not (0.0 < quantile < 1.0):
        raise ValueError(f"quantile must be in (0, 1), got {quantile}")
    return Slo(name=name, kind=LATENCY, target=float(target_s),
               budget=1.0 - quantile, hist=hist)


def availability_slo(name: str, target: float = 0.999,
                     good_counter: str = _DEFAULT_GOOD,
                     bad_counters: Tuple[str, ...] = _DEFAULT_BAD) -> Slo:
    """"fraction of ok verdicts ≥ ``target``" over the once-per-request
    verdict counters."""
    if not (0.0 < target < 1.0):
        raise ValueError(f"availability target must be in (0, 1), "
                         f"got {target}")
    return Slo(name=name, kind=AVAILABILITY, target=float(target),
               budget=1.0 - target, good_counter=good_counter,
               bad_counters=tuple(bad_counters))


def recall_slo(name: str, counts: Callable, floor: float = 0.95) -> Slo:
    """"live recall@k ≥ ``floor``" over ``counts() -> (matched, total)``
    (a :meth:`~raft_tpu.obs.shadow.ShadowSampler.counts` bound method)."""
    if not (0.0 < floor < 1.0):
        raise ValueError(f"recall floor must be in (0, 1), got {floor}")
    return Slo(name=name, kind=RECALL, target=float(floor),
               budget=1.0 - floor, counts=counts)


def default_serving_slos(target_p99_s: float, sampler=None,
                         availability_target: float = 0.999,
                         recall_floor: float = 0.95) -> tuple:
    """The serving layer's three-class objective set: p99 latency over
    ``serving.request_latency_s``, availability over the verdict counters,
    and (when a shadow ``sampler`` is wired) the live recall floor."""
    slos = [
        latency_slo("serving_p99", "serving.request_latency_s",
                    target_s=target_p99_s, quantile=0.99),
        availability_slo("serving_availability",
                         target=availability_target),
    ]
    if sampler is not None:
        slos.append(recall_slo("serving_recall", sampler.counts,
                               floor=recall_floor))
    return tuple(slos)


def _hist_good_bad(snap: dict, hist: str, target_s: float) -> tuple:
    """(good, bad) cumulative counts from a pow2 histogram: a bucket whose
    upper bound exceeds the target MAY hold violations — counted bad, the
    ≤2× conservative convention shared with ``p99_ub``."""
    h = (snap.get("histograms") or {}).get(hist) or {}
    total = int(h.get("count", 0))
    bad = 0
    for key, n in (h.get("buckets") or {}).items():
        try:
            bound = float(str(key)[3:])
        except (ValueError, IndexError):
            continue
        if bound > target_s:
            bad += int(n)
    return total - bad, bad


class SloEngine:
    """Cumulative-sample ring + dual-window burn-rate evaluation over a
    set of :class:`Slo` objectives.

    ``clock`` is injectable (tests drive synthetic timelines); windows and
    the breach threshold come from the ``RAFT_TPU_OBS_BURN_*`` env knobs
    unless given explicitly.
    """

    def __init__(self, slos, *, registry=None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 threshold: Optional[float] = None,
                 clock: Callable = time.monotonic):
        self.slos = tuple(slos)
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._registry = registry
        self.fast_window_s = (_env_float(FAST_WINDOW_ENV, 60.0)
                              if fast_window_s is None else
                              float(fast_window_s))
        self.slow_window_s = (_env_float(SLOW_WINDOW_ENV, 600.0)
                              if slow_window_s is None else
                              float(slow_window_s))
        self.threshold = (_env_float(THRESHOLD_ENV, 10.0)
                          if threshold is None else float(threshold))
        self._clock = clock
        self._samples: deque = deque(maxlen=4096)
        self._last_state = {s.name: "ok" for s in self.slos}
        # baseline sample at construction: burn rates answer "since when?",
        # and for a fresh engine the honest answer is "since it started
        # watching" — without this, traffic that predates the engine would
        # either vanish (zero delta) or be blamed on the first window
        self.sample()

    def _snapshot(self) -> dict:
        reg = self._registry if self._registry is not None else \
            obs.registry()
        return reg.snapshot()

    def _good_bad(self, slo: Slo, snap: dict) -> tuple:
        if slo.kind == LATENCY:
            return _hist_good_bad(snap, slo.hist, slo.target)
        if slo.kind == AVAILABILITY:
            counters = snap.get("counters") or {}
            good = int(counters.get(slo.good_counter, 0))
            bad = int(sum(counters.get(c, 0) for c in slo.bad_counters))
            return good, bad
        matched, total = slo.counts()  # RECALL
        return int(matched), int(total) - int(matched)

    # -- sampling -----------------------------------------------------------
    def sample(self, snapshot: Optional[dict] = None,
               now: Optional[float] = None) -> dict:
        """Append one cumulative ``(good, bad)`` sample per objective to
        the window ring (call periodically — each serving window boundary,
        each bench load step). Returns the appended sample. A failing
        source records zeros for its objective, classified, and never
        raises (hot-path contract)."""
        with obs.record_span("obs.slo::sample"):
            now = self._clock() if now is None else now
            snap = self._snapshot() if snapshot is None else snapshot
            cum = {}
            for slo in self.slos:
                try:
                    cum[slo.name] = self._good_bad(slo, snap)
                except Exception as e:
                    kind = resilience.classify(e)
                    record_event("slo_source_error", site=slo.name,
                                 kind=kind, error=repr(e)[:200])
                    if obs.enabled():
                        obs.add(f"slo.source_error.{kind}")
                    cum[slo.name] = None
            rec = {"t": now, "cum": cum}
            self._samples.append(rec)
            return rec

    def _window_delta(self, name: str, now: float, window_s: float,
                      newest) -> tuple:
        """(Δgood, Δbad) between the newest sample and the sample CLOSEST
        to the window start ``now − window_s`` (ties prefer the earlier
        sample). For an engine younger than the window this degrades to
        since-start; a sparse ring picks the nearest cumulative point
        rather than silently stretching the window to the whole history —
        which would dilute exactly the fast-window bursts dual-window
        alerting exists to catch. The newest sample itself is never the
        baseline (unless it is the ONLY sample): when sampling is sparser
        than the window, self-as-baseline would collapse every burn to 0
        and a sustained 100% failure rate could never breach."""
        t_start = now - window_s
        base = fallback = None
        best = math.inf
        for rec in self._samples:
            cum = rec["cum"].get(name)
            if cum is None:
                continue
            if cum is newest:
                fallback = cum  # sole-sample case only
                continue
            dist = abs(rec["t"] - t_start)
            if dist < best:
                best = dist
                base = cum
        if base is None:
            base = fallback
        if base is None or newest is None:
            return 0, 0
        return newest[0] - base[0], newest[1] - base[1]

    # -- evaluation ---------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> dict:
        """Sample, then score every objective: ``{name: {"kind", "target",
        "value", "good", "bad", "burn_fast", "burn_slow", "burn_rate",
        "state"}}``. Burn rates are always finite (no traffic ⇒ 0.0);
        ``state`` is ``ok`` / ``warn`` (fast window burning) / ``breach``
        (BOTH windows above threshold — emits a classified ``slo_breach``
        event + counter on the transition) / ``unknown`` (source failed).
        Never raises."""
        with obs.record_span("obs.slo::evaluate"):
            now = self._clock() if now is None else now
            sampled = self.sample(now=now)
            out = {}
            for slo in self.slos:
                newest = sampled["cum"].get(slo.name)
                if newest is None:
                    out[slo.name] = {"kind": slo.kind, "target": slo.target,
                                     "state": "unknown"}
                    self._last_state[slo.name] = "unknown"
                    continue
                good, bad = newest
                total = good + bad
                burns = {}
                for label, win in (("burn_fast", self.fast_window_s),
                                   ("burn_slow", self.slow_window_s)):
                    dg, db = self._window_delta(slo.name, now, win, newest)
                    dt_total = dg + db
                    bad_rate = db / dt_total if dt_total > 0 else 0.0
                    burns[label] = bad_rate / slo.budget
                state = "ok"
                if burns["burn_fast"] > self.threshold:
                    state = ("breach"
                             if burns["burn_slow"] > self.threshold
                             else "warn")
                row = {
                    "kind": slo.kind,
                    "target": slo.target,
                    "budget": slo.budget,
                    "good": good,
                    "bad": bad,
                    "value": (good / total) if total else None,
                    "burn_fast": burns["burn_fast"],
                    "burn_slow": burns["burn_slow"],
                    # the headline single number: the fast window
                    "burn_rate": burns["burn_fast"],
                    "state": state,
                }
                # counter + event fire on the TRANSITION into breach, so
                # the count means breach episodes, not polling frequency
                if state == "breach" and \
                        self._last_state[slo.name] != "breach":
                    if obs.enabled():
                        obs.add(f"slo.breach.{slo.name}")
                    record_event(
                        "slo_breach", site=slo.name, kind=slo.kind,
                        burn_fast=round(burns["burn_fast"], 3),
                        burn_slow=round(burns["burn_slow"], 3),
                        target=slo.target)
                self._last_state[slo.name] = state
                out[slo.name] = row
            return out
