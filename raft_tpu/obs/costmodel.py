"""Static HBM footprint prediction + pre-dispatch admission gauges.

ROADMAP item 4 names its prerequisite outright: "static HBM-footprint
prediction for a search/build dispatch before it runs" — today the only
memory policy is OOM-then-halve after the fact. The "Memory Safe
Computations with XLA" line (PAPERS.md) shows per-program cost accounting
is tractable precisely because this repo's shapes are **capacity-padded
and enumerable**: every scan operand's shape derives from layout
parameters known on the host (n_lists, max_list_size, page capacity,
table width), never from data. So the footprint of a dispatch is a sum of
closed-form terms, computed before anything touches the device:

* :func:`predict_index_bytes` — resident bytes of an index from its
  layout parameters alone, for the five index families (brute_force /
  ivf_flat / ivf_pq / ivf_bq / cagra) plus the serving
  ``PagedListStore``. EXACT against ``obs.memory.index_bytes`` of the
  built artifact (tier-1 property-tested): the formula IS the field
  layout.
* :func:`estimate` — one dispatch's operand + output + workspace byte
  accounting per registered jit entry point, using the same
  ``per_query``/``q_tile`` workspace-budget arithmetic the dispatch sites
  themselves use; :func:`estimate_search` builds the kwargs from a live
  index/store.
* :func:`xla_memory_analysis` — the compiler cross-check: where the
  backend provides ``lowered.compile().memory_analysis()`` (or
  ``cost_analysis``), returns XLA's own argument/output/temp byte counts
  to validate the static model against (None, classified, where the
  backend doesn't).
* :func:`check_admission` — the pre-dispatch hook: compares a predicted
  footprint against the live ``memory.*`` watermark (obs/memory.py) and
  an HBM budget (``Device.memory_stats()['bytes_limit']`` on TPU,
  ``RAFT_TPU_OBS_HBM_BYTES`` override elsewhere), returning a classified
  ``ADMIT`` / ``QUEUE`` / ``REJECT`` verdict record. Gauges and events
  only — never a hot-path exception (the obs/slo.py posture); the item-4
  admission controller is the consumer that will act on the verdicts.

Admission thresholds ride env knobs: a projected footprint under
``RAFT_TPU_OBS_ADMIT_SOFT`` (default 0.85) of budget ADMITs, under
``RAFT_TPU_OBS_ADMIT_HARD`` (default 0.97) QUEUEs, above it REJECTs.
With no budget discoverable the verdict is ADMIT with
``budget_source="unknown"`` — prediction without a denominator is still a
gauge, not a guess at a verdict.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from raft_tpu import obs
from raft_tpu.obs import compile as obs_compile
from raft_tpu.obs import memory as obs_memory

__all__ = [
    "ADMIT",
    "HARD_ENV",
    "HBM_ENV",
    "QUEUE",
    "REJECT",
    "SOFT_ENV",
    "admission_counts",
    "check_admission",
    "estimate",
    "estimate_search",
    "hbm_budget",
    "index_layout",
    "paged_scan_estimator",
    "predict_index_bytes",
    "xla_memory_analysis",
]

ADMIT, QUEUE, REJECT = "admit", "queue", "reject"

#: counter namespace every verdict lands under (obs registry); consumers
#: fold it back out with :func:`admission_counts`
ADMISSION_COUNTER_PREFIX = "costmodel.admission."

HBM_ENV = "RAFT_TPU_OBS_HBM_BYTES"
SOFT_ENV = "RAFT_TPU_OBS_ADMIT_SOFT"
HARD_ENV = "RAFT_TPU_OBS_ADMIT_HARD"


def _frac(env: str, default: float) -> float:
    raw = os.environ.get(env, "").strip()
    try:
        v = float(raw) if raw else default
    except ValueError:
        v = default
    return min(max(v, 0.0), 1.0)


def _isize(dtype) -> int:
    return int(np.dtype(dtype).itemsize)


# ---------------------------------------------------------------------------
# resident-index prediction (the five families + the paged store)
# ---------------------------------------------------------------------------


def _predict_brute_force(*, n: int, dim: int, dtype="float32",
                         norms: bool = True) -> int:
    total = n * dim * _isize(dtype)
    if norms:
        total += n * 4
    return total


def _predict_ivf_flat(*, n_lists: int, dim: int, max_list_size: int,
                      dtype="float32", norms: bool = True,
                      plan_cache: bool = False) -> int:
    total = n_lists * dim * 4                                # centers
    total += n_lists * max_list_size * dim * _isize(dtype)   # list_data
    total += n_lists * max_list_size * 4                     # list_ids
    if norms:
        total += n_lists * max_list_size * 4                 # list_norms
    if plan_cache:
        total += n_lists * 4     # _lens_np_cache (first ragged-plan search)
    return total


def _predict_ivf_pq(*, n_lists: int, dim: int, max_list_size: int,
                    pq_dim: int, pq_bits: int = 8,
                    rot_dim: Optional[int] = None,
                    codebook_kind: str = "subspace",
                    decoded: bool = False,
                    plan_cache: bool = False) -> int:
    if rot_dim is None:
        rot_dim = pq_dim * (-(-dim // pq_dim))
    dsub = rot_dim // pq_dim
    n_codes = 1 << pq_bits
    code_width = (pq_dim * pq_bits + 7) // 8
    total = n_lists * dim * 4                                # centers
    total += rot_dim * rot_dim * 4                           # rotation
    cb_rows = n_lists if codebook_kind == "cluster" else pq_dim
    total += cb_rows * n_codes * dsub * 4                    # codebooks
    total += n_lists * max_list_size * code_width            # list_codes
    total += n_lists * max_list_size * 4                     # list_ids
    total += n_lists * max_list_size * 4                     # b_sum
    if decoded:
        total += n_lists * max_list_size * rot_dim + 4       # int8 + scale
    if plan_cache:
        total += n_lists * 4     # _lens_np_cache (first ragged-plan search)
    return total


def _rotation_bytes(rot_dim: int, rotation_kind: str) -> int:
    """Resident bytes of the rotation operand: the dense (rot_dim, rot_dim)
    fp32 matrix, or the SRHT (rot_dim,) fp32 sign diagonal — the 1/d
    storage side of the Hadamard rotation's O(d·log d) apply."""
    if rotation_kind == "hadamard":
        return rot_dim * 4
    return rot_dim * rot_dim * 4


def _auto_rot_dim_bq(dim: int, rotation_kind: str) -> int:
    """ivf_bq.auto_rot_dim mirrored (kind-aware): whole code bytes for
    dense, the next power of two for the Walsh–Hadamard butterfly — the
    kinds disagree (dim=100 → 104 vs 128), so a kind-blind default would
    under-predict every hadamard byte count."""
    if rotation_kind == "hadamard":
        d = max(int(dim), 1)
        return max(8, 1 << (d - 1).bit_length())
    return -(-int(dim) // 8) * 8


def _predict_ivf_bq(*, n_lists: int, dim: int, max_list_size: int,
                    rot_dim: Optional[int] = None, bits: int = 1,
                    rotation_kind: str = "dense",
                    plan_cache: bool = False) -> int:
    if rot_dim is None:
        rot_dim = _auto_rot_dim_bq(dim, rotation_kind)
    total = n_lists * dim * 4                                # centers
    total += _rotation_bytes(rot_dim, rotation_kind)         # rotation
    total += n_lists * max_list_size * (bits * rot_dim // 8)  # list_codes
    total += n_lists * max_list_size * 4                     # list_ids
    total += n_lists * max_list_size * 4                     # list_scale
    total += n_lists * max_list_size * 4                     # list_bias
    if plan_cache:
        total += n_lists * 4     # _lens_np_cache (first ragged-plan search)
    return total


def _predict_cagra(*, n: int, dim: int, graph_degree: int, dtype="float32",
                   proj_dim: int = 0, n_centroids: int = 0) -> int:
    total = n * dim * _isize(dtype)                          # dataset
    total += n * graph_degree * 4                            # graph
    total += n * 4                                           # norms
    if proj_dim:
        total += dim * proj_dim * 4 + 4 + 4                  # proj+scale+energy
        total += n * graph_degree * proj_dim                 # nbr_codes int8
    if n_centroids:
        total += n_centroids * dim * 4 + n_centroids * 4
    return total


def _predict_paged_store(*, n_lists: int, dim: int, capacity_pages: int,
                         page_rows: int, table_width: int, payload_width: int,
                         payload_dtype="float32", store_kind: str = "ivf_flat",
                         pq_dim: int = 0, pq_bits: int = 8,
                         rot_dim: Optional[int] = None,
                         rotation_kind: str = "dense", bits: int = 1,
                         paged_plan_cache: bool = False) -> int:
    # ``bits`` (BQ multi-bit stores) rides in the payload_width the caller
    # measured off the pool — accepted here so index_layout() round-trips
    del bits
    total = n_lists * dim * 4                                         # centers
    total += capacity_pages * page_rows * payload_width * _isize(payload_dtype)
    total += capacity_pages * page_rows * 4                           # page_ids
    total += capacity_pages * page_rows * 4                           # page_aux
    total += capacity_pages * page_rows * 4           # page_bias (round 16)
    total += n_lists * table_width * 4                        # device table
    # host bookkeeping (counted by index_bytes too — numpy arrays carry
    # nbytes): page table + per-list chain lengths + per-page fill counts
    # + page→list ownership + per-list live-row counters (round 19 drift
    # detection)
    total += n_lists * table_width * 4                          # host _table
    total += n_lists * 4                                        # _list_pages
    total += capacity_pages * 4                                 # _fill
    total += capacity_pages * 4                                 # _page_list
    total += n_lists * 8                                        # _list_live
    if paged_plan_cache:
        # the paged Pallas path's device chain-length mirror (_dev_lens),
        # materialized on its first search
        total += n_lists * 4
    if store_kind == "ivf_pq":
        if rot_dim is None:
            rot_dim = pq_dim * (-(-dim // pq_dim))
        total += rot_dim * rot_dim * 4                                # rotation
        total += pq_dim * (1 << pq_bits) * (rot_dim // pq_dim) * 4    # codebooks
        total += capacity_pages * page_rows * rot_dim       # page_cache int8
        total += 4                                  # decoded_scale (0-d fp32)
    elif store_kind == "ivf_bq":
        if rot_dim is None:
            rot_dim = _auto_rot_dim_bq(dim, rotation_kind)
        total += _rotation_bytes(rot_dim, rotation_kind)              # rotation
        total += capacity_pages * page_rows * 4             # page_scale
    return total


_FAMILIES = {
    "brute_force": _predict_brute_force,
    "ivf_flat": _predict_ivf_flat,
    "ivf_pq": _predict_ivf_pq,
    "ivf_bq": _predict_ivf_bq,
    "cagra": _predict_cagra,
    "paged_store": _predict_paged_store,
}


def predict_index_bytes(kind: str, **layout) -> int:
    """Resident bytes of a ``kind`` index from its capacity-padded layout
    parameters — computable BEFORE the index exists (the admission
    controller's build-side input), and EXACT against
    ``obs.memory.index_bytes`` of the built artifact (the formula is the
    field layout; tier-1 property-tests pin the equality for
    flat/pq/bq)."""
    with obs.record_span("obs.costmodel::predict_index_bytes",
                         attrs={"kind": kind} if obs.enabled() else None):
        fn = _FAMILIES.get(kind)
        if fn is None:
            raise ValueError(
                f"unknown index family {kind!r} (have {sorted(_FAMILIES)})")
        return int(fn(**layout))


def predict_build_streaming_bytes(*, n: int, dim: int, n_lists: int,
                                  max_list_size: int, chunk_rows: int,
                                  train_rows: int = 0,
                                  rot_dim: Optional[int] = None,
                                  bits: int = 1,
                                  rotation_kind: str = "dense") -> dict:
    """Predicted PEAK resident bytes of one ``ivf_bq.build_streaming`` run
    — the bound the streamed build exists to enforce: the donated index
    blocks plus ONE chunk's encode transient (never the raw (n, dim)
    matrix). Closed-form, computable before the build runs (the
    billion-scale admission input: at the SIFT-1B 15.6M-row per-chip
    share this is the number that must fit next to the serving residents).

    Returns ``{"index_bytes", "chunk_transient_bytes", "labels_bytes",
    "train_bytes", "peak_bytes"}`` where ``peak_bytes = index + pass-1
    labels + max(chunk transient, training residents)`` — the two phases'
    peaks never coexist (the trainset is freed before pass 2).
    ``train_rows=0`` resolves to the build's own default sample
    (min(2M, max(n_lists·32, n·0.5)) — the default trainset fraction;
    pass ``train_rows`` explicitly for other configurations. Modeling
    the sentinel as zero residency would under-predict by the whole
    trainset), and ``train_bytes`` counts 2× the sample: the per-chunk
    parts and their concatenation coexist transiently
    (jnp.concatenate in build_streaming's training phase)."""
    if rot_dim is None:
        rot_dim = _auto_rot_dim_bq(dim, rotation_kind)
    idx = _predict_ivf_bq(n_lists=n_lists, dim=dim,
                          max_list_size=max_list_size, rot_dim=rot_dim,
                          bits=bits, rotation_kind=rotation_kind)
    # one chunk in flight: the fp32 rows, the rotated residual u and its
    # fp32 level view (the g/proj einsum operand), the packed codes, and
    # the per-row labels/scale/bias scalars
    chunk_t = int(chunk_rows) * (dim * 4 + 2 * rot_dim * 4
                                 + (bits * rot_dim) // 8 + 16)
    labels = int(n) * 4                   # pass-1 labels, kept whole-run
    t_rows = int(train_rows) or int(min(2_000_000,
                                        max(n_lists * 32, n * 0.5)))
    t_rows = min(t_rows, int(n))
    train = 2 * t_rows * dim * 4          # parts + concat coexist
    return {"index_bytes": int(idx), "chunk_transient_bytes": int(chunk_t),
            "labels_bytes": int(labels), "train_bytes": int(train),
            "peak_bytes": int(idx + labels + max(chunk_t, train))}


def index_layout(index) -> dict:
    """``{"kind": ..., **layout}`` of a built index/store, suitable for
    ``predict_index_bytes(**index_layout(idx))`` — how the bench stamps
    verify the predictor against the ``index_bytes`` gauge of the real
    artifact."""
    # lazy imports: neighbors/serving import obs, so the reverse edge must
    # not run at module import time
    from raft_tpu.neighbors import brute_force as bf_mod
    from raft_tpu.neighbors import cagra as cagra_mod
    from raft_tpu.neighbors import ivf_bq as bq_mod
    from raft_tpu.neighbors import ivf_flat as flat_mod
    from raft_tpu.neighbors import ivf_pq as pq_mod
    from raft_tpu.serving.store import PagedListStore

    # the ragged-plan search path memoizes a (n_lists,) host array on the
    # index after its first search — part of the artifact's real footprint
    plan = getattr(index, "_lens_np_cache", None) is not None
    if isinstance(index, flat_mod.IvfFlatIndex):
        return {"kind": "ivf_flat", "n_lists": index.n_lists,
                "dim": index.dim, "max_list_size": index.max_list_size,
                "dtype": str(index.list_data.dtype),
                "norms": index.list_norms is not None, "plan_cache": plan}
    if isinstance(index, pq_mod.IvfPqIndex):
        return {"kind": "ivf_pq", "n_lists": index.n_lists,
                "dim": index.dim, "max_list_size": index.max_list_size,
                "pq_dim": index.pq_dim, "pq_bits": index.pq_bits,
                "rot_dim": int(index.rotation.shape[0]),
                "codebook_kind": index.codebook_kind,
                "decoded": index.decoded is not None, "plan_cache": plan}
    if isinstance(index, bq_mod.IvfBqIndex):
        return {"kind": "ivf_bq", "n_lists": index.n_lists,
                "dim": index.dim, "max_list_size": index.max_list_size,
                "rot_dim": index.rot_dim, "bits": index.bits,
                "rotation_kind": index.rotation_kind, "plan_cache": plan}
    if isinstance(index, cagra_mod.CagraIndex):
        return {"kind": "cagra", "n": index.size, "dim": index.dim,
                "graph_degree": index.graph_degree,
                "dtype": str(index.dataset.dtype),
                "proj_dim": (0 if index.proj is None
                             else int(index.proj.shape[1])),
                "n_centroids": (0 if index.centroids is None
                                else int(index.centroids.shape[0]))}
    if isinstance(index, bf_mod.BruteForceIndex):
        return {"kind": "brute_force", "n": index.size, "dim": index.dim,
                "dtype": str(index.dataset.dtype),
                "norms": index.norms is not None}
    if isinstance(index, PagedListStore):
        return {"kind": "paged_store", "store_kind": index.kind,
                "n_lists": index.n_lists, "dim": index.dim,
                "capacity_pages": index.capacity_pages,
                "page_rows": index.page_rows,
                "table_width": index.table_width,
                "payload_width": int(index.pages.shape[2]),
                "payload_dtype": str(index.pages.dtype),
                "pq_dim": index.pq_dim, "pq_bits": index.pq_bits,
                "rot_dim": (None if index.rotation is None
                            else int(index.rotation.shape[0])),
                "rotation_kind": getattr(index, "rotation_kind", "dense"),
                "bits": int(getattr(index, "bq_bits", 1)),
                # the paged Pallas path's lazily-built device mirror
                "paged_plan_cache": getattr(index, "_dev_lens", None)
                is not None}
    raise TypeError(f"unsupported index type {type(index).__name__}")


# ---------------------------------------------------------------------------
# per-dispatch estimators (operand + output + workspace)
# ---------------------------------------------------------------------------


def _ws_tile(q: int, per_query: int, workspace_bytes: int) -> int:
    """The dispatch sites' own tile arithmetic (ivf_flat.search et al.):
    q_tile = clamp(workspace // per_query, 1..q)."""
    return int(max(1, min(q, workspace_bytes // max(1, per_query))))


def _workspace_bytes() -> int:
    from raft_tpu.core.resources import current_resources

    return int(current_resources().workspace_bytes)


def _est_ivf_flat_search(*, q, dim, n_lists, max_list_size, n_probes, k,
                         dtype="float32", norms=True, workspace_bytes=None):
    ws = workspace_bytes if workspace_bytes is not None else _workspace_bytes()
    operands = q * dim * 4 + _predict_ivf_flat(
        n_lists=n_lists, dim=dim, max_list_size=max_list_size, dtype=dtype,
        norms=norms)
    per_query = max(1, n_probes * max_list_size * (dim + 2) * 4)
    qt = _ws_tile(q, per_query, ws)
    workspace = qt * per_query + q * n_lists * 8       # gather tile + coarse
    outputs = q * k * 8
    return operands, outputs, workspace


def _est_ivf_flat_paged(*, q, dim, n_lists, capacity_pages, page_rows,
                        table_width, n_probes, k, dtype="float32",
                        workspace_bytes=None):
    ws = workspace_bytes if workspace_bytes is not None else _workspace_bytes()
    operands = q * dim * 4 + _predict_paged_store(
        n_lists=n_lists, dim=dim, capacity_pages=capacity_pages,
        page_rows=page_rows, table_width=table_width, payload_width=dim,
        payload_dtype=dtype)
    per_query = max(1, n_probes * table_width * page_rows * (dim + 2) * 4)
    qt = _ws_tile(q, per_query, ws)
    workspace = qt * per_query + q * n_lists * 8
    outputs = q * k * 8
    return operands, outputs, workspace


def _est_ivf_pq_search(*, q, dim, n_lists, max_list_size, pq_dim, n_probes,
                       k, pq_bits=8, rot_dim=None, workspace_bytes=None):
    ws = workspace_bytes if workspace_bytes is not None else _workspace_bytes()
    if rot_dim is None:
        rot_dim = pq_dim * (-(-dim // pq_dim))
    operands = q * dim * 4 + _predict_ivf_pq(
        n_lists=n_lists, dim=dim, max_list_size=max_list_size, pq_dim=pq_dim,
        pq_bits=pq_bits, rot_dim=rot_dim)
    per_query = max(1, n_probes * max_list_size * (pq_dim * 5 + 8))
    qt = _ws_tile(q, per_query, ws)
    luts = q * pq_dim * (1 << pq_bits) * 4
    workspace = qt * per_query + luts + q * n_lists * 8
    outputs = q * k * 8
    return operands, outputs, workspace


def _est_ivf_pq_paged(*, q, dim, n_lists, capacity_pages, page_rows,
                      table_width, pq_dim, n_probes, k, pq_bits=8,
                      rot_dim=None, workspace_bytes=None):
    ws = workspace_bytes if workspace_bytes is not None else _workspace_bytes()
    code_width = (pq_dim * pq_bits + 7) // 8
    operands = q * dim * 4 + _predict_paged_store(
        n_lists=n_lists, dim=dim, capacity_pages=capacity_pages,
        page_rows=page_rows, table_width=table_width,
        payload_width=code_width, payload_dtype="uint8", store_kind="ivf_pq",
        pq_dim=pq_dim, pq_bits=pq_bits, rot_dim=rot_dim)
    per_query = max(1, n_probes * table_width * page_rows * (pq_dim * 5 + 8))
    qt = _ws_tile(q, per_query, ws)
    luts = q * pq_dim * (1 << pq_bits) * 4
    workspace = qt * per_query + luts + q * n_lists * 8
    outputs = q * k * 8
    return operands, outputs, workspace


def _est_ivf_bq_search(*, q, dim, n_lists, max_list_size, n_probes, k,
                       rot_dim=None, bits=1, rotation_kind="dense",
                       workspace_bytes=None):
    ws = workspace_bytes if workspace_bytes is not None else _workspace_bytes()
    if rot_dim is None:
        rot_dim = _auto_rot_dim_bq(dim, rotation_kind)
    operands = q * dim * 4 + _predict_ivf_bq(
        n_lists=n_lists, dim=dim, max_list_size=max_list_size,
        rot_dim=rot_dim, bits=bits, rotation_kind=rotation_kind)
    # rotated (plane-extended) queries + coarse gemm + the unpacked ±1
    # strip block the scan holds per tile (bf16 rows, bits·rot_dim wide)
    # + score/merge rows
    width = rot_dim * bits
    per_query = max(1, n_probes * max_list_size * (width * 2 + 8))
    qt = _ws_tile(q, per_query, ws)
    workspace = qt * per_query + q * width * 4 + q * n_lists * 8
    outputs = q * k * 8
    return operands, outputs, workspace


def _est_ivf_bq_paged(*, q, dim, n_lists, capacity_pages, page_rows,
                      table_width, n_probes, k, rot_dim=None, bits=1,
                      rotation_kind="dense", workspace_bytes=None):
    ws = workspace_bytes if workspace_bytes is not None else _workspace_bytes()
    if rot_dim is None:
        rot_dim = _auto_rot_dim_bq(dim, rotation_kind)
    operands = q * dim * 4 + _predict_paged_store(
        n_lists=n_lists, dim=dim, capacity_pages=capacity_pages,
        page_rows=page_rows, table_width=table_width,
        payload_width=bits * rot_dim // 8, payload_dtype="uint8",
        store_kind="ivf_bq", rot_dim=rot_dim, rotation_kind=rotation_kind)
    # the unpacked ±1 strip block per probed chain row + score/merge rows
    width = rot_dim * bits
    per_query = max(1, n_probes * table_width * page_rows * (width * 2 + 8))
    qt = _ws_tile(q, per_query, ws)
    workspace = qt * per_query + q * width * 4 + q * n_lists * 8
    outputs = q * k * 8
    return operands, outputs, workspace


def _est_brute_force_search(*, q, n, dim, k, tile_rows=65536,
                            dtype="float32", workspace_bytes=None):
    operands = q * dim * 4 + _predict_brute_force(n=n, dim=dim, dtype=dtype)
    tile = min(n, tile_rows)
    workspace = q * tile * 4 * 2                       # distance tile + select
    outputs = q * k * 8
    return operands, outputs, workspace


def _est_serving_upsert(*, n_rows, payload_width, dim,
                        payload_dtype="float32", extra_row_bytes=0,
                        workspace_bytes=None):
    batch = 1 << max(0, int(n_rows - 1).bit_length())  # pow2 scatter bucket
    operands = n_rows * dim * 4                        # incoming vectors
    # payload + id + aux + scan bias + kind-specific extra pool row
    workspace = batch * (payload_width * _isize(payload_dtype) + 4 + 4 + 4
                         + int(extra_row_bytes) + 16)
    outputs = 0                                        # in-place pool update
    return operands, outputs, workspace


_ESTIMATORS = {
    "ivf_flat.search": _est_ivf_flat_search,
    "ivf_flat.paged_scan": _est_ivf_flat_paged,
    "ivf_pq.search": _est_ivf_pq_search,
    "ivf_pq.paged_scan": _est_ivf_pq_paged,
    "ivf_bq.search": _est_ivf_bq_search,
    "ivf_bq.paged_scan": _est_ivf_bq_paged,
    "brute_force.search": _est_brute_force_search,
    "serving.upsert": _est_serving_upsert,
}


def estimate(entry: str, **shapes) -> dict:
    """Static footprint of ONE dispatch of ``entry``: operand bytes (the
    resident arrays the program reads), output bytes, and workspace bytes
    (the big intermediates, via the same per-query/tile arithmetic the
    dispatch site uses to size itself). ``transient_bytes`` = outputs +
    workspace — the allocation the dispatch ADDS on top of what is already
    resident, which is the number admission projects forward."""
    with obs.record_span("obs.costmodel::estimate",
                         attrs={"entry": entry} if obs.enabled() else None):
        fn = _ESTIMATORS.get(entry)
        if fn is None:
            raise ValueError(
                f"unknown entry {entry!r} (have {sorted(_ESTIMATORS)})")
        operands, outputs, workspace = fn(**shapes)
        out = {
            "entry": entry,
            "operand_bytes": int(operands),
            "output_bytes": int(outputs),
            "workspace_bytes": int(workspace),
            "transient_bytes": int(outputs + workspace),
            "total_bytes": int(operands + outputs + workspace),
        }
        if obs.enabled():
            obs.set_gauge(f"costmodel.{entry}.total_bytes",
                          out["total_bytes"])
        return out


def estimate_search(index, q: int, k: int, n_probes: int = 0,
                    workspace_bytes: Optional[int] = None,
                    filter=None) -> dict:
    """:func:`estimate` with kwargs derived from a live index/store — the
    bench-section and serving-dispatch convenience.

    ``filter`` (a :class:`~raft_tpu.core.bitset.Bitset`) projects the
    footprint of the plan the dispatch will ACTUALLY run: the families
    widen ``n_probes`` by the selectivity factor
    (``neighbors/_filtering.widen_plan``) before scanning, so a filtered
    estimate widens here with the same rule — predicted-vs-measured
    stays exact under push-down."""
    layout = index_layout(index)
    kind = layout.pop("kind")
    if filter is not None and n_probes:
        from raft_tpu.neighbors import _filtering
        n_probes, _, _, _ = _filtering.widen_plan(
            filter, n_probes, layout.get("n_lists", n_probes))
    ws = {"workspace_bytes": workspace_bytes}
    if kind == "ivf_flat":
        return estimate("ivf_flat.search", q=q, k=k, n_probes=n_probes,
                        dim=layout["dim"], n_lists=layout["n_lists"],
                        max_list_size=layout["max_list_size"],
                        dtype=layout["dtype"], norms=layout["norms"], **ws)
    if kind == "ivf_pq":
        return estimate("ivf_pq.search", q=q, k=k, n_probes=n_probes,
                        dim=layout["dim"], n_lists=layout["n_lists"],
                        max_list_size=layout["max_list_size"],
                        pq_dim=layout["pq_dim"], pq_bits=layout["pq_bits"],
                        rot_dim=layout["rot_dim"], **ws)
    if kind == "ivf_bq":
        return estimate("ivf_bq.search", q=q, k=k, n_probes=n_probes,
                        dim=layout["dim"], n_lists=layout["n_lists"],
                        max_list_size=layout["max_list_size"],
                        rot_dim=layout["rot_dim"],
                        bits=layout.get("bits", 1),
                        rotation_kind=layout.get("rotation_kind", "dense"),
                        **ws)
    if kind == "brute_force":
        return estimate("brute_force.search", q=q, k=k, n=layout["n"],
                        dim=layout["dim"], dtype=layout["dtype"], **ws)
    if kind == "paged_store":
        sk = layout.get("store_kind")
        entry = {"ivf_pq": "ivf_pq.paged_scan",
                 "ivf_bq": "ivf_bq.paged_scan"}.get(sk,
                                                    "ivf_flat.paged_scan")
        kw = dict(q=q, k=k, n_probes=n_probes, dim=layout["dim"],
                  n_lists=layout["n_lists"],
                  capacity_pages=layout["capacity_pages"],
                  page_rows=layout["page_rows"],
                  table_width=layout["table_width"], **ws)
        if entry == "ivf_pq.paged_scan":
            kw.update(pq_dim=layout["pq_dim"], pq_bits=layout["pq_bits"],
                      rot_dim=layout["rot_dim"])
        elif entry == "ivf_bq.paged_scan":
            kw.update(rot_dim=layout["rot_dim"],
                      bits=layout.get("bits", 1),
                      rotation_kind=layout.get("rotation_kind", "dense"))
        return estimate(entry, **kw)
    raise ValueError(f"no dispatch estimator for index family {kind!r}")


def paged_scan_estimator(store, k: int, n_probes: int):
    """``batch_size -> estimate dict`` closed over one store's CURRENT
    capacity layout — the ``QueryQueue(cost_model=...)`` hook. Re-reads
    the layout each call, so a capacity growth is priced from the next
    dispatch on."""

    def cost(batch: int) -> dict:
        return estimate_search(store, q=int(batch), k=k, n_probes=n_probes)

    return cost


# ---------------------------------------------------------------------------
# XLA cross-check
# ---------------------------------------------------------------------------


def xla_memory_analysis(jitted, *args, **kwargs) -> Optional[dict]:
    """The backend's own byte accounting for one lowering of ``jitted``:
    ``{"argument_bytes", "output_bytes", "temp_bytes", "generated_code_bytes"}``
    from ``lower(...).compile().memory_analysis()``, falling back to
    ``cost_analysis()``'s ``bytes accessed``. None (classified into the
    event ring) where the backend provides neither — the static model
    stands alone there."""
    from raft_tpu import resilience

    with obs.record_span("obs.costmodel::xla_memory_analysis"):
        try:
            # analysis-only lowering: mute the compile ledger — the body's
            # trace_event would otherwise record a same-signature re-trace
            # as a fabricated "unexplained retrace" and inflate the
            # zero-recompile deltas this module exists to validate
            with obs_compile.suppress_analysis():
                compiled = jitted.lower(*args, **kwargs).compile()
            mem = compiled.memory_analysis()
            if mem is not None:
                out = {}
                for ours, theirs in (
                        ("argument_bytes", "argument_size_in_bytes"),
                        ("output_bytes", "output_size_in_bytes"),
                        ("temp_bytes", "temp_size_in_bytes"),
                        ("generated_code_bytes",
                         "generated_code_size_in_bytes")):
                    v = getattr(mem, theirs, None)
                    if v is not None:
                        out[ours] = int(v)
                if out:
                    return out
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if isinstance(cost, dict) and "bytes accessed" in cost:
                return {"bytes_accessed": int(cost["bytes accessed"])}
            return None
        except Exception as e:
            # a backend without the analysis API is a supported state; the
            # event carries the kind so a real lowering failure is visible
            resilience.record_event(
                "costmodel_xla_analysis_unavailable",
                kind=resilience.classify(e), error=repr(e)[:200])
            return None


# ---------------------------------------------------------------------------
# pre-dispatch admission
# ---------------------------------------------------------------------------


def admission_counts(counters: dict) -> dict:
    """``{verdict: count}`` folded out of a counters snapshot — the ONE
    definition of the verdict-counter namespace, shared by
    ``obs.report.collect`` and the bench operating-point record."""
    return {k[len(ADMISSION_COUNTER_PREFIX):]: int(v)
            for k, v in (counters or {}).items()
            if k.startswith(ADMISSION_COUNTER_PREFIX)}


def hbm_budget() -> dict:
    """``{"bytes": int, "source": str}`` — the denominator admission
    projects against: ``RAFT_TPU_OBS_HBM_BYTES`` when set (tests, CPU
    serving hosts), else the sum of ``Device.memory_stats()['bytes_limit']``
    over local devices (TPU), else 0 with ``source="unknown"``."""
    raw = os.environ.get(HBM_ENV, "").strip()
    if raw.isdigit() and int(raw) > 0:
        return {"bytes": int(raw), "source": "env"}
    total = 0
    for dev in obs_memory.device_stats():
        total += int(dev.get("bytes_limit", 0) or 0)
    if total > 0:
        return {"bytes": total, "source": "device_stats"}
    return {"bytes": 0, "source": "unknown"}


def check_admission(predicted, entry: str = "",
                    budget_bytes: Optional[int] = None,
                    bytes_in_use: Optional[int] = None) -> dict:
    """Pre-dispatch admission verdict for a predicted footprint:
    ``predicted`` is an :func:`estimate` dict (its ``transient_bytes`` is
    the projected delta) or a plain byte count. Projects ``bytes_in_use +
    predicted`` against the budget and classifies ADMIT (≤ soft·budget) /
    QUEUE (≤ hard·budget) / REJECT — recorded as gauges
    (``costmodel.admission.*``) and, for non-admit verdicts, classified
    events in the resilience ring. On a multi-device backend with
    per-device allocator limits the verdict is the WORST device's: the
    whole predicted footprint is projected onto each device's own
    ``(bytes_in_use + predicted) / bytes_limit`` — summing across devices
    would dilute one hot chip's pressure by the device count and admit
    the dispatch that OOMs it. Returns the verdict record; NEVER raises
    (an admission check that throws is worse than no check — failures
    degrade to an ``unknown``-budget ADMIT, classified).

    ``bytes_in_use`` (round 18) overrides the live watermark sample —
    the per-tenant residency budgeter projects against its own PREDICTED
    resident ledger (deterministic, synthetic-budget friendly) instead
    of whatever else the process happens to hold. QUEUE/REJECT records
    carry ``shortfall_bytes`` = ``projected − soft·budget`` — the exact
    number of bytes an eviction must free to return the projection to
    ADMIT, so the capacity controller sizes demotions instead of
    guessing."""
    from raft_tpu import resilience

    with obs.record_span("obs.costmodel::check_admission",
                         attrs={"entry": entry} if obs.enabled() else None):
        try:
            if isinstance(predicted, dict):
                pred_bytes = int(predicted.get(
                    "transient_bytes", predicted.get("total_bytes", 0)))
            else:
                pred_bytes = int(predicted)
        except Exception as e:
            # a malformed prediction must not cost the dispatch either:
            # zero-byte ADMIT, classified — the caller's hook is broken,
            # not the request
            resilience.record_event("admission_bad_prediction",
                                    kind=resilience.classify(e),
                                    error=repr(e)[:200])
            pred_bytes = 0
        per_dev = []
        try:
            if bytes_in_use is not None:
                # the budgeter's ledger IS the watermark: no sampling, no
                # per-device dilution — one deterministic projection
                in_use = int(bytes_in_use)
            else:
                mem = obs_memory.sample(f"admission.{entry}" if entry
                                        else "admission")
                in_use = int(mem["bytes_in_use"])
                per_dev = [d for d in (mem.get("per_device") or [])
                           if d.get("bytes_limit")]
            budget = ({"bytes": int(budget_bytes), "source": "caller"}
                      if budget_bytes else hbm_budget())
        except Exception as e:
            # the check must not cost the dispatch: degrade classified
            resilience.record_event("admission_check_error",
                                    kind=resilience.classify(e),
                                    error=repr(e)[:200])
            in_use, budget = 0, {"bytes": 0, "source": "unknown"}
        projected = in_use + pred_bytes
        soft, hard = _frac(SOFT_ENV, 0.85), _frac(HARD_ENV, 0.97)
        shortfall = None
        if budget["source"] == "device_stats" and per_dev:
            # worst-device projection (see docstring)
            frac = max((d["bytes_in_use"] + pred_bytes) / d["bytes_limit"]
                       for d in per_dev)
            verdict = (ADMIT if frac <= soft
                       else QUEUE if frac <= hard else REJECT)
            shortfall = max(d["bytes_in_use"] + pred_bytes
                            - soft * d["bytes_limit"] for d in per_dev)
        elif budget["bytes"] <= 0:
            verdict, frac = ADMIT, None
        else:
            frac = projected / budget["bytes"]
            verdict = (ADMIT if frac <= soft
                       else QUEUE if frac <= hard else REJECT)
            shortfall = projected - soft * budget["bytes"]
        rec = {
            "verdict": verdict,
            "entry": entry,
            "predicted_bytes": pred_bytes,
            "bytes_in_use": in_use,
            "projected_bytes": projected,
            "budget_bytes": budget["bytes"],
            "budget_source": budget["source"],
            "projected_fraction": (round(frac, 4)
                                   if frac is not None else None),
            "t": round(time.time(), 3),
        }
        if verdict != ADMIT and shortfall is not None:
            # the eviction size: free this many bytes and the projection
            # is back under the soft threshold (capacity controller input)
            rec["shortfall_bytes"] = int(np.ceil(max(0.0, shortfall)))
        if obs.enabled():
            obs.add(f"{ADMISSION_COUNTER_PREFIX}{verdict}")
            obs.set_gauge("costmodel.admission.predicted_bytes", pred_bytes)
            obs.set_gauge("costmodel.admission.projected_bytes", projected)
        if verdict != ADMIT:
            resilience.record_event(f"admission_{verdict}", entry=entry,
                                    predicted_bytes=pred_bytes,
                                    projected_bytes=projected,
                                    budget_bytes=budget["bytes"])
        return rec
