"""Fleet-wide metric aggregation: merge per-process snapshots exactly.

EQuARX-style per-shard collective accounting (PAPERS.md) needs one question
answered that flat per-process files cannot: *what did the whole fleet do?*
Every ``export_jsonl`` / heartbeat record is stamped with
``process_index``/``process_count`` (obs/tracing.process_info); this module
folds any number of those per-process snapshots into ONE fleet view — and the
merge is **exact**, not approximate:

* counters — integer/float sums, key-wise;
* timers — ``count``/``total_s`` sum, ``min_s``/``max_s`` min/max, mean
  recomputed from the merged totals;
* histograms — ``count``/``sum`` sum, ``min``/``max`` min/max, and the
  power-of-two buckets merged KEY-WISE (a bucket bound is a pure function of
  the observed value, so identical bounds on different processes are the
  same bucket — merging loses nothing the per-process histograms had);
* gauges — ``min`` min-of-min, ``max`` max-of-max, ``count`` sum, and the
  per-process LAST values preserved verbatim in the ``last`` map (each
  process's snapshot keys its final value by process — dict union is
  associative, so nothing is averaged away); the merged ``value`` is the
  max over preserved last values (the conservative fleet watermark).

Merging is associative and commutative (sums/mins/maxes of disjoint streams),
which ``tests/test_aggregate.py`` property-tests; percentile upper bounds
(:func:`percentile_bounds`, the ≤2× bucket-bound estimates) are derived from
the merged buckets, never merged themselves.

CLI (the parent-side entry bench.py uses after a multichip window)::

    python -m raft_tpu.obs.aggregate results/metrics/*.jsonl [--output f.json]

Deliberately stdlib-only at module level: bench.py's jax-free orchestrator
loads this file by path (``_load_by_path``) the same way it loads
``bench/progress.py``, so fleet aggregation works even when the raft_tpu/jax
package import is the thing that wedged.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Iterable, List, Optional

__all__ = [
    "main",
    "merge_files",
    "merge_records",
    "merge_snapshots",
    "percentile_bounds",
    "read_jsonl",
    "read_trace",
    "stitch_traces",
]

#: the quantiles snapshot()/export carry, as (key, q) pairs
QUANTILES = (("p50_ub", 0.50), ("p90_ub", 0.90), ("p99_ub", 0.99))


def percentile_bounds(buckets: dict, count: int) -> dict:
    """p50/p90/p99 UPPER-BOUND estimates from power-of-two buckets.

    A bucket key ``le_B`` counts observations with value ≤ B where B is the
    smallest power of two ≥ the value — so the true q-quantile lies in
    ``(B/2, B]`` of the first bucket whose cumulative count reaches
    ``ceil(q·count)``, and the returned bound over-estimates it by AT MOST
    2× (exactly the bucket resolution). Returns ``{}`` for an empty
    histogram."""
    if not count or not buckets:
        return {}
    bounds = []
    for key, n in buckets.items():
        try:
            bounds.append((float(str(key)[3:]), int(n)))
        except (ValueError, IndexError):
            continue
    if not bounds:
        return {}
    bounds.sort()
    out = {}
    for key, q in QUANTILES:
        need = max(1, math.ceil(q * count))
        cum = 0
        for bound, n in bounds:
            cum += n
            if cum >= need:
                out[key] = bound
                break
        else:
            out[key] = bounds[-1][0]
    return out


def _merge_timer(a: dict, b: dict) -> dict:
    count = a.get("count", 0) + b.get("count", 0)
    total = a.get("total_s", 0.0) + b.get("total_s", 0.0)
    return {
        "count": count,
        "total_s": total,
        "min_s": min(a.get("min_s", math.inf), b.get("min_s", math.inf)),
        "max_s": max(a.get("max_s", 0.0), b.get("max_s", 0.0)),
        "mean_s": total / count if count else 0.0,
    }


def _merge_hist(a: dict, b: dict) -> dict:
    buckets = dict(a.get("buckets") or {})
    for key, n in (b.get("buckets") or {}).items():
        buckets[key] = buckets.get(key, 0) + n
    count = a.get("count", 0) + b.get("count", 0)
    out = {
        "count": count,
        "sum": a.get("sum", 0.0) + b.get("sum", 0.0),
        "min": min(a.get("min", math.inf), b.get("min", math.inf)),
        "max": max(a.get("max", -math.inf), b.get("max", -math.inf)),
        "buckets": buckets,
    }
    out.update(percentile_bounds(buckets, count))
    return out


def _merge_gauge(a: dict, b: dict) -> dict:
    last = dict(a.get("last") or
                ({"p0": a["value"]} if "value" in a else {}))
    last.update(b.get("last") or
                ({"p0": b["value"]} if "value" in b else {}))
    out = {
        "min": min(a.get("min", math.inf), b.get("min", math.inf)),
        "max": max(a.get("max", -math.inf), b.get("max", -math.inf)),
        "count": a.get("count", 0) + b.get("count", 0),
        "last": last,
    }
    # the merged headline value: the max over preserved per-process last
    # values — conservative for usage-shaped gauges (memory watermarks,
    # queue depth), where the worst process IS the fleet answer. For
    # quality-shaped gauges (obs.shadow.recall), max hides the degraded
    # process — direction-sensitive consumers must read ``last``/``min``,
    # which is exactly why the per-process values are preserved verbatim
    if last:
        out["value"] = max(last.values())
    return out


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Fold snapshot dicts ({"counters": .., "timers": .., "histograms": ..,
    "gauges": ..}) into one fleet snapshot, exactly (module docstring). Left
    fold in input order; the operation is associative/commutative up to
    float summation order, and bit-exact for counters, histogram buckets and
    gauge last-value maps."""
    counters: dict = {}
    timers: dict = {}
    hists: dict = {}
    gauges: dict = {}
    for snap in snaps:
        for key, val in (snap.get("counters") or {}).items():
            counters[key] = counters.get(key, 0) + val
        for key, val in (snap.get("timers") or {}).items():
            timers[key] = _merge_timer(timers[key], val) if key in timers \
                else dict(val)
        for key, val in (snap.get("histograms") or {}).items():
            hists[key] = _merge_hist(hists[key], val) if key in hists \
                else _merge_hist({}, val)
        for key, val in (snap.get("gauges") or {}).items():
            gauges[key] = _merge_gauge(gauges[key], val) if key in gauges \
                else _merge_gauge({}, val)
    return {"counters": counters, "timers": timers, "histograms": hists,
            "gauges": gauges}


def merge_records(records: List[dict]) -> dict:
    """Fleet view from export_jsonl-shaped records: keep the NEWEST snapshot
    per (source, process_index) — each line is a cumulative snapshot of its
    process, so merging two generations of the same process would double
    count — then merge the survivors. Returns the merged snapshot plus
    provenance (``processes``, ``process_count``, t range)."""
    latest: dict = {}
    offsets: dict = {}
    for rec in records:
        if rec.get("type") == "clock_offset":
            # the flight recorder's per-process handshake: NOT a metrics
            # snapshot — folding it into ``latest`` would let a newer
            # handshake supersede (and erase) its process's real snapshot.
            # Newest handshake per process wins; the fold is a key-wise
            # max-by-t, so grouping cannot change it (associativity).
            pi = rec.get("process_index", 0)
            prev = offsets.get(pi)
            if prev is None or rec.get("t", 0) >= prev.get("t", 0):
                offsets[pi] = rec
            continue
        src = rec.get("_source", "")
        key = (src, rec.get("process_index", 0))
        prev = latest.get(key)
        if prev is None or rec.get("t", 0) >= prev.get("t", 0):
            latest[key] = rec
    picked = [latest[k] for k in sorted(latest, key=str)]
    merged = merge_snapshots(picked)
    procs = sorted({r.get("process_index", 0) for r in picked})
    merged["processes"] = procs
    merged["process_count"] = max(
        [r.get("process_count", 1) for r in picked] + [len(procs)])
    ts = [r["t"] for r in picked if isinstance(r.get("t"), (int, float))]
    if ts:
        merged["t_min"] = min(ts)
        merged["t_max"] = max(ts)
    if offsets:
        merged["clock_offsets"] = {
            f"p{pi}": {key: rec.get(key) for key in
                       ("offset_s", "t_epoch", "t_mono", "t")
                       if key in rec}
            for pi, rec in sorted(offsets.items())}
    return merged


def read_jsonl(path: str) -> List[dict]:
    """Parse one metrics JSONL file, skipping torn/corrupt lines (the same
    tolerance bench/progress.read_progress gives heartbeat files). Each
    record is tagged with its source path for per-process dedup."""
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    rec["_source"] = path
                    records.append(rec)
    except OSError:
        return []
    return records


def merge_files(paths: Iterable[str]) -> dict:
    """Read + merge any number of per-process metrics JSONL files."""
    records: List[dict] = []
    sources = []
    for path in paths:
        recs = read_jsonl(path)
        if recs:
            sources.append(path)
        records.extend(recs)
    out = merge_records(records)
    out["sources"] = sources
    return out


def read_trace(path: str) -> Optional[dict]:
    """Load one per-process Chrome trace export (obs/tracing.chrome_trace
    shape). Returns None for unreadable/garbage files — a dead child's
    torn trace must cost one track, not the stitch."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("traceEvents"), list):
        return None
    return doc


def stitch_traces(docs: Iterable[Optional[dict]],
                  clock_offsets: Optional[dict] = None) -> dict:
    """Fold per-process Chrome traces (``trace_bench_p{i}.json`` exports)
    into ONE fleet timeline loadable as a single Perfetto file.

    Each source keeps its own ``pid`` track (a ``process_name`` metadata
    event labels it ``host<i>``); two exports claiming the same
    process_index — the id-collision case — are re-homed on the next free
    track, never merged. Host-LOCAL span/trace ids are namespaced
    ``p<i>/<id>`` so pid-counter collisions across hosts stay distinct,
    while the ``fleet_trace_id`` attr (obs/tracing.fleet_trace_id) is left
    verbatim — it is the cross-host join key, one fleet trace over
    distinct host tracks. ``clock_offsets`` (merge_records' fold of the
    flight recording's handshake records, ``{"p<i>": {"offset_s": ..}}``)
    shifts each host's timestamps onto the shared reference clock."""
    events: list = []
    used: set = set()
    counts = [1]
    sources = []
    for slot, doc in enumerate(d for d in docs if d is not None):
        meta = doc.get("otherData") or {}
        pi = int(meta.get("process_index", slot))
        while pi in used:
            pi += 1
        used.add(pi)
        counts.append(int(meta.get("process_count", 1) or 1))
        sources.append({"process_index": pi, **{
            k: v for k, v in meta.items() if k != "process_index"}})
        off_s = 0.0
        if clock_offsets:
            row = clock_offsets.get(f"p{pi}") or clock_offsets.get(pi) or {}
            try:
                off_s = float(row.get("offset_s", 0.0) or 0.0)
            except (TypeError, ValueError):
                off_s = 0.0
        events.append({"name": "process_name", "ph": "M", "pid": pi,
                       "args": {"name": f"host{pi}"}})
        for ev in doc.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = pi
            if off_s and isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = round(ev["ts"] - off_s * 1e6, 1)
            args = ev.get("args")
            if isinstance(args, dict):
                args = dict(args)
                for key in ("trace_id", "span_id", "parent_id"):
                    if args.get(key):
                        args[key] = f"p{pi}/{args[key]}"
                ev["args"] = args
            events.append(ev)
    # metadata events first (no ts), then chronological across hosts
    events.sort(key=lambda e: (e.get("ph") != "M",
                               e["ts"] if isinstance(e.get("ts"),
                                                     (int, float)) else 0.0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "stitched": True,
            "processes": sorted(used),
            "process_count": max(counts + [len(used)]),
            "sources": sources,
        },
    }


def _emit(text: str, output: Optional[str]) -> None:
    if output:
        with open(output, "w") as f:
            f.write(text + "\n")
            f.flush()
    else:
        print(text)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs.aggregate",
        description="Merge per-process obs metrics JSONL files into one "
                    "fleet-wide snapshot (exact for counters and "
                    "power-of-two histograms), or stitch per-process "
                    "Chrome traces into one fleet timeline (--stitch).")
    ap.add_argument("files", nargs="+",
                    help="metrics JSONL files (or Chrome trace JSON files "
                         "with --stitch)")
    ap.add_argument("--stitch", action="store_true",
                    help="treat files as per-process Chrome traces and "
                         "fold them into ONE fleet trace with per-host "
                         "tracks")
    ap.add_argument("--handshakes", default=None, metavar="PATH",
                    help="flight recording JSONL whose clock_offset "
                         "handshake records align host clocks in the "
                         "stitch")
    ap.add_argument("--output", default=None, metavar="PATH",
                    help="write the fleet view here instead of stdout")
    ap.add_argument("--indent", type=int, default=2)
    args = ap.parse_args(argv)
    if args.stitch:
        docs = [read_trace(p) for p in args.files]
        if not any(d is not None for d in docs):
            print("aggregate: no loadable traces in "
                  f"{', '.join(args.files)}", file=sys.stderr)
            return 2
        offsets = None
        if args.handshakes:
            offsets = merge_records(
                read_jsonl(args.handshakes)).get("clock_offsets")
        doc = stitch_traces(docs, clock_offsets=offsets)
        _emit(json.dumps(doc, indent=args.indent, sort_keys=True),
              args.output)
        return 0
    fleet = merge_files(args.files)
    if not fleet.get("sources"):
        print("aggregate: no parseable records in "
              f"{', '.join(args.files)}", file=sys.stderr)
        return 2
    _emit(json.dumps(fleet, indent=args.indent, sort_keys=True),
          args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
