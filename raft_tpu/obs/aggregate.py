"""Fleet-wide metric aggregation: merge per-process snapshots exactly.

EQuARX-style per-shard collective accounting (PAPERS.md) needs one question
answered that flat per-process files cannot: *what did the whole fleet do?*
Every ``export_jsonl`` / heartbeat record is stamped with
``process_index``/``process_count`` (obs/tracing.process_info); this module
folds any number of those per-process snapshots into ONE fleet view — and the
merge is **exact**, not approximate:

* counters — integer/float sums, key-wise;
* timers — ``count``/``total_s`` sum, ``min_s``/``max_s`` min/max, mean
  recomputed from the merged totals;
* histograms — ``count``/``sum`` sum, ``min``/``max`` min/max, and the
  power-of-two buckets merged KEY-WISE (a bucket bound is a pure function of
  the observed value, so identical bounds on different processes are the
  same bucket — merging loses nothing the per-process histograms had);
* gauges — ``min`` min-of-min, ``max`` max-of-max, ``count`` sum, and the
  per-process LAST values preserved verbatim in the ``last`` map (each
  process's snapshot keys its final value by process — dict union is
  associative, so nothing is averaged away); the merged ``value`` is the
  max over preserved last values (the conservative fleet watermark).

Merging is associative and commutative (sums/mins/maxes of disjoint streams),
which ``tests/test_aggregate.py`` property-tests; percentile upper bounds
(:func:`percentile_bounds`, the ≤2× bucket-bound estimates) are derived from
the merged buckets, never merged themselves.

CLI (the parent-side entry bench.py uses after a multichip window)::

    python -m raft_tpu.obs.aggregate results/metrics/*.jsonl [--output f.json]

Deliberately stdlib-only at module level: bench.py's jax-free orchestrator
loads this file by path (``_load_by_path``) the same way it loads
``bench/progress.py``, so fleet aggregation works even when the raft_tpu/jax
package import is the thing that wedged.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Iterable, List, Optional

__all__ = [
    "main",
    "merge_files",
    "merge_records",
    "merge_snapshots",
    "percentile_bounds",
    "read_jsonl",
]

#: the quantiles snapshot()/export carry, as (key, q) pairs
QUANTILES = (("p50_ub", 0.50), ("p90_ub", 0.90), ("p99_ub", 0.99))


def percentile_bounds(buckets: dict, count: int) -> dict:
    """p50/p90/p99 UPPER-BOUND estimates from power-of-two buckets.

    A bucket key ``le_B`` counts observations with value ≤ B where B is the
    smallest power of two ≥ the value — so the true q-quantile lies in
    ``(B/2, B]`` of the first bucket whose cumulative count reaches
    ``ceil(q·count)``, and the returned bound over-estimates it by AT MOST
    2× (exactly the bucket resolution). Returns ``{}`` for an empty
    histogram."""
    if not count or not buckets:
        return {}
    bounds = []
    for key, n in buckets.items():
        try:
            bounds.append((float(str(key)[3:]), int(n)))
        except (ValueError, IndexError):
            continue
    if not bounds:
        return {}
    bounds.sort()
    out = {}
    for key, q in QUANTILES:
        need = max(1, math.ceil(q * count))
        cum = 0
        for bound, n in bounds:
            cum += n
            if cum >= need:
                out[key] = bound
                break
        else:
            out[key] = bounds[-1][0]
    return out


def _merge_timer(a: dict, b: dict) -> dict:
    count = a.get("count", 0) + b.get("count", 0)
    total = a.get("total_s", 0.0) + b.get("total_s", 0.0)
    return {
        "count": count,
        "total_s": total,
        "min_s": min(a.get("min_s", math.inf), b.get("min_s", math.inf)),
        "max_s": max(a.get("max_s", 0.0), b.get("max_s", 0.0)),
        "mean_s": total / count if count else 0.0,
    }


def _merge_hist(a: dict, b: dict) -> dict:
    buckets = dict(a.get("buckets") or {})
    for key, n in (b.get("buckets") or {}).items():
        buckets[key] = buckets.get(key, 0) + n
    count = a.get("count", 0) + b.get("count", 0)
    out = {
        "count": count,
        "sum": a.get("sum", 0.0) + b.get("sum", 0.0),
        "min": min(a.get("min", math.inf), b.get("min", math.inf)),
        "max": max(a.get("max", -math.inf), b.get("max", -math.inf)),
        "buckets": buckets,
    }
    out.update(percentile_bounds(buckets, count))
    return out


def _merge_gauge(a: dict, b: dict) -> dict:
    last = dict(a.get("last") or
                ({"p0": a["value"]} if "value" in a else {}))
    last.update(b.get("last") or
                ({"p0": b["value"]} if "value" in b else {}))
    out = {
        "min": min(a.get("min", math.inf), b.get("min", math.inf)),
        "max": max(a.get("max", -math.inf), b.get("max", -math.inf)),
        "count": a.get("count", 0) + b.get("count", 0),
        "last": last,
    }
    # the merged headline value: the max over preserved per-process last
    # values — conservative for usage-shaped gauges (memory watermarks,
    # queue depth), where the worst process IS the fleet answer. For
    # quality-shaped gauges (obs.shadow.recall), max hides the degraded
    # process — direction-sensitive consumers must read ``last``/``min``,
    # which is exactly why the per-process values are preserved verbatim
    if last:
        out["value"] = max(last.values())
    return out


def merge_snapshots(snaps: Iterable[dict]) -> dict:
    """Fold snapshot dicts ({"counters": .., "timers": .., "histograms": ..,
    "gauges": ..}) into one fleet snapshot, exactly (module docstring). Left
    fold in input order; the operation is associative/commutative up to
    float summation order, and bit-exact for counters, histogram buckets and
    gauge last-value maps."""
    counters: dict = {}
    timers: dict = {}
    hists: dict = {}
    gauges: dict = {}
    for snap in snaps:
        for key, val in (snap.get("counters") or {}).items():
            counters[key] = counters.get(key, 0) + val
        for key, val in (snap.get("timers") or {}).items():
            timers[key] = _merge_timer(timers[key], val) if key in timers \
                else dict(val)
        for key, val in (snap.get("histograms") or {}).items():
            hists[key] = _merge_hist(hists[key], val) if key in hists \
                else _merge_hist({}, val)
        for key, val in (snap.get("gauges") or {}).items():
            gauges[key] = _merge_gauge(gauges[key], val) if key in gauges \
                else _merge_gauge({}, val)
    return {"counters": counters, "timers": timers, "histograms": hists,
            "gauges": gauges}


def merge_records(records: List[dict]) -> dict:
    """Fleet view from export_jsonl-shaped records: keep the NEWEST snapshot
    per (source, process_index) — each line is a cumulative snapshot of its
    process, so merging two generations of the same process would double
    count — then merge the survivors. Returns the merged snapshot plus
    provenance (``processes``, ``process_count``, t range)."""
    latest: dict = {}
    for rec in records:
        src = rec.get("_source", "")
        key = (src, rec.get("process_index", 0))
        prev = latest.get(key)
        if prev is None or rec.get("t", 0) >= prev.get("t", 0):
            latest[key] = rec
    picked = [latest[k] for k in sorted(latest, key=str)]
    merged = merge_snapshots(picked)
    procs = sorted({r.get("process_index", 0) for r in picked})
    merged["processes"] = procs
    merged["process_count"] = max(
        [r.get("process_count", 1) for r in picked] + [len(procs)])
    ts = [r["t"] for r in picked if isinstance(r.get("t"), (int, float))]
    if ts:
        merged["t_min"] = min(ts)
        merged["t_max"] = max(ts)
    return merged


def read_jsonl(path: str) -> List[dict]:
    """Parse one metrics JSONL file, skipping torn/corrupt lines (the same
    tolerance bench/progress.read_progress gives heartbeat files). Each
    record is tagged with its source path for per-process dedup."""
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    rec["_source"] = path
                    records.append(rec)
    except OSError:
        return []
    return records


def merge_files(paths: Iterable[str]) -> dict:
    """Read + merge any number of per-process metrics JSONL files."""
    records: List[dict] = []
    sources = []
    for path in paths:
        recs = read_jsonl(path)
        if recs:
            sources.append(path)
        records.extend(recs)
    out = merge_records(records)
    out["sources"] = sources
    return out


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs.aggregate",
        description="Merge per-process obs metrics JSONL files into one "
                    "fleet-wide snapshot (exact for counters and "
                    "power-of-two histograms).")
    ap.add_argument("files", nargs="+", help="metrics JSONL files")
    ap.add_argument("--output", default=None, metavar="PATH",
                    help="write the fleet view here instead of stdout")
    ap.add_argument("--indent", type=int, default=2)
    args = ap.parse_args(argv)
    fleet = merge_files(args.files)
    if not fleet.get("sources"):
        print("aggregate: no parseable records in "
              f"{', '.join(args.files)}", file=sys.stderr)
        return 2
    text = json.dumps(fleet, indent=args.indent, sort_keys=True)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
            f.flush()
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
