"""Online recall estimation: shadow-sample served queries off the hot path.

Recall is the one serving SLO that, until now, only existed offline — bench
runs measure it against precomputed ground truth, but a live index that
drifts (upserts, deletes, a lost shard) degrades recall silently. The
:class:`ShadowSampler` closes that gap the way serving systems do: a
**deterministic, seeded** fraction of served queries
(``RAFT_TPU_OBS_SHADOW_RATE``) is re-run through an exact search
*off the hot path* — background thread, bounded queue, drop-on-pressure —
and each shadow result scores the served top-k against the exact top-k.
The running ``(matched, total)`` slot counts feed a live recall@k estimate
with a Wilson binomial confidence interval, which is exactly the shape the
recall SLO burn rate (obs/slo.py) consumes.

Failure contract (the round-7 invariant): the shadow path must never block
or fail a serving request. ``offer()`` is the only hot-path touch — one
seeded-hash decision and, for sampled queries, one bounded-deque append
(full queue ⇒ drop, counted). The worker runs each exact search under a
hard :class:`~raft_tpu.resilience.Deadline` behind the
``obs.shadow.search`` faultpoint; any failure is routed through
``resilience.classify`` into a ``shadow_error`` event and the estimate
degrades to **stale** until the next successful sample.

Sampling decisions hash ``(seed, sequence_number)`` (the resilience
backoff-jitter pattern — no wall clock, no global RNG), so the sampled
subset is reproducible for tests and replayable across runs.
"""

from __future__ import annotations

import hashlib
import math
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from raft_tpu import obs, resilience
from raft_tpu.resilience.retry import record_event

__all__ = ["RATE_ENV", "ShadowSampler", "sample_decision", "wilson_interval"]

RATE_ENV = "RAFT_TPU_OBS_SHADOW_RATE"

#: z for the 95% Wilson interval
_Z95 = 1.959963984540054


def default_rate() -> float:
    """The shadow fraction from ``RAFT_TPU_OBS_SHADOW_RATE`` (0 disables;
    values clamp into [0, 1]; unset/garbage ⇒ 0)."""
    raw = os.environ.get(RATE_ENV, "").strip()
    try:
        return min(1.0, max(0.0, float(raw))) if raw else 0.0
    except ValueError:
        return 0.0


def sample_decision(seed: int, seq: int, rate: float) -> bool:
    """Deterministic Bernoulli(rate) draw for the ``seq``-th offer: a
    blake2b hash of ``(seed, seq)`` mapped to [0, 1) — the same
    no-clock/no-global-RNG determinism contract as the retry jitter."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = hashlib.blake2b(f"{seed}:{seq}".encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64 < rate


def wilson_interval(matched: int, total: int) -> tuple:
    """(low, high) 95% Wilson score interval for a binomial proportion —
    well-behaved at the boundaries (recall 1.0 with few samples gets a
    wide, honest interval instead of [1, 1])."""
    if total <= 0:
        return (0.0, 1.0)
    p = matched / total
    z2 = _Z95 * _Z95
    denom = 1.0 + z2 / total
    center = (p + z2 / (2.0 * total)) / denom
    half = (_Z95 * math.sqrt(p * (1.0 - p) / total
                             + z2 / (4.0 * total * total))) / denom
    # the interval must CONTAIN the point estimate; at the boundaries the
    # exact bound equals p and float rounding can land a hair inside it
    low = max(0.0, min(center - half, p))
    high = min(1.0, max(center + half, p))
    return (low, high)


class ShadowSampler:
    """Re-run a seeded fraction of served queries through exact search and
    keep a live recall@k estimate.

    ``exact_fn(queries_2d) -> (vals, ids)`` is the exact reference — for a
    paged store, the store's own scan at ``n_probes = n_lists`` (exact over
    the *current* corpus, so upserted rows are scored fairly); for a static
    index, a brute-force closure.

    Drive it with the background worker (:meth:`start`/:meth:`stop`) in
    serving, or synchronously (:meth:`pump`) in deterministic tests.
    """

    def __init__(self, exact_fn: Callable, *, k: int,
                 rate: Optional[float] = None, seed: int = 0,
                 max_pending: int = 64, timeout_s: float = 30.0):
        self._exact_fn = exact_fn
        self.k = int(k)
        self.rate = default_rate() if rate is None else \
            min(1.0, max(0.0, float(rate)))
        self.seed = int(seed)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._pending: deque = deque()  # guarded-by: _lock
        self._max_pending = max(1, int(max_pending))
        self._seq = 0      # guarded-by: _lock
        self._matched = 0  # guarded-by: _lock
        self._total = 0    # guarded-by: _lock
        self._samples = 0  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        self._errors = 0   # guarded-by: _lock
        self._stale = True  # guarded-by: _lock -- no data yet: stale until the first sample
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- hot-path side ------------------------------------------------------
    def offer(self, query, served_ids, trace_id: Optional[str] = None) -> bool:
        """Hot-path entry: decide (seeded hash), enqueue or drop. Returns
        True when the query was enqueued for shadowing. Never blocks, never
        raises past the decision: a full queue drops the sample (counted),
        never delays the request."""
        with self._lock:
            seq = self._seq
            self._seq += 1
            if not sample_decision(self.seed, seq, self.rate):
                return False
            if len(self._pending) >= self._max_pending:
                self._dropped += 1
                drop = True
            else:
                self._pending.append(
                    (np.asarray(query, np.float32).reshape(1, -1),
                     np.asarray(served_ids).reshape(-1), trace_id))
                drop = False
        if obs.enabled():
            obs.add("obs.shadow.dropped" if drop else "obs.shadow.offered")
        return not drop

    # -- shadow side --------------------------------------------------------
    def _score(self, item) -> None:
        query, served, trace_id = item
        with obs.record_span("obs.shadow::search",
                             attrs={"trace_id": trace_id}
                             if obs.enabled() else None):
            resilience.faultpoint("obs.shadow.search")
            # hard deadline: a hung exact search (the round-5 wedge class)
            # must cost the shadow sample, never wedge the worker
            with resilience.Deadline(self.timeout_s, label="obs.shadow"):
                _, exact_ids = self._exact_fn(query)
        exact = set(int(i) for i in np.asarray(exact_ids).reshape(-1)[:self.k]
                    if int(i) >= 0)
        got = [int(i) for i in served[:self.k] if int(i) >= 0]
        matched = sum(1 for i in got if i in exact)
        total = max(len(exact), 1)
        with self._lock:
            self._matched += matched
            self._total += total
            self._samples += 1
            self._stale = False
        if obs.enabled():
            obs.add("obs.shadow.samples")
            obs.add("obs.shadow.slots", total)
            obs.add("obs.shadow.slot_misses", total - matched)
            est = self.estimate()
            if est["recall"] is not None:
                obs.set_gauge("obs.shadow.recall", est["recall"])

    def pump(self) -> bool:
        """Process ONE queued shadow sample synchronously; True when there
        was one. The deterministic test/bench driver — same scoring path as
        the worker, including the stale-on-failure contract."""
        with self._lock:
            item = self._pending.popleft() if self._pending else None
        if item is None:
            return False
        try:
            self._score(item)
        except Exception as e:
            # never propagate: a shadow failure costs the estimate its
            # freshness, classified, and nothing else
            kind = resilience.classify(e)
            with self._lock:
                self._errors += 1
                self._stale = True
            if obs.enabled():
                obs.add(f"obs.shadow.errors.{kind}")
            record_event("shadow_error", site="obs.shadow.search",
                         kind=kind, error=repr(e)[:200])
        return True

    def drain(self, timeout_s: float = 30.0) -> None:
        """Pump until the queue is empty (worker running or not)."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            with self._lock:
                empty = not self._pending
            if empty:
                return
            if self._worker is None or not self._worker.is_alive():
                self.pump()
            else:
                time.sleep(1e-3)

    # -- worker -------------------------------------------------------------
    def start(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._stop.clear()
        self._worker = threading.Thread(
            target=self._run, name="raft-tpu-shadow", daemon=True)
        self._worker.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self.pump():
                self._stop.wait(timeout=5e-3)

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        if drain:
            self.drain(timeout_s=timeout_s)
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None

    # -- estimate -----------------------------------------------------------
    def counts(self) -> tuple:
        """Cumulative ``(matched, total)`` shadow slot counts — the
        good/bad source the recall SLO burn rate consumes."""
        with self._lock:
            return self._matched, self._total

    def estimate(self) -> dict:
        """Live recall estimate: ``{"recall", "ci_low", "ci_high",
        "samples", "slots", "dropped", "errors", "stale"}``. ``recall`` is
        None until the first successful sample; ``stale`` is True then and
        after any classified shadow failure (cleared by the next success).
        """
        with self._lock:
            matched, total = self._matched, self._total
            samples, dropped = self._samples, self._dropped
            errors, stale = self._errors, self._stale
        low, high = wilson_interval(matched, total)
        return {
            "recall": matched / total if total else None,
            "ci_low": low if total else 0.0,
            "ci_high": high if total else 1.0,
            "samples": samples,
            "slots": total,
            "dropped": dropped,
            "errors": errors,
            "stale": stale,
        }
