"""Roofline plane: per-dispatch FLOP/byte model + MXU/HBM utilization gauges.

The compute twin of :mod:`obs.costmodel` (round 14): the cost model answers
"will this dispatch FIT", this module answers "is the hardware actually
being USED". TPU-KNN (PAPERS.md) frames TPU ANN search entirely in
peak-FLOP/s terms, and the ROADMAP's standing worry — no TPU headline has
moved since r04, the fused CAGRA hop may underfill the MXU — has had
nothing but guesswork behind it. The same property that made the HBM cost
model exact makes a compute model tractable: every dispatch's shapes are
**capacity-padded and enumerable**, so FLOPs and bytes-moved are closed
forms of the layout parameters, computable before anything runs.

Per registered entry (the costmodel/compile registries' dispatch surface —
ivf_flat/pq/bq scans incl. paged, brute_force, the fused CAGRA hop, the
serving scatter):

* :func:`estimate_flops` — static FLOPs (matmul convention: 2 per MAC,
  plus the documented per-candidate bias/scale terms) and bytes-moved
  (operand streams + outputs, capacity-padded; strip-shaped scans share
  one list fetch across the ``C`` query slots of a strip — the planner's
  best-case packing, which the bench regime achieves), and the derived
  arithmetic intensity. EXACT against a hand-counted tiny-shape oracle
  (tier-1 + check.sh, zero tolerance: the formula IS the op sequence).
* :func:`platform_peaks` — per-generation peak table selected by
  ``jax.devices()[0].device_kind`` (TPU v2→v6e, bf16 dense MXU peak +
  HBM bandwidth), overridable for unlisted platforms via
  ``RAFT_TPU_OBS_PEAK_FLOPS`` / ``RAFT_TPU_OBS_PEAK_BW``; an honest CPU
  fallback answers ``source="unknown"`` and every derived utilization is
  marked ``peaks_unknown`` instead of being invented.
* :func:`utilization` — the roofline fold: time bound
  ``max(flops/peak_flops, bytes/peak_bw)``, ``bound ∈ {compute, memory,
  unknown}``, and — given a measured duration — ``achieved_gflops``,
  ``mxu_utilization``, ``hbm_bw_utilization`` and
  ``model_to_measured`` (bound/measured, ≤1 by construction; how much of
  the gap is overhead vs the model being optimistic).
* The measured leg rides the existing ``RAFT_TPU_OBS_SYNC`` device-time
  attribution: sync-mode spans now fold their committed durations into
  ``dispatch.<span>`` histograms (obs/registry), and :func:`summary`
  pairs each noted entry with its histogram mean, so every hot entry
  carries ``(predicted_bound_s, measured_s, mxu_utilization,
  hbm_bw_utilization, bound)`` as gauges.
* Occupancy: the three Pallas kernels expose static diagnostics from
  their OWN planning code (``strip_scan.occupancy_stats`` /
  ``bq_scan.occupancy_stats`` / ``cagra_hop.occupancy_stats``) —
  padded-row/padded-strip fraction, tile fill, grid shape — so "the
  kernel underfills the MXU" is a number, not a hunch.
* :func:`xla_cost_analysis` — the compiler cross-check: where the
  backend's ``compiled.cost_analysis()`` reports ``flops``, the static
  model is validated against it (tier-1 pins agreement within a
  documented band at the matmul level; the backend may fold constants or
  skip transcendentals, so the band is 2×, not exact).

Dispatch sites call :func:`note_dispatch` behind their existing
``obs.enabled()`` gate (telemetry off ⇒ zero roofline work on the hot
path — tier-1 NOOP-gated); ``obs.report.collect()`` folds
:func:`summary` in as the ``roofline`` section, and the bench stamps
``mxu_utilization`` / ``bound`` / ``padded_fraction`` /
``achieved_gflops`` next to every ``predicted_index_bytes`` — the
per-config efficiency record the r06/r08/r09 TPU-cheque session and the
item-3 autotuner frontier fit consume.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

import numpy as np

from raft_tpu import obs

__all__ = [
    "BOUND_COMPUTE",
    "BOUND_MEMORY",
    "BOUND_UNKNOWN",
    "PEAK_BW_ENV",
    "PEAK_FLOPS_ENV",
    "dispatch_histogram",
    "entries",
    "estimate_flops",
    "estimate_search_flops",
    "memo_occupancy",
    "note_dispatch",
    "note_search",
    "platform_peaks",
    "reset",
    "summary",
    "utilization",
    "utilization_search",
    "xla_cost_analysis",
]

PEAK_FLOPS_ENV = "RAFT_TPU_OBS_PEAK_FLOPS"
PEAK_BW_ENV = "RAFT_TPU_OBS_PEAK_BW"

BOUND_COMPUTE, BOUND_MEMORY, BOUND_UNKNOWN = "compute", "memory", "unknown"

#: strip query slots (ops/strip_scan.C) — the cross-query sharing factor of
#: one strip fetch. Mirrored here as a plain constant so the model stays
#: importable in jax-free parents (strip_scan imports pallas at module load).
STRIP_C = 192

# ---------------------------------------------------------------------------
# per-platform peaks
# ---------------------------------------------------------------------------

#: (pattern, peak bf16 dense FLOP/s, peak HBM bytes/s) per chip — public
#: spec-sheet numbers, matched against a lowercased ``device_kind``.
#: Ordered: the FIRST matching pattern wins, so the lite/p variants sit
#: above their base generation.
_PEAK_TABLE = (
    ("v6e", 918e12, 1640e9),
    ("v6 lite", 918e12, 1640e9),
    ("trillium", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v5", 459e12, 2765e9),
    ("v4 lite", 138e12, 614e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 46e12, 700e9),
)


def _env_float(env: str) -> Optional[float]:
    raw = os.environ.get(env, "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def _device_kind() -> str:
    """``jax.devices()[0].device_kind`` ONLY from an already-initialized
    backend (the obs/memory ``_live_jax`` contract: a telemetry read must
    never pay first-touch backend init — the round-5 wedge class)."""
    jax = sys.modules.get("jax")
    xb = sys.modules.get("jax._src.xla_bridge")
    if jax is None or xb is None or not getattr(xb, "_backends", None):
        return ""
    try:
        devs = jax.local_devices()
        return str(devs[0].device_kind) if devs else ""
    # a backend without device_kind is a supported state — the peaks just
    # degrade to unknown, which every consumer handles
    except Exception:  # graftlint: ignore[unclassified-except]
        return ""


def platform_peaks() -> dict:
    """``{"peak_flops", "peak_bw", "source", "device_kind"}`` — the
    roofline denominators. Resolution order: the env overrides
    (``RAFT_TPU_OBS_PEAK_FLOPS`` / ``RAFT_TPU_OBS_PEAK_BW``, for unlisted
    platforms and CPU preview runs), then the per-generation table keyed
    by ``device_kind``, else zeros with ``source="unknown"`` — utilization
    against an invented peak would be worse than none."""
    env_f, env_b = _env_float(PEAK_FLOPS_ENV), _env_float(PEAK_BW_ENV)
    kind = _device_kind()
    if env_f and env_b:
        return {"peak_flops": env_f, "peak_bw": env_b, "source": "env",
                "device_kind": kind}
    # a PARTIAL override is ignored entirely: folding one synthetic peak
    # into the table's other would produce a half-made-up denominator
    # stamped with spec-sheet provenance — the exact failure the
    # source field exists to prevent (both knobs or neither)
    low = kind.lower()
    for pat, pf, pb in _PEAK_TABLE:
        if pat in low:
            return {"peak_flops": pf, "peak_bw": pb,
                    "source": "table", "device_kind": kind}
    return {"peak_flops": 0.0, "peak_bw": 0.0,
            "source": "unknown", "device_kind": kind}


# ---------------------------------------------------------------------------
# static FLOP / byte models (capacity-padded closed forms)
# ---------------------------------------------------------------------------


def _isize(dtype) -> int:
    return int(np.dtype(dtype).itemsize)


def _ceil_div(a: int, b: int) -> int:
    return -(-int(a) // int(b))


def _rot_dim_pq(dim: int, pq_dim: int, rot_dim) -> int:
    return int(rot_dim) if rot_dim else pq_dim * _ceil_div(dim, pq_dim)


def _rot_dim_bq(dim: int, rot_dim, rotation_kind: str = "dense") -> int:
    if rot_dim:
        return int(rot_dim)
    if rotation_kind == "hadamard":
        # the Walsh–Hadamard width: next power of two, not byte-rounding
        return max(8, 1 << (max(int(dim), 1) - 1).bit_length())
    return _ceil_div(dim, 8) * 8


def _fb_brute_force_search(*, q, n, dim, k, dtype="float32"):
    """One tiled exact scan: the (q, n) gemm + the norm/bias add."""
    flops = 2 * q * n * dim + q * n
    br = q * dim * 4 + n * dim * _isize(dtype) + n * 4
    return flops, br, q * k * 8


def _fb_ivf_flat_search(*, q, dim, n_lists, max_list_size, n_probes, k,
                        dtype="float32"):
    """Coarse gemm + strip scan over capacity-padded lists. List traffic
    is one fetch per FULL strip (``STRIP_C`` query-pairs share it — the
    planner's best-case packing): data + per-entry bias + the merge's id
    row."""
    coarse = 2 * q * n_lists * dim
    scan = 2 * q * n_probes * max_list_size * dim \
        + q * n_probes * max_list_size
    strips = _ceil_div(q * n_probes, STRIP_C)
    br = q * dim * 4 + n_lists * dim * 4 \
        + strips * max_list_size * (dim * _isize(dtype) + 4 + 4)
    return coarse + scan, br, q * k * 8


def _fb_ivf_pq_search(*, q, dim, n_lists, max_list_size, pq_dim, n_probes,
                      k, pq_bits=8, rot_dim=None):
    """The TPU-default decoded-int8 strip scan: coarse gemm + query
    rotation + one rot_dim-wide contraction per probed entry (+ bias add).
    Strip traffic reads the int8 cache at 1 byte/dim."""
    rd = _rot_dim_pq(dim, pq_dim, rot_dim)
    coarse = 2 * q * n_lists * dim
    rotate = 2 * q * dim * rd
    scan = 2 * q * n_probes * max_list_size * rd \
        + q * n_probes * max_list_size
    strips = _ceil_div(q * n_probes, STRIP_C)
    br = q * dim * 4 + n_lists * dim * 4 + rd * rd * 4 \
        + strips * max_list_size * (rd + 4 + 4)
    return coarse + rotate + scan, br, q * k * 8


def _log2i(n: int) -> int:
    return max(int(n), 1).bit_length() - 1


def _rotate_cost(q: int, dim: int, rd: int, rotation_kind: str):
    """(flops, rotation-operand bytes) of rotating ``q`` rows up to width
    ``rd``: the dense gemm (2 per MAC, (rd, rd) fp32 operand) or the SRHT
    butterfly — the sign multiply, log2(rd) full-width add/sub stages and
    the 1/√d scale, with only the (rd,) sign diagonal as its operand."""
    if rotation_kind == "hadamard":
        return q * rd * (_log2i(rd) + 2), rd * 4
    return 2 * q * dim * rd, rd * rd * 4


def _fb_ivf_bq_search(*, q, dim, n_lists, max_list_size, n_probes, k,
                      rot_dim=None, bits=1, rotation_kind="dense"):
    """The packed multi-bit strip scan: coarse gemm + rotation (dense gemm
    or SRHT butterfly) + one bits·rot_dim-wide contraction per probed
    entry (every extra bit-plane widens the MXU contraction), plus the
    per-entry scale multiply AND bias add. Strip traffic reads
    bits·rot_dim/8 code bytes + two fp32 scalars per entry."""
    rd = _rot_dim_bq(dim, rot_dim, rotation_kind)
    coarse = 2 * q * n_lists * dim
    rotate, rot_bytes = _rotate_cost(q, dim, rd, rotation_kind)
    scan = 2 * q * n_probes * max_list_size * rd * bits \
        + 2 * q * n_probes * max_list_size
    strips = _ceil_div(q * n_probes, STRIP_C)
    br = q * dim * 4 + n_lists * dim * 4 + rot_bytes \
        + strips * max_list_size * (bits * rd // 8 + 4 + 4 + 4)
    return coarse + rotate + scan, br, q * k * 8


def _fb_ivf_flat_build(*, n, dim, n_lists, kmeans_iters=20, train_rows=0,
                       dtype="float32"):
    """One packed IVF-Flat build, kmeans-dominated: per CONFIGURED EM
    iteration one assign gemm + one M-step one-hot matmul over the
    trainset (4·tr·K·d — the balancing loop may extend past the
    configured budget, so this is the floor the build can't beat), the
    full-data predict, and the row-norm reduction. Bytes: the trainset
    re-streamed per iteration, the dataset twice (predict + pack read),
    the packed block written."""
    tr = train_rows or n
    flops = kmeans_iters * 4 * tr * n_lists * dim \
        + 2 * n * n_lists * dim + 2 * n * dim
    br = (kmeans_iters + 1) * tr * dim * 4 + 2 * n * dim * 4
    bw = n * (dim * _isize(dtype) + 4 + 4)
    return flops, br, bw


def _fb_ivf_pq_build(*, n, dim, n_lists, pq_dim, kmeans_iters=20,
                     codebook_iters=25, train_rows=0, cb_rows=0,
                     pq_bits=8, rot_dim=None):
    """One packed IVF-PQ build: the flat build's kmeans legs + per-subspace
    codebook Lloyd (4·cbr·n_codes·rot_dim per configured iteration) + the
    dense rotation of every row + the encode's code-scoring einsum
    (2·n·n_codes·rot_dim). Writes packed codes + ids + b_sum."""
    tr = train_rows or n
    rd = _rot_dim_pq(dim, pq_dim, rot_dim)
    n_codes = 1 << pq_bits
    cbr = cb_rows or min(tr, 65536)
    flops = kmeans_iters * 4 * tr * n_lists * dim \
        + 2 * n * n_lists * dim \
        + codebook_iters * 4 * cbr * n_codes * rd \
        + 2 * n * dim * rd + 2 * n * n_codes * rd
    br = (kmeans_iters + 1) * tr * dim * 4 + 2 * n * dim * 4 + rd * rd * 4
    bw = n * ((pq_dim * pq_bits + 7) // 8 + 4 + 4)
    return flops, br, bw


def _fb_ivf_bq_build(*, n, dim, n_lists, kmeans_iters=20, train_rows=0,
                     rot_dim=None, bits=1, rotation_kind="dense"):
    """One IVF-BQ build (packed or streamed — the op sequence is the
    same): the flat build's kmeans legs + the rotation of every row
    (dense gemm or SRHT butterfly — THE build-cost headline this round:
    O(d²) → O(d·log d) per row) + the level quantize and the
    norm/projection/bias reductions (rd·(2·bits + 4) per row, counting
    the quantize compare/scale ops per plane and the three einsum-grade
    reductions). Writes packed codes + ids + the two fp32 scalars. BQ has
    NO codebook leg — that is the IVF-RaBitQ build-time headline."""
    tr = train_rows or n
    rd = _rot_dim_bq(dim, rot_dim, rotation_kind)
    rot_f, rot_bytes = _rotate_cost(n, dim, rd, rotation_kind)
    flops = kmeans_iters * 4 * tr * n_lists * dim \
        + 2 * n * n_lists * dim + rot_f + n * rd * (2 * bits + 4)
    br = (kmeans_iters + 1) * tr * dim * 4 + 2 * n * dim * 4 + rot_bytes
    bw = n * (bits * rd // 8 + 8 + 4)
    return flops, br, bw


def _fb_srht_apply(*, n, rot_dim):
    """One SRHT rotation apply (ops/linalg.srht_rotate): the sign
    multiply, log2(rot_dim) butterfly add/sub stages and the 1/√d scale —
    n·rot_dim·(log2(rot_dim) + 2) VPU flops against n·rot_dim fp32 rows
    in/out and the (rot_dim,) sign diagonal. The O(d·log d)-vs-O(d²)
    build-cost claim as a number."""
    flops = n * rot_dim * (_log2i(rot_dim) + 2)
    br = n * rot_dim * 4 + rot_dim * 4
    return flops, br, n * rot_dim * 4


def _fb_ivf_flat_paged(*, q, dim, n_lists, page_rows, table_width,
                       n_probes, k, dtype="float32", capacity_pages=0):
    """The paged gather scan: per (query, probe) the whole capacity-padded
    chain (table_width × page_rows entries) is gathered — NO cross-query
    sharing (that is exactly what ROADMAP item 2's paged-Pallas merge
    would buy back, and what this model makes visible)."""
    ent = n_probes * table_width * page_rows
    coarse = 2 * q * n_lists * dim
    scan = 2 * q * ent * dim + q * ent
    br = q * dim * 4 + n_lists * dim * 4 \
        + q * ent * (dim * _isize(dtype) + 4 + 4)
    return coarse + scan, br, q * k * 8


def _fb_ivf_pq_paged(*, q, dim, n_lists, page_rows, table_width, pq_dim,
                     n_probes, k, pq_bits=8, rot_dim=None,
                     capacity_pages=0):
    """The paged PQ gather scan: coarse + rotation + per-query LUT build
    (pq_dim × 2^bits × dsub MACs = 2·q·2^bits·rot_dim flops) + pq_dim
    lookup-adds per gathered candidate (2 ops each: gather + add)."""
    rd = _rot_dim_pq(dim, pq_dim, rot_dim)
    n_codes = 1 << pq_bits
    code_w = (pq_dim * pq_bits + 7) // 8
    ent = n_probes * table_width * page_rows
    coarse = 2 * q * n_lists * dim
    rotate = 2 * q * dim * rd
    luts = 2 * q * n_codes * rd
    scan = 2 * q * ent * pq_dim
    br = q * dim * 4 + n_lists * dim * 4 + rd * rd * 4 \
        + pq_dim * n_codes * (rd // pq_dim) * 4 \
        + q * ent * (code_w + 4 + 4)
    return coarse + rotate + luts + scan, br, q * k * 8


def _fb_ivf_flat_paged_pallas(*, q, dim, n_lists, page_rows, table_width,
                              n_probes, k, dtype="float32"):
    """The paged Pallas strip scan (round 16): coarse gemm + one
    rot-free contraction per capacity-chain row (+ bias add). Byte
    streams are PAGE-granular and strip-shared: one chain fetch (payload
    pages + the bias pool's rows) serves the ``STRIP_C`` query slots of a
    strip — the cross-query sharing the gather model cannot have. The
    model is capacity-padded by convention (the runtime skip path prunes
    dead pages; occupancy stats carry the live fractions)."""
    ent = table_width * page_rows
    coarse = 2 * q * n_lists * dim
    scan = 2 * q * n_probes * ent * dim + q * n_probes * ent
    strips = _ceil_div(q * n_probes, STRIP_C)
    br = q * dim * 4 + n_lists * dim * 4 \
        + strips * ent * (dim * _isize(dtype) + 4)
    return coarse + scan, br, q * k * 8


def _fb_ivf_pq_paged_pallas(*, q, dim, n_lists, page_rows, table_width,
                            pq_dim, n_probes, k, pq_bits=8, rot_dim=None):
    """The paged PQ Pallas scan: coarse gemm + query rotation + one
    rot_dim-wide int8 contraction per capacity-chain row (+ bias add) —
    the decoded-cache formulation, paged. Streams the int8 cache pool at
    1 byte/dim + the 4-byte bias row, strip-shared."""
    rd = _rot_dim_pq(dim, pq_dim, rot_dim)
    ent = table_width * page_rows
    coarse = 2 * q * n_lists * dim
    rotate = 2 * q * dim * rd
    scan = 2 * q * n_probes * ent * rd + q * n_probes * ent
    strips = _ceil_div(q * n_probes, STRIP_C)
    br = q * dim * 4 + n_lists * dim * 4 + rd * rd * 4 \
        + strips * ent * (rd + 4)
    return coarse + rotate + scan, br, q * k * 8


def _fb_ivf_bq_paged_pallas(*, q, dim, n_lists, page_rows, table_width,
                            n_probes, k, rot_dim=None, bits=1,
                            rotation_kind="dense"):
    """The paged multi-bit Pallas scan: coarse gemm + rotation + one
    bits·rot_dim-wide contraction per capacity-chain row, plus the per-row
    scale multiply AND bias add. Streams bits·rot_dim/8 code bytes + two
    fp32 scalars per row, strip-shared."""
    rd = _rot_dim_bq(dim, rot_dim, rotation_kind)
    ent = table_width * page_rows
    coarse = 2 * q * n_lists * dim
    rotate, rot_bytes = _rotate_cost(q, dim, rd, rotation_kind)
    scan = 2 * q * n_probes * ent * rd * bits + 2 * q * n_probes * ent
    strips = _ceil_div(q * n_probes, STRIP_C)
    br = q * dim * 4 + n_lists * dim * 4 + rot_bytes \
        + strips * ent * (bits * rd // 8 + 4 + 4)
    return coarse + rotate + scan, br, q * k * 8


def _fb_cagra_fused_hop(*, q, width, degree, proj_dim, itopk, hops=1):
    """One fused traversal hop per query block: the int8→bf16 distance
    contraction (ip + norm: 4·q·b·p), and the two exact one-hot payload
    extractions over the (itopk, itopk+b) merge (2·2·q·itopk·cat). The
    VPU dedup compare-matrix is not MXU work and is deliberately not
    counted. Traffic: parent graph rows + inlined code records (the
    in-kernel DMAs) + the three candidate buffers in and out."""
    b = width * degree
    cat = itopk + b
    flops = hops * (4 * q * b * proj_dim + 4 * q * itopk * cat)
    br = hops * (q * b * 4 + q * b * proj_dim + q * proj_dim * 4
                 + 3 * q * itopk * 4)
    bw = hops * (3 * q * itopk * 4)
    return flops, br, bw


def _fb_serving_scatter(*, n_rows, dim, payload_width,
                        payload_dtype="float32", extra_row_bytes=0):
    """One pow2-bucketed append scatter: pure data movement (flops = 0 —
    memory-bound by construction). Reads the incoming rows, writes the
    bucketed payload + id + aux + scan-bias slots, plus the kind-specific
    extra pool row (``extra_row_bytes``: PQ int8 decoded cache = rot_dim,
    BQ scale = 4, flat = 0)."""
    bucket = 1 << max(0, int(n_rows - 1).bit_length())
    br = n_rows * dim * 4
    bw = bucket * (payload_width * _isize(payload_dtype) + 4 + 4 + 4
                   + int(extra_row_bytes))
    return 0, br, bw


def _fb_maint_reencode(*, n_rows, dim, rot_dim=0, pq_dim=0, n_codes=0):
    """One maintenance re-encode pass over the cycle's affected rows
    (serving/maintenance.py): the residual rotation (2·n·rot_dim·dim
    MACs → 2 flops each; rot_dim = 0 for flat stores, which re-encode
    nothing) plus, for PQ, the per-subspace nearest-codeword search
    (n·pq_dim·n_codes·dsub MACs with dsub = rot_dim/pq_dim). Traffic:
    the float32 rows in, the rotated residual out — the code packing
    rides the same dispatch and is byte-noise next to it."""
    flops = 2 * n_rows * rot_dim * dim
    if pq_dim and n_codes:
        dsub = rot_dim // max(1, pq_dim)
        flops += 2 * n_rows * pq_dim * n_codes * dsub
    br = n_rows * dim * 4
    bw = n_rows * rot_dim * 4
    return flops, br, bw


_MODELS = {
    "brute_force.search": _fb_brute_force_search,
    "ivf_flat.search": _fb_ivf_flat_search,
    "ivf_flat.paged_scan": _fb_ivf_flat_paged,
    "ivf_flat.paged_pallas": _fb_ivf_flat_paged_pallas,
    "ivf_pq.search": _fb_ivf_pq_search,
    "ivf_pq.paged_scan": _fb_ivf_pq_paged,
    "ivf_pq.paged_pallas": _fb_ivf_pq_paged_pallas,
    "ivf_bq.search": _fb_ivf_bq_search,
    "ivf_bq.paged_pallas": _fb_ivf_bq_paged_pallas,
    "cagra.fused_hop": _fb_cagra_fused_hop,
    "serving.scatter": _fb_serving_scatter,
    "serving.maintenance.reencode": _fb_maint_reencode,
    "linalg.srht_apply": _fb_srht_apply,
    "ivf_flat.build": _fb_ivf_flat_build,
    "ivf_pq.build": _fb_ivf_pq_build,
    "ivf_bq.build": _fb_ivf_bq_build,
}

#: dispatch entry → the span whose sync-mode committed durations measure
#: it (``dispatch.<span>`` histograms, obs/registry round-15 satellite)
_SPAN_OF = {
    "brute_force.search": "brute_force::search",
    "ivf_flat.search": "ivf_flat::scan",
    "ivf_flat.paged_scan": "ivf_flat::paged_scan",
    "ivf_flat.paged_pallas": "ivf_flat::paged_pallas",
    "ivf_pq.search": "ivf_pq::scan",
    "ivf_pq.paged_scan": "ivf_pq::paged_scan",
    "ivf_pq.paged_pallas": "ivf_pq::paged_pallas",
    "ivf_bq.search": "ivf_bq::scan",
    "ivf_bq.paged_pallas": "ivf_bq::paged_pallas",
    "cagra.fused_hop": "cagra::hop",
    "serving.scatter": "serving::upsert",
    "serving.maintenance.reencode": "serving::maintenance_recluster",
}

# opt the modeled spans into the registry's sync-mode dispatch fold —
# only these earn `dispatch.*` histograms (folding every span would
# double histogram cardinality and label host spans as device dispatches)
from raft_tpu.obs.registry import register_dispatch_span as _reg_span

for _span_name in set(_SPAN_OF.values()):
    _reg_span(_span_name)
del _reg_span


def estimate_flops(entry: str, **shapes) -> dict:
    """Static FLOPs and bytes-moved of ONE dispatch of ``entry`` from its
    capacity-padded layout parameters — the roofline numerators. FLOPs
    follow the matmul convention (2 per MAC) plus the documented
    per-candidate bias/scale terms; bytes are operand streams + outputs
    (strip scans share one list fetch across ``STRIP_C`` query slots —
    the planner's best-case packing). Exact vs the hand-counted
    tiny-shape oracle (tier-1 + check.sh, zero tolerance)."""
    with obs.record_span("obs.roofline::estimate_flops",
                         attrs={"entry": entry} if obs.enabled() else None):
        fn = _MODELS.get(entry)
        if fn is None:
            raise ValueError(
                f"unknown roofline entry {entry!r} (have {sorted(_MODELS)})")
        flops, br, bw = fn(**shapes)
        total = int(br + bw)
        return {
            "entry": entry,
            "flops": int(flops),
            "bytes_read": int(br),
            "bytes_written": int(bw),
            "bytes": total,
            "arithmetic_intensity": (round(flops / total, 4) if total
                                     else None),
        }


def _search_kwargs(index, q: int, k: int, n_probes: int) -> tuple:
    """``(entry, model kwargs)`` for a live index/store — the ONE place
    the layout (``costmodel.index_layout``, shared with the HBM
    predictor) is projected onto a model's keyword surface. Everything
    index-derived (estimate_search_flops / utilization_search /
    note_search) routes through here, so layout-only keys (``norms``,
    ``plan_cache``, ``payload_width``, …) can never leak into the
    keyword-only model functions."""
    # lazy: costmodel lazily imports neighbors/serving, an edge this
    # module must not force at import time
    from raft_tpu.obs import costmodel

    layout = costmodel.index_layout(index)
    kind = layout.pop("kind")
    if kind == "ivf_flat":
        return "ivf_flat.search", dict(
            q=q, k=k, n_probes=n_probes, dim=layout["dim"],
            n_lists=layout["n_lists"],
            max_list_size=layout["max_list_size"], dtype=layout["dtype"])
    if kind == "ivf_pq":
        return "ivf_pq.search", dict(
            q=q, k=k, n_probes=n_probes, dim=layout["dim"],
            n_lists=layout["n_lists"],
            max_list_size=layout["max_list_size"],
            pq_dim=layout["pq_dim"], pq_bits=layout["pq_bits"],
            rot_dim=layout["rot_dim"])
    if kind == "ivf_bq":
        return "ivf_bq.search", dict(
            q=q, k=k, n_probes=n_probes, dim=layout["dim"],
            n_lists=layout["n_lists"],
            max_list_size=layout["max_list_size"],
            rot_dim=layout["rot_dim"], bits=layout.get("bits", 1),
            rotation_kind=layout.get("rotation_kind", "dense"))
    if kind == "brute_force":
        return "brute_force.search", dict(
            q=q, k=k, n=layout["n"], dim=layout["dim"],
            dtype=layout["dtype"])
    if kind == "paged_store":
        # engine-aware (round 16): model the scan the auto backend would
        # actually dispatch — the paged Pallas strip engine where
        # eligible, the gather scan otherwise (ivf_bq has no gather path;
        # its jnp reference computes the same math as the kernel)
        from raft_tpu.neighbors.ivf_flat import paged_backend_auto

        sk = layout.get("store_kind")
        engine = paged_backend_auto(index, k)
        base = dict(q=q, k=k, n_probes=n_probes, dim=layout["dim"],
                    n_lists=layout["n_lists"],
                    page_rows=layout["page_rows"],
                    table_width=layout["table_width"])
        if sk == "ivf_bq":
            return "ivf_bq.paged_pallas", dict(
                base, rot_dim=layout["rot_dim"],
                bits=layout.get("bits", 1),
                rotation_kind=layout.get("rotation_kind", "dense"))
        if sk == "ivf_pq":
            pq_kw = dict(base, pq_dim=layout["pq_dim"],
                         pq_bits=layout["pq_bits"],
                         rot_dim=layout["rot_dim"])
            return (("ivf_pq.paged_pallas", pq_kw)
                    if engine != "gather" else ("ivf_pq.paged_scan", pq_kw))
        flat_kw = dict(base, dtype=layout["payload_dtype"])
        return (("ivf_flat.paged_pallas", flat_kw)
                if engine != "gather" else ("ivf_flat.paged_scan", flat_kw))
    raise ValueError(f"no roofline model for index family {kind!r}")


def estimate_search_flops(index, q: int, k: int, n_probes: int = 0) -> dict:
    """:func:`estimate_flops` with kwargs derived from a live index/store —
    the bench-section convenience (the costmodel.estimate_search twin)."""
    entry, kwargs = _search_kwargs(index, q, k, n_probes)
    return estimate_flops(entry, **kwargs)


# ---------------------------------------------------------------------------
# roofline fold (bound + utilization)
# ---------------------------------------------------------------------------


def _fold(est: dict, peaks: dict, measured_s: Optional[float],
          occupancy: Optional[dict]) -> dict:
    """The roofline fold over ONE estimate dict (shared by
    :func:`utilization` and :func:`summary`, whose estimate is a
    per-dispatch mean): bound + measured-leg utilizations."""
    out = dict(est)
    out["peaks_source"] = peaks["source"]
    known = peaks["peak_flops"] > 0 and peaks["peak_bw"] > 0
    if known:
        ct = est["flops"] / peaks["peak_flops"]
        mt = est["bytes"] / peaks["peak_bw"]
        out["compute_bound_s"] = ct
        out["memory_bound_s"] = mt
        out["predicted_bound_s"] = max(ct, mt)
        out["bound"] = BOUND_COMPUTE if ct >= mt else BOUND_MEMORY
    else:
        out["peaks_unknown"] = True
        out["predicted_bound_s"] = None
        out["bound"] = BOUND_UNKNOWN
    if measured_s is not None and measured_s > 0:
        out["measured_s"] = float(measured_s)
        out["achieved_gflops"] = round(est["flops"] / measured_s / 1e9, 3)
        if known:
            out["mxu_utilization"] = round(
                est["flops"] / measured_s / peaks["peak_flops"], 6)
            out["hbm_bw_utilization"] = round(
                est["bytes"] / measured_s / peaks["peak_bw"], 6)
            out["model_to_measured"] = round(
                out["predicted_bound_s"] / measured_s, 6)
        else:
            out["mxu_utilization"] = None
            out["hbm_bw_utilization"] = None
    else:
        out["measured_s"] = None
    if occupancy is not None:
        out["occupancy"] = dict(occupancy)
        if "padded_row_fraction" in occupancy:
            out["padded_fraction"] = occupancy["padded_row_fraction"]
    return out


def utilization(entry: str, measured_s: Optional[float] = None,
                occupancy: Optional[dict] = None, **shapes) -> dict:
    """One entry's roofline record: the static model, the per-platform
    time bound ``max(flops/peak_flops, bytes/peak_bw)`` with its binding
    side, and — when a measured duration is supplied —
    ``achieved_gflops`` / ``mxu_utilization`` / ``hbm_bw_utilization`` /
    ``model_to_measured``. With no discoverable peaks the record is
    honest: ``bound="unknown"``, ``peaks_unknown=True``, utilizations
    None (``achieved_gflops`` still reports — it needs no denominator)."""
    with obs.record_span("obs.roofline::utilization",
                         attrs={"entry": entry} if obs.enabled() else None):
        return _fold(estimate_flops(entry, **shapes), platform_peaks(),
                     measured_s, occupancy)


def utilization_search(index, q: int, k: int, n_probes: int = 0,
                       measured_s: Optional[float] = None,
                       occupancy: Optional[dict] = None) -> dict:
    """:func:`utilization` with model kwargs derived from a live
    index/store (the bench-stamp convenience)."""
    entry, kwargs = _search_kwargs(index, q, k, n_probes)
    return utilization(entry, measured_s=measured_s, occupancy=occupancy,
                       **kwargs)


# ---------------------------------------------------------------------------
# dispatch notes (the hot-path leg) + summary (the report leg)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_DISPATCHES: dict = {}   # entry -> {"shapes", "est", "occupancy", "count"}


def memo_occupancy(index, key: tuple, compute):
    """One-entry occupancy memo cached ON the index (the
    ``_lens_np_cache`` pattern): steady-state telemetry-on dispatches
    reuse the planner stats instead of re-running class_info/fit_q_tile/
    static_layout per call. ``key`` must capture everything the stats
    depend on (lens-cache identity, q, p, k, workspace); an index
    mutation replaces the lens cache object, which invalidates the key.
    Frozen containers that reject attribute writes just recompute."""
    cache = getattr(index, "_roofline_occ_cache", None)
    if cache is not None and cache[0] == key:
        return cache[1]
    occ = compute()
    try:
        index._roofline_occ_cache = (key, occ)
    except AttributeError:
        pass
    return occ


def note_dispatch(entry: str, shapes: dict,
                  occupancy: Optional[dict] = None) -> None:
    """Record one dispatch of ``entry`` (shape kwargs for the model, plus
    optional static occupancy stats from the kernel's planning code), so
    :func:`summary` can pair the static model with the measured
    ``dispatch.*`` histograms. FLOPs/bytes accumulate across dispatches
    (mixed shapes fold to honest per-dispatch means, not last-shape
    snapshots). NOOP when telemetry is off — callers gate, and the gate
    is re-checked here so a stray call costs one branch."""
    if not obs.enabled():
        return
    with _LOCK:
        cached = _DISPATCHES.get(entry)
        est = (cached["est"] if cached is not None
               and cached.get("shapes") == shapes else None)
    if est is None:
        # round-16 satellite: a steady-state burst of same-shape
        # dispatches (delete-heavy serving windows) reuses the last
        # estimate instead of re-running the closed form per call — the
        # model is a pure function of the shape kwargs
        est = estimate_flops(entry, **shapes)
    with _LOCK:
        rec = _DISPATCHES.get(entry)
        if rec is None:
            rec = _DISPATCHES[entry] = {"count": 0, "total_flops": 0,
                                        "total_bytes_read": 0,
                                        "total_bytes_written": 0}
        rec["count"] += 1
        rec["total_flops"] += est["flops"]
        rec["total_bytes_read"] += est["bytes_read"]
        rec["total_bytes_written"] += est["bytes_written"]
        rec["shapes"] = dict(shapes)
        rec["est"] = est
        if occupancy is not None:
            rec["occupancy"] = dict(occupancy)
    obs.set_gauge(f"roofline.{entry}.flops", est["flops"])
    obs.set_gauge(f"roofline.{entry}.bytes", est["bytes"])


def note_search(index, q: int, k: int, n_probes: int = 0,
                occupancy: Optional[dict] = None) -> None:
    """:func:`note_dispatch` from a live index/store (search-site sugar;
    the shared ``_search_kwargs`` projection, so layout-only keys can
    never poison the note registry)."""
    if not obs.enabled():
        return
    entry, kwargs = _search_kwargs(index, q, k, n_probes)
    note_dispatch(entry, kwargs, occupancy=occupancy)


def entries() -> dict:
    """{entry: dispatch-note record} for every entry noted so far."""
    with _LOCK:
        return {k: dict(v) for k, v in _DISPATCHES.items()}


def reset() -> None:
    """Clear the dispatch-note registry (tests)."""
    with _LOCK:
        _DISPATCHES.clear()


def dispatch_histogram(entry: str,
                       snapshot: Optional[dict] = None) -> Optional[dict]:
    """The ``dispatch.<span>`` histogram measuring ``entry`` (committed
    sync-mode durations; obs/registry), or None when sync attribution
    never ran for it."""
    from raft_tpu.obs.registry import DISPATCH_HIST_PREFIX

    span = _SPAN_OF.get(entry)
    if span is None:
        return None
    snap = snapshot if snapshot is not None else obs.snapshot()
    return (snap.get("histograms") or {}).get(
        f"{DISPATCH_HIST_PREFIX}{span}")


def summary(snapshot: Optional[dict] = None) -> dict:
    """One report-ready roofline section: the platform peaks and, per
    noted entry, the static model + measured fold + occupancy. Both legs
    are PER-DISPATCH MEANS over the window — mean FLOPs/bytes over every
    noted dispatch against the histogram-mean committed duration (the
    sync-mode ``dispatch.*`` fold; ``measured_s=None`` honestly when
    ``RAFT_TPU_OBS_SYNC`` never ran) — so mixed-shape windows (a serving
    bucket ramp) report window-average utilization, never one shape's
    model against another shape's time. Numeric utilizations also land
    as ``roofline.<entry>.*`` gauges so the fleet merge carries them."""
    with obs.record_span("obs.roofline::summary"):
        peaks = platform_peaks()
        snap = snapshot if snapshot is not None else obs.snapshot()
        out_entries = {}
        for entry, rec in entries().items():
            n = rec.get("count", 0)
            if not n:
                continue
            h = dispatch_histogram(entry, snap)
            measured = None
            if h and h.get("count"):
                measured = h["sum"] / h["count"]
            br = rec["total_bytes_read"] / n
            bw = rec["total_bytes_written"] / n
            est = {
                "entry": entry,
                "flops": rec["total_flops"] / n,
                "bytes_read": br,
                "bytes_written": bw,
                "bytes": br + bw,
                "arithmetic_intensity": (
                    round(rec["total_flops"] / n / (br + bw), 4)
                    if br + bw else None),
            }
            row = _fold(est, peaks, measured, rec.get("occupancy"))
            row["dispatches"] = n
            row["last_shapes"] = dict(rec.get("shapes") or {})
            out_entries[entry] = row
            if obs.enabled():
                for key in ("mxu_utilization", "hbm_bw_utilization",
                            "achieved_gflops"):
                    v = row.get(key)
                    if isinstance(v, (int, float)):
                        obs.set_gauge(f"roofline.{entry}.{key}", v)
        return {"peaks": peaks, "entries": out_entries}


# ---------------------------------------------------------------------------
# compiler cross-check
# ---------------------------------------------------------------------------


def xla_cost_analysis(jitted, *args, **kwargs) -> Optional[dict]:
    """The backend's own FLOP accounting for one lowering of ``jitted``:
    ``{"flops", "bytes_accessed"?}`` from ``compiled.cost_analysis()``
    where the backend provides it, None (classified into the event ring)
    where it doesn't — the static model stands alone there. The lowering
    is analysis-only and rides ``obs.compile.suppress_analysis`` so it
    never fabricates an unexplained retrace."""
    from raft_tpu import resilience
    from raft_tpu.obs import compile as obs_compile

    with obs.record_span("obs.roofline::xla_cost_analysis"):
        try:
            with obs_compile.suppress_analysis():
                compiled = jitted.lower(*args, **kwargs).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if not isinstance(cost, dict) or "flops" not in cost:
                return None
            out = {"flops": int(cost["flops"])}
            if "bytes accessed" in cost:
                out["bytes_accessed"] = int(cost["bytes accessed"])
            return out
        except Exception as e:
            # a backend without cost_analysis is a supported state; the
            # event carries the kind so a real lowering failure is visible
            resilience.record_event(
                "roofline_xla_analysis_unavailable",
                kind=resilience.classify(e), error=repr(e)[:200])
            return None
