"""Fleet flight recorder: the time-and-fleet dimension of the obs stack.

Every plane built so far — metrics, span trees, SLO burn, shadow recall,
cost model, roofline, capacity — answers *point-in-time and host-local*
questions. This module adds the two missing axes:

* **Time** — :class:`FlightRecorder`, a pumpable/background windowed
  sampler (``RAFT_TPU_OBS_FLIGHT_INTERVAL_S``) that snapshots
  ``obs.report.collect()`` plus a **config fingerprint** (the knob vector:
  algo, nprobe, k, scan engine, page_rows, batch cap, tier census,
  process_count — :func:`fingerprint`) into a bounded ring and a
  crash-safe JSONL stream via ``bench/progress``. Each window also carries
  *window-local* operating-point deltas (``ops``: QPS and latency
  percentile bounds from counter/bucket differences between consecutive
  cumulative snapshots), the resilience events that landed since the last
  window (induced shard loss shows up as a timeline event, not a grep),
  and — on the first window — the subprocess device-health verdict
  (obs/health.py), so every recording opens self-documenting against the
  round-5 wedge class. Every provider degrades classified (the
  ``obs.flight.sample`` faultpoint is the round-7 injectable stand-in),
  so a broken plane costs one window's section, never the serving loop.

* **Fleet** — the straggler plane: the ``distributed.shard_skew`` gauge
  (max/median per-dispatch shard-time ratio, set by
  ``distributed/_sharding.probe_shards``) is folded into every window,
  and a ratio that stays hot for ``RAFT_TPU_OBS_STRAGGLER_WINDOWS``
  consecutive windows raises a classified ``straggler`` event plus the
  ``flight.straggler_events`` counter. Cross-host trace *stitching* lives
  in obs/aggregate.py (``stitch_traces``); this module contributes the
  per-process clock-offset handshake record that opens each recording
  (obs/tracing.clock_handshake) so the stitcher can align host clocks.

The frontier: :func:`extract_frontier` groups windows by fingerprint and
marks the Pareto-optimal operating points (maximize recall and QPS,
minimize p99 upper bound) — exactly the dataset ROADMAP item 2's
autotuner consumes, replacing hand-read sweep-config archaeology.

CLI::

    python -m raft_tpu.obs.flight results/flight_*.jsonl            # summary
    python -m raft_tpu.obs.flight rec.jsonl --validate              # gate
    python -m raft_tpu.obs.flight rec.jsonl --render                # timeline
    python -m raft_tpu.obs.flight rec.jsonl --frontier frontier.json

Telemetry-off contract: a disabled registry means the recorder holds ZERO
state — no ring, no providers, no clock reads; ``maybe_sample`` is one
attribute check. Like report/aggregate, this module is deliberately NOT
imported by ``obs/__init__`` (clean ``-m`` execution; the report import
would drag the SLO plane onto the package import path).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import sys
import threading
import time
from collections import deque
from typing import Optional

from raft_tpu import obs, resilience
from raft_tpu.obs import tracing

__all__ = [
    "FlightRecorder",
    "SCHEMA_VERSION",
    "extract_frontier",
    "fingerprint",
    "main",
    "read_recording",
    "render",
    "validate",
]

#: flight_window record schema (independent of obs.report's version — the
#: embedded report carries its own stamp)
SCHEMA_VERSION = 1

INTERVAL_ENV = "RAFT_TPU_OBS_FLIGHT_INTERVAL_S"
CAP_ENV = "RAFT_TPU_OBS_FLIGHT_CAP"
RATIO_ENV = "RAFT_TPU_OBS_STRAGGLER_RATIO"
WINDOWS_ENV = "RAFT_TPU_OBS_STRAGGLER_WINDOWS"

_DEFAULT_INTERVAL_S = 1.0
_DEFAULT_CAP = 256
_DEFAULT_RATIO = 4.0
_DEFAULT_WINDOWS = 2
_HEALTH_TIMEOUT_S = 10.0

#: the latency histogram / success counter the window-local ops derive from
_LAT_HIST = "serving.request_latency_s"
_OK_COUNTER = "serving.requests.ok"
_SKEW_GAUGE = "distributed.shard_skew"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        v = float(raw) if raw else default
    except ValueError:
        return default
    return v if v > 0 else default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw.isdigit() and int(raw) > 0 else default


def _classified(fn, label: str, out_errors: dict):
    """One provider; failure degrades its section to None, classified into
    ``errors`` — the report.py contract: a recorder must record, not raise."""
    try:
        return fn()
    except Exception as e:
        out_errors[label] = resilience.classify(e)
        return None


def fingerprint(knobs: dict) -> dict:
    """Canonical config fingerprint: the knob vector plus a short stable
    hash (``fp``) that keys frontier groups. ``process_count`` is stamped
    from the fleet identity so a scale-out is a DIFFERENT operating point
    even with identical per-host knobs. Values must be JSON-serializable;
    the hash is over the sorted canonical JSON, so dict ordering and float
    repr quirks cannot split one configuration into two groups."""
    _pi, pc = tracing.process_info()
    out = dict(knobs or {})
    out.setdefault("process_count", pc)
    blob = json.dumps(out, sort_keys=True, default=str)
    out["fp"] = hashlib.sha1(blob.encode()).hexdigest()[:12]
    return out


def _resolve(provider):
    """Providers may be live objects or zero-arg callables (the bench's
    per-window queue is rebuilt per load, so it hands a closure)."""
    return provider() if callable(provider) else provider


class FlightRecorder:
    """Windowed operating-point sampler over the whole observability plane.

    Drive it by pumping (:meth:`maybe_sample` in a serving loop — one
    attribute check plus one clock read per call when the interval has not
    elapsed) or with the background thread (:meth:`start` / :meth:`stop`).
    ``path`` (optional) streams every window crash-safe through
    ``bench/progress.export_metrics``; the recording opens with the
    per-process clock-offset handshake record the trace stitcher consumes.

    Providers (``engine``/``sampler``/``queue``/``capacity``) are passed
    straight to ``obs.report.collect``; each may be a zero-arg callable.
    ``knobs`` (dict or callable) feeds :func:`fingerprint`. ``health`` is
    a precomputed device-health verdict for the first window; with
    ``probe_health=True`` the recorder runs the subprocess probe itself
    (classified on failure) — callers pay that cost once, on the first
    sample, so take window 0 off any measured clock.
    """

    def __init__(self, path: Optional[str] = None, *, knobs=None,
                 engine=None, sampler=None, queue=None, capacity=None,
                 health=None, probe_health: bool = False,
                 interval_s: Optional[float] = None,
                 cap: Optional[int] = None,
                 extra: Optional[dict] = None):
        self._enabled = obs.enabled()
        if not self._enabled:
            return  # telemetry off ⇒ ZERO flight state (the NOOP contract)
        self._path = path
        self._knobs = knobs
        self._engine = engine
        self._sampler = sampler
        self._queue = queue
        self._capacity = capacity
        self._health = health
        self._probe_health = bool(probe_health)
        self._extra = dict(extra) if extra else None
        self._interval_s = (float(interval_s) if interval_s is not None
                            else _env_float(INTERVAL_ENV,
                                            _DEFAULT_INTERVAL_S))
        self._ring: deque = deque(
            maxlen=cap if cap else _env_int(CAP_ENV, _DEFAULT_CAP))  # guarded-by: _lock
        self._ratio = _env_float(RATIO_ENV, _DEFAULT_RATIO)
        self._hot_needed = _env_int(WINDOWS_ENV, _DEFAULT_WINDOWS)
        self._hot = 0                  # guarded-by: _lock
        self._straggler_events = 0     # guarded-by: _lock
        self._window = 0               # guarded-by: _lock
        self._t_last: Optional[float] = None   # guarded-by: _lock
        self._prev_ops: Optional[dict] = None  # guarded-by: _lock
        self._last_event_t = 0.0       # guarded-by: _lock
        self._wrote_handshake = False  # guarded-by: _lock
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()

    # -- pump / background ---------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def windows_recorded(self) -> int:
        if not self._enabled:
            return 0
        with self._lock:
            return self._window

    @property
    def straggler_events(self) -> int:
        if not self._enabled:
            return 0
        with self._lock:
            return self._straggler_events

    def records(self) -> list:
        """Snapshot of the bounded window ring, oldest first."""
        if not self._enabled:
            return []
        with self._lock:
            return list(self._ring)

    def maybe_sample(self, now: Optional[float] = None) -> Optional[dict]:
        """Sample one window iff the interval elapsed; the pump entry for
        serving loops. Disabled or early: None, at one branch of cost."""
        if not self._enabled:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            t_last = self._t_last
        if t_last is not None and now - t_last < self._interval_s:
            return None
        return self.sample(now=now)

    def start(self) -> None:
        """Background mode: a daemon thread pumps at a quarter interval."""
        if not self._enabled or self._thread is not None:
            return
        self._stop_ev.clear()
        t = threading.Thread(target=self._run, name="flight-recorder",
                             daemon=True)
        self._thread = t
        t.start()

    def stop(self) -> None:
        if not self._enabled or self._thread is None:
            return
        self._stop_ev.set()
        self._thread.join(timeout=10.0)
        self._thread = None

    def _run(self) -> None:
        tick = max(self._interval_s / 4.0, 0.01)
        while not self._stop_ev.wait(tick):
            self.sample_safe()

    def sample_safe(self) -> Optional[dict]:
        """:meth:`maybe_sample` that classifies instead of raising — the
        background thread's entry (an exception there would die silent)."""
        if not self._enabled:
            return None
        try:
            return self.maybe_sample()
        except Exception as e:
            resilience.classify(e)
            obs.add("flight.sample_degraded")
            return None

    # -- sampling ------------------------------------------------------------
    def sample(self, now: Optional[float] = None) -> Optional[dict]:
        """Record one window NOW (forced — callers close a load window with
        this regardless of the interval). Every provider degrades
        classified; an armed ``obs.flight.sample`` fault degrades the whole
        window to a classified stub, and the NEXT sample recovers."""
        if not self._enabled:
            return None
        with self._lock:
            with obs.record_span("obs.flight::sample",
                                 attrs={"window": self._window}):
                return self._sample_locked(
                    time.monotonic() if now is None else now)

    def _sample_locked(self, t_mono: float) -> dict:
        errors: dict = {}
        rec = {
            "t": round(time.time(), 3),
            "type": "flight_window",
            "schema_version": SCHEMA_VERSION,
            "window": self._window,
            "interval_s": (round(t_mono - self._t_last, 4)
                           if self._t_last is not None else 0.0),
        }
        try:
            resilience.faultpoint("obs.flight.sample")
            snap = _classified(obs.snapshot, "snapshot", errors) or {}
            rec["fingerprint"] = _classified(
                lambda: fingerprint(_resolve(self._knobs) or {}),
                "fingerprint", errors)
            rec["report"] = _classified(
                lambda: self._report(snap), "report", errors)
            ops = _classified(
                lambda: self._ops(snap, rec["interval_s"]), "ops", errors)
            rec["ops"] = ops if ops is not None else {}
            rec["events"] = _classified(
                lambda: self._new_events(), "events", errors) or []
            if self._window == 0:
                rec["health"] = _classified(
                    lambda: self._health_verdict(), "health", errors)
            self._straggler_check(rec)
        except Exception as e:
            # the armed-faultpoint path (and any residue the per-provider
            # guards cannot see): the window survives as a classified stub
            errors["sample"] = resilience.classify(e)
            obs.add("flight.sample_degraded")
        if errors:
            rec["errors"] = errors
        if self._extra:
            rec.update(self._extra)
        self._window += 1
        self._t_last = t_mono
        self._ring.append(rec)
        export_errors: dict = {}
        _classified(lambda: self._export(rec), "export", export_errors)
        if export_errors:
            # the ring still holds the window; a dead stream (read-only fs)
            # costs durability, classified, never the serving loop
            obs.add("flight.export_degraded")
        return rec

    def _report(self, snap: dict) -> dict:
        # lazy: report drags the SLO plane; a pumping process that never
        # samples (telemetry off upstream) must not pay the import
        from raft_tpu.obs import report as obs_report

        return obs_report.collect(
            engine=_resolve(self._engine), sampler=_resolve(self._sampler),
            queue=_resolve(self._queue), capacity=_resolve(self._capacity),
            snapshot=snap, window=self._window)

    def _ops(self, snap: dict, dt: float) -> dict:
        """Window-LOCAL operating point: deltas between this and the
        previous cumulative snapshot — counters subtract, histogram buckets
        subtract key-wise and re-derive percentile bounds over just this
        window's observations."""
        from raft_tpu.obs import aggregate

        counters = snap.get("counters") or {}
        hist = (snap.get("histograms") or {}).get(_LAT_HIST) or {}
        prev = self._prev_ops or {}
        ok = int(counters.get(_OK_COUNTER, 0))
        d_ok = ok - prev.get("ok", 0)
        ops = {"requests_ok": d_ok}
        if dt > 0:
            ops["qps"] = round(d_ok / dt, 2)
        prev_b = prev.get("buckets") or {}
        buckets = dict(hist.get("buckets") or {})
        d_buckets = {key: n - prev_b.get(key, 0)
                     for key, n in buckets.items()
                     if n - prev_b.get(key, 0) > 0}
        d_count = int(hist.get("count", 0)) - prev.get("count", 0)
        if d_count > 0:
            pb = aggregate.percentile_bounds(d_buckets, d_count)
            if pb:
                ops["p50_ub_s"] = pb["p50_ub"]
                ops["p99_ub_s"] = pb["p99_ub"]
        skew = ((snap.get("gauges") or {}).get(_SKEW_GAUGE) or {}).get("value")
        if skew is not None:
            ops["shard_skew"] = round(float(skew), 3)
        self._prev_ops = {"ok": ok, "buckets": buckets,
                          "count": int(hist.get("count", 0))}
        return ops

    def _new_events(self) -> list:
        """Resilience events that landed since the last window — how an
        induced shard loss (partial_merge) shows as a TIMELINE event."""
        fresh = [dict(e) for e in resilience.recent_events()
                 if e.get("t", 0) > self._last_event_t]
        if fresh:
            self._last_event_t = max(e.get("t", 0) for e in fresh)
        return fresh

    def _health_verdict(self) -> Optional[dict]:
        if self._health is not None:
            h = self._health
            return h.as_dict() if hasattr(h, "as_dict") else dict(h)
        if not self._probe_health:
            return None
        from raft_tpu.obs import health as obs_health

        return obs_health.probe("default",
                                timeout=_HEALTH_TIMEOUT_S).as_dict()

    def _straggler_check(self, rec: dict) -> None:
        """A shard-skew ratio hot for N consecutive windows is a straggler:
        one classified event per sustained excursion, then re-arm."""
        skew = (rec.get("ops") or {}).get("shard_skew")
        if skew is not None and skew >= self._ratio:
            self._hot += 1
        else:
            self._hot = 0
        if self._hot >= self._hot_needed:
            self._straggler_events += 1
            obs.add("flight.straggler_events")
            resilience.record_event(
                "straggler", site="obs.flight", skew=skew,
                windows=self._hot, ratio=self._ratio)
            rec["straggler"] = {"skew": skew, "windows": self._hot,
                                "ratio": self._ratio}
            self._hot = 0
        rec["straggler_events"] = self._straggler_events

    def _export(self, rec: dict) -> None:
        if not self._path:
            return
        # bench/progress: the one fsync'd JSONL writer (crash-safety
        # contract) — stdlib-only, no import cycle
        from raft_tpu.bench import progress

        if not self._wrote_handshake:
            self._wrote_handshake = True
            progress.export_metrics(self._path, tracing.clock_handshake())
        progress.export_metrics(self._path, rec)


# ---------------------------------------------------------------------------
# recording analysis: read / validate / frontier / render
# ---------------------------------------------------------------------------


def read_recording(path: str) -> list:
    """Parse one flight JSONL recording, skipping torn/corrupt lines (the
    bench/progress read tolerance). Returns ALL records — flight_window
    lines plus the opening clock_offset handshake."""
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        return []
    return records


def _windows(records: list) -> list:
    return [r for r in records if r.get("type") == "flight_window"]


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


_KNOWN_KINDS = {resilience.OOM, resilience.TRANSIENT, resilience.DEADLINE,
                resilience.FATAL}


def validate(records: list) -> list:
    """Structural health of one recording: the list of problems (empty =
    valid). A degraded window (classified ``errors``) is VALID — that is
    the recorder doing its job — but unclassified degradation, non-
    monotonic window ids, a missing handshake or a missing opening health
    verdict are not."""
    problems = []
    wins = _windows(records)
    if not wins:
        problems.append("no flight_window records")
        return problems
    if not any(r.get("type") == "clock_offset" for r in records):
        problems.append("recording carries no clock_offset handshake")
    last_by_proc: dict = {}
    for rec in wins:
        w = rec.get("window")
        label = f"window {w!r}"
        if not isinstance(w, int) or w < 0:
            problems.append(f"{label}: bad window id")
            continue
        if rec.get("schema_version") != SCHEMA_VERSION:
            problems.append(f"{label}: schema_version "
                            f"{rec.get('schema_version')!r} != "
                            f"{SCHEMA_VERSION}")
        pi = rec.get("process_index", 0)
        prev = last_by_proc.get(pi)
        if prev is not None and w <= prev:
            problems.append(f"{label}: window id not increasing for "
                            f"process {pi} (prev {prev})")
        last_by_proc[pi] = w
        if not _finite(rec.get("interval_s")) or rec["interval_s"] < 0:
            problems.append(f"{label}: interval_s not finite")
        errors = rec.get("errors") or {}
        for section, kind in errors.items():
            if kind not in _KNOWN_KINDS:
                problems.append(f"{label}: unclassified degradation "
                                f"{section}={kind!r}")
        degraded = "sample" in errors
        if not degraded:
            fp = rec.get("fingerprint")
            if "fingerprint" not in errors and (
                    not isinstance(fp, dict) or not fp.get("fp")):
                problems.append(f"{label}: fingerprint missing its fp hash")
            if "ops" not in errors and not isinstance(rec.get("ops"), dict):
                problems.append(f"{label}: ops section missing")
            if w == 0 and "health" not in rec and "health" not in errors:
                problems.append("window 0 carries no device-health verdict")
    return problems


def extract_frontier(records: list) -> dict:
    """Group windows by config fingerprint and mark the Pareto frontier
    over (recall ± CI up, QPS up, p99 upper bound down). Missing axes
    compare as worst-possible but equal-to-each-other, so a recording
    with no recall plane still yields a QPS/p99 frontier — and at least
    one point is always non-dominated when any group exists."""
    with obs.record_span("obs.flight::frontier"):
        groups: dict = {}
        for rec in _windows(records):
            fp_rec = rec.get("fingerprint")
            if not isinstance(fp_rec, dict) or not fp_rec.get("fp"):
                continue
            fp = fp_rec["fp"]
            g = groups.setdefault(fp, {
                "fp": fp,
                "knobs": {k: v for k, v in fp_rec.items() if k != "fp"},
                "windows": 0, "_qps": [], "_p99": [], "recall": None,
            })
            g["windows"] += 1
            ops = rec.get("ops") or {}
            if _finite(ops.get("qps")) and ops["qps"] > 0:
                g["_qps"].append(float(ops["qps"]))
            if _finite(ops.get("p99_ub_s")):
                g["_p99"].append(float(ops["p99_ub_s"]))
            recall = ((rec.get("report") or {}).get("recall")
                      if isinstance(rec.get("report"), dict) else None)
            if isinstance(recall, dict) and _finite(recall.get("recall")):
                # cumulative estimate: the newest window's value wins
                g["recall"] = recall["recall"]
                g["recall_ci_low"] = recall.get("ci_low")
                g["recall_ci_high"] = recall.get("ci_high")
        points = []
        for g in groups.values():
            qps = sorted(g.pop("_qps"))
            p99 = sorted(g.pop("_p99"))
            g["qps"] = qps[len(qps) // 2] if qps else None
            g["p99_ub_s"] = p99[len(p99) // 2] if p99 else None
            points.append(g)

        def axes(pt):
            return (pt["recall"] if _finite(pt["recall"]) else -math.inf,
                    pt["qps"] if _finite(pt["qps"]) else -math.inf,
                    -pt["p99_ub_s"] if _finite(pt["p99_ub_s"]) else -math.inf)

        for pt in points:
            a = axes(pt)
            pt["pareto"] = not any(
                all(bj >= aj for aj, bj in zip(a, axes(other)))
                and any(bj > aj for aj, bj in zip(a, axes(other)))
                for other in points if other is not pt)
        points.sort(key=lambda p: (not p["pareto"],
                                   -(p["qps"] or 0.0), p["fp"]))
        return {
            "type": "flight_frontier",
            "schema_version": SCHEMA_VERSION,
            "points": len(points),
            "pareto_points": sum(1 for p in points if p["pareto"]),
            "groups": points,
        }


def render(records: list) -> str:
    """Human-readable timeline: one line per window — elapsed offset,
    fingerprint, window-local QPS/p99/skew, event and degradation notes."""
    with obs.record_span("obs.flight::render"):
        wins = _windows(records)
        if not wins:
            return "(empty recording)"
        t0 = wins[0].get("t", 0.0)
        lines = []
        for rec in wins:
            ops = rec.get("ops") or {}
            bits = [f"w{rec.get('window', '?'):>3}",
                    f"t=+{max(0.0, rec.get('t', t0) - t0):.2f}s",
                    f"fp={(rec.get('fingerprint') or {}).get('fp', '-')}"]
            if ops.get("qps") is not None:
                bits.append(f"qps={ops['qps']:g}")
            if ops.get("p99_ub_s") is not None:
                bits.append(f"p99<={ops['p99_ub_s']:g}s")
            if ops.get("shard_skew") is not None:
                bits.append(f"skew={ops['shard_skew']:g}")
            events = rec.get("events") or []
            if events:
                names = sorted({e.get("event", "?") for e in events})
                bits.append(f"events={len(events)}({','.join(names)})")
            if "straggler" in rec:
                bits.append("STRAGGLER")
            if rec.get("errors"):
                bits.append("degraded=" + ",".join(
                    f"{k}:{v}" for k, v in sorted(rec["errors"].items())))
            if "health" in rec:
                h = rec.get("health")
                verdict = (h or {}).get("healthy") if isinstance(h, dict) \
                    else None
                bits.append(f"health={'ok' if verdict else verdict}")
            lines.append("  ".join(bits))
        return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs.flight",
        description="Validate, render and mine flight recordings: the "
                    "continuous operating-point timeline the serving bench "
                    "streams, and the Pareto frontier (recall vs p99 vs "
                    "QPS, grouped by config fingerprint) the autotuner "
                    "consumes.")
    ap.add_argument("files", nargs="+", help="flight JSONL recording(s)")
    ap.add_argument("--validate", action="store_true",
                    help="exit 1 unless every recording passes validate()")
    ap.add_argument("--render", action="store_true",
                    help="print the window-by-window timeline")
    ap.add_argument("--frontier", nargs="?", const="frontier.json",
                    default=None, metavar="PATH",
                    help="extract the Pareto frontier to PATH (default "
                         "frontier.json); exit 1 if it comes out empty")
    ap.add_argument("--indent", type=int, default=2)
    args = ap.parse_args(argv)

    all_records = []
    rc = 0
    for path in args.files:
        records = read_recording(path)
        if not _windows(records):
            print(f"flight: no flight_window records in {path}",
                  file=sys.stderr)
            return 2
        all_records.extend(records)
        if args.validate:
            problems = validate(records)
            if problems:
                for p in problems:
                    print(f"flight: INVALID: {path}: {p}", file=sys.stderr)
                rc = 1
            else:
                print(f"flight: valid: {path} "
                      f"({len(_windows(records))} windows)", file=sys.stderr)
    if args.render:
        print(render(all_records))
    frontier = extract_frontier(all_records)
    if args.frontier:
        with open(args.frontier, "w") as f:
            json.dump(frontier, f, indent=args.indent, sort_keys=True)
            f.write("\n")
            f.flush()
        if not frontier["pareto_points"]:
            print("flight: frontier EMPTY (no fingerprinted windows)",
                  file=sys.stderr)
            return 1
    wins = _windows(all_records)
    stragglers = sum(1 for r in wins if "straggler" in r)
    print(f"flight: {len(wins)} windows, "
          f"{frontier['points']} fingerprint group(s), "
          f"{frontier['pareto_points']} on the frontier, "
          f"{stragglers} straggler window(s)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
