"""Hierarchical span trees: contextvar parenting, bounded ring, Perfetto export.

PR 1's ``record_span`` produced *flat* timers — enough to say "ivf_pq::search
ran 40 times for 12 s" but not where inside a search the time went. This
module upgrades every enabled span into a node of a trace tree:

* **Parenting** is a :mod:`contextvars` variable, so nesting follows the call
  stack for free (threads and ``contextvars.copy_context`` tasks each get
  their own lineage; a span opened on a fresh thread starts a new trace).
* **Identity** is ``(trace_id, span_id, parent_id)`` — ids come from a
  process-local counter (deterministic, no clock/RNG reads), prefixed with
  the pid so traces from different processes never collide when merged.
* **Storage** is a bounded ring (``RAFT_TPU_OBS_TRACE_CAP``, default 4096
  spans) guarded by one lock; completed spans append one small dict each.
  The ring, not an unbounded list, is what makes leaving telemetry on for a
  whole bench window safe.
* **Export** is Chrome trace-event JSON (:func:`chrome_trace` /
  :func:`export_chrome_trace`) — one ``"X"`` (complete) event per span with
  its attributes under ``args``, plus ``"i"`` (instant) events for the
  resilience recovery ring — loadable directly in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing``.

**Sync mode** (``RAFT_TPU_OBS_SYNC=1`` / :func:`enable_sync`): JAX dispatch
is asynchronous, so a span around a jitted region measures dispatch +
trace/compile time, not device execution — systematically under-reporting
jitted search phases. Sync mode force-drains the dispatch queue at span exit
(the resilience force-completion pattern: a scalar host fetch, because
``block_until_ready`` does not synchronize on the tunneled axon runtime) and
records BOTH numbers: ``dur_s`` becomes committed time, and the pre-drain
wall-clock rides the span as the ``dispatch_s`` attribute. It costs one host
round-trip per span, so it is OFF by default and meant for attribution runs,
not amortized QPS measurement.

Everything here is stdlib-only at import time (jax and resilience are
reached lazily), so the module stays importable in jax-free parents.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Optional

__all__ = [
    "alloc_id",
    "chrome_trace",
    "clear_spans",
    "clock_handshake",
    "current_span",
    "disable_sync",
    "drain_device",
    "enable_sync",
    "enter_span",
    "exit_span",
    "export_chrome_trace",
    "fleet_trace_id",
    "manual_span",
    "process_info",
    "push_span",
    "reset_fleet_ids",
    "set_ring_cap",
    "spans",
    "sync_enabled",
]

# ---------------------------------------------------------------------------
# process identity (fleet aggregation stamps)
# ---------------------------------------------------------------------------


def _jax_process_info():
    """(process_index, process_count) from jax, ONLY when a backend already
    exists. jax.process_index() initializes the backend on first touch —
    exactly the operation that wedged round 5 — so this never triggers init:
    it requires jax AND an initialized xla_bridge backend to already be in
    sys.modules, else answers None. Multi-host launchers that want stamps
    without a live backend set RAFT_TPU_PROCESS_INDEX/COUNT instead."""
    try:
        jax = sys.modules.get("jax")
        if jax is None:
            return None
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is None or not getattr(xb, "_backends", None):
            return None
        return int(jax.process_index()), int(jax.process_count())
    # a stamp is best-effort decoration: jax-internals drift degrades to
    # "no live backend", never to a telemetry failure worth classifying
    except Exception:  # graftlint: ignore[unclassified-except]
        return None


def process_info() -> tuple:
    """(process_index, process_count) for stamping telemetry records.

    Resolution order: ``RAFT_TPU_PROCESS_INDEX``/``RAFT_TPU_PROCESS_COUNT``
    env override (tests, launchers), then an already-initialized jax backend,
    then ``(0, 1)``. Never initializes a backend (see :func:`_jax_process_info`).
    """
    pi = os.environ.get("RAFT_TPU_PROCESS_INDEX", "").strip()
    pc = os.environ.get("RAFT_TPU_PROCESS_COUNT", "").strip()
    if pi.lstrip("-").isdigit():
        return int(pi), int(pc) if pc.lstrip("-").isdigit() else 1
    live = _jax_process_info()
    if live is not None:
        return live
    return 0, 1


# ---------------------------------------------------------------------------
# sync mode (device-time attribution)
# ---------------------------------------------------------------------------

_sync = os.environ.get("RAFT_TPU_OBS_SYNC", "").strip().lower() in (
    "1", "true", "on", "yes",
)


def sync_enabled() -> bool:
    return _sync


def enable_sync() -> None:
    global _sync
    _sync = True


def disable_sync() -> None:
    global _sync
    _sync = False


def drain_device() -> bool:
    """Force completion of everything dispatched so far on EVERY local
    device: enqueue a trivial computation per device and host-fetch its
    scalar result. Each device's stream executes in order, so the fetch
    returning implies every earlier dispatch on that device committed (the
    bench.py/_force and resilience.force_completion contract —
    block_until_ready does not sync on the tunneled runtime); draining only
    the default device would let a multi-chip span's shards run on while
    dur_s claims they committed. Returns False (and stays silent) when jax
    has no live backend — like :func:`_jax_process_info`, this must never
    TRIGGER backend init (a span around pure host work would otherwise pay
    first-touch init inside telemetry teardown, the round-5 wedge class)."""
    try:
        jax = sys.modules.get("jax")
        xb = sys.modules.get("jax._src.xla_bridge")
        if jax is None or xb is None or not getattr(xb, "_backends", None):
            return False
        import jax.numpy as jnp

        for dev in jax.local_devices():
            x = jax.device_put(jnp.float32(0), dev) + jnp.float32(0)
            float(x)
        return True
    # a failed drain only means "no device-time attribution for this
    # span" (the caller records no dispatch_s) — not a failure class
    except Exception:  # graftlint: ignore[unclassified-except]
        return False


# ---------------------------------------------------------------------------
# span ring + contextvar lineage
# ---------------------------------------------------------------------------

def _ring_cap() -> int:
    raw = os.environ.get("RAFT_TPU_OBS_TRACE_CAP", "").strip()
    if raw.isdigit() and int(raw) > 0:
        return int(raw)
    return 4096


_SPANS: deque = deque(maxlen=_ring_cap())  # guarded-by: _LOCK
_LOCK = threading.Lock()


def set_ring_cap(cap: int) -> None:
    """Resize the span ring at runtime (newest spans kept). The
    ``RAFT_TPU_OBS_TRACE_CAP`` env var is read once at import — a process
    that decides on a long attribution run AFTER importing raft_tpu uses
    this instead (the runtime twin, like enable_sync for the env gate)."""
    global _SPANS
    with _LOCK:
        _SPANS = deque(_SPANS, maxlen=max(1, int(cap)))
_ids = itertools.count(1)
_ID_PREFIX = f"{os.getpid():x}"

#: (trace_id, span_id) of the innermost open span in this context
_current: contextvars.ContextVar = contextvars.ContextVar(
    "raft_tpu_obs_span", default=None)


def current_span() -> Optional[tuple]:
    """(trace_id, span_id) of the innermost open span, or None."""
    return _current.get()


def _next_id() -> str:
    return f"{_ID_PREFIX}-{next(_ids)}"


def enter_span():
    """Open a span in the current context: allocate ids, inherit the trace
    from the enclosing span (or start a new trace at the root), and make
    this span the parent of anything opened inside it.

    Returns ``((trace_id, span_id, parent_id), token)``; the token MUST be
    passed back to :func:`exit_span`."""
    parent = _current.get()
    sid = _next_id()
    if parent is None:
        ids = (_next_id(), sid, None)
    else:
        ids = (parent[0], sid, parent[1])
    token = _current.set((ids[0], ids[1]))
    return ids, token


def exit_span(ids, token, *, name: str, t0: float, dur_s: float,
              attrs: Optional[dict] = None, error: Optional[str] = None,
              dispatch_s: Optional[float] = None) -> dict:
    """Close a span opened by :func:`enter_span`: restore the parent context
    and append the completed record to the ring. Returns the record."""
    _current.reset(token)
    trace_id, span_id, parent_id = ids
    rec = {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "t0": t0,
        "dur_s": dur_s,
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = dict(attrs)
    if error is not None:
        rec["error"] = error
    if dispatch_s is not None:
        rec["dispatch_s"] = dispatch_s
    push_span(rec)
    return rec


#: per-site fleet dispatch counters (``fleet_trace_id``)
_fleet_ids: dict = {}  # guarded-by: _LOCK


def fleet_trace_id(site: str) -> str:
    """Deterministic FLEET-scoped id for one dispatch of ``site``:
    ``fleet:<site>:<n>`` where n counts this process's dispatches of that
    site. Deliberately NOT pid-prefixed — under SPMD every host runs the
    identical dispatch sequence, so host i and host j stamp the SAME id on
    the same logical dispatch, which is exactly what lets the trace
    stitcher (obs/aggregate.stitch_traces) line per-host tracks up into
    one fleet trace. Span/trace ids stay host-local (:func:`alloc_id`);
    this rides spans as an ``attrs`` entry."""
    with _LOCK:
        n = _fleet_ids.get(site, 0) + 1
        _fleet_ids[site] = n
    return f"fleet:{site}:{n}"


def reset_fleet_ids() -> None:
    """Reset the per-site dispatch counters (tests simulating two hosts
    from one process re-zero between 'hosts' to mirror SPMD determinism)."""
    with _LOCK:
        _fleet_ids.clear()


def clock_handshake(reference_epoch: Optional[float] = None) -> dict:
    """The per-process clock-offset handshake record that opens a flight
    recording: this host's epoch and monotonic readings, plus ``offset_s``
    relative to a fleet-agreed reference epoch (``reference_epoch`` or the
    ``RAFT_TPU_FLEET_EPOCH`` env var a multi-host launcher distributes).
    With no reference the offset is 0.0 — single-host recordings stitch
    unshifted. The stitcher subtracts ``offset_s`` from a host's event
    timestamps so skewed wall clocks align on one timeline."""
    pi, pc = process_info()
    t_epoch = time.time()
    t_mono = time.monotonic()
    if reference_epoch is None:
        raw = os.environ.get("RAFT_TPU_FLEET_EPOCH", "").strip()
        try:
            reference_epoch = float(raw) if raw else None
        except ValueError:
            reference_epoch = None
    return {
        "type": "clock_offset",
        "process_index": pi,
        "process_count": pc,
        "t_epoch": round(t_epoch, 6),
        "t_mono": round(t_mono, 6),
        "offset_s": (round(t_epoch - reference_epoch, 6)
                     if reference_epoch is not None else 0.0),
    }


def alloc_id() -> str:
    """One fresh span/trace id from the process-local counter. Callers
    building EXPLICIT-lineage spans (:func:`manual_span`) allocate ids up
    front so children can reference a parent that completes later — the
    serving request lifecycle, whose root span closes after its dispatch
    children were already recorded on another thread."""
    return _next_id()


def manual_span(name: str, *, t0: float, dur_s: float,
                trace_id: Optional[str] = None,
                span_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                attrs: Optional[dict] = None,
                error: Optional[str] = None) -> dict:
    """Record one COMPLETED span with explicit lineage, bypassing the
    contextvar stack. This is the cross-thread escape hatch: a serving
    request's submit → admit → dispatch → complete lifecycle spans the
    caller thread and the batcher's worker, so contextvar parenting cannot
    link them — the queue allocates the request's ids at submit
    (:func:`alloc_id`) and files each lifecycle phase under them as it
    happens. ``t0`` is epoch seconds (the ring/export convention). Returns
    the record pushed to the ring."""
    rec = {
        "name": name,
        "trace_id": trace_id if trace_id is not None else _next_id(),
        "span_id": span_id if span_id is not None else _next_id(),
        "parent_id": parent_id,
        "t0": t0,
        "dur_s": dur_s,
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = dict(attrs)
    if error is not None:
        rec["error"] = error
    push_span(rec)
    return rec


def push_span(rec: dict) -> None:
    with _LOCK:
        _SPANS.append(rec)


def spans() -> list:
    """Snapshot of the completed-span ring, oldest first."""
    with _LOCK:
        return list(_SPANS)


def clear_spans() -> None:
    with _LOCK:
        _SPANS.clear()


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------


def chrome_trace(span_records: Optional[list] = None,
                 events: Optional[list] = None,
                 extra: Optional[dict] = None) -> dict:
    """Assemble a Chrome trace-event JSON dict from span records (default:
    the ring) and instant events (default: the resilience recovery ring).

    Spans become ``"X"`` complete events (ts/dur in microseconds, pid =
    ``process_index`` so multi-host traces interleave cleanly in one
    Perfetto view); recovery events become ``"i"`` instants. Span attributes
    and ids ride under ``args`` and round-trip through the file."""
    if span_records is None:
        span_records = spans()
    if events is None:
        events = _resilience_events()
    pi, pc = process_info()
    out = []
    for rec in span_records:
        args = {
            "trace_id": rec.get("trace_id"),
            "span_id": rec.get("span_id"),
            "parent_id": rec.get("parent_id"),
        }
        args.update(rec.get("attrs") or {})
        if "error" in rec:
            args["error"] = rec["error"]
        if "dispatch_s" in rec:
            args["dispatch_s"] = rec["dispatch_s"]
        out.append({
            "name": rec.get("name", "?"),
            "cat": "span",
            "ph": "X",
            "ts": round(float(rec.get("t0", 0.0)) * 1e6, 1),
            "dur": round(float(rec.get("dur_s", 0.0)) * 1e6, 1),
            "pid": pi,
            "tid": rec.get("tid", 0),
            "args": args,
        })
    for ev in events:
        ev = dict(ev)
        out.append({
            "name": ev.pop("event", "event"),
            "cat": "resilience",
            "ph": "i",
            "s": "p",
            "ts": round(float(ev.pop("t", 0.0)) * 1e6, 1),
            "pid": pi,
            "tid": 0,
            "args": ev,
        })
    meta = {"process_index": pi, "process_count": pc}
    if extra:
        meta.update(extra)
    return {"traceEvents": out, "displayTimeUnit": "ms", "otherData": meta}


def _resilience_events() -> list:
    """The resilience recovery ring, reached lazily (resilience imports obs,
    so a module-level import here would be a cycle); empty when the package
    is only partially imported."""
    try:
        from raft_tpu.resilience.retry import recent_events

        return recent_events()
    # a partially imported resilience package (bootstrap orderings) means
    # "no instant events for this export" — nothing to classify
    except Exception:  # graftlint: ignore[unclassified-except]
        return []


def export_chrome_trace(path, extra: Optional[dict] = None) -> dict:
    """Serialize :func:`chrome_trace` to ``path`` crash-safely (tmp file +
    flush + fsync + atomic rename — the bench/progress.py durability
    contract: a kill mid-write leaves the old file or the complete new one)
    and return the dict. Bench code must route through
    ``bench/progress.write_artifact`` instead (graftlint ``span-name``
    enforces it); this is the library entry."""
    doc = chrome_trace(extra=extra)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return doc
