"""Random generation (reference cpp/include/raft/random/).

The reference's counter-based Philox/PCG RNG (random/rng_state.hpp:28-33,
rng_device.cuh) maps directly onto JAX's splittable threefry keys — both give
reproducible, order-independent streams. Dataset generators re-designed on top:
make_blobs (random/make_blobs.cuh:65), make_regression, permute,
sample_without_replacement, multi-variable gaussian, and the RMAT rectangular
graph generator (random/rmat_rectangular_generator.cuh:81).
"""

from raft_tpu.random.generators import (
    RngState,
    make_blobs,
    make_regression,
    multi_variable_gaussian,
    permute,
    rmat,
    sample_without_replacement,
    uniform,
    normal,
)

__all__ = [
    "RngState",
    "make_blobs",
    "make_regression",
    "multi_variable_gaussian",
    "permute",
    "rmat",
    "sample_without_replacement",
    "uniform",
    "normal",
]
