"""RNG state + dataset/graph generators — see package docstring."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class RngState:
    """Stateful convenience wrapper over a splittable key (the analog of the
    mutable rng_state handed through reference APIs, random/rng_state.hpp:28)."""

    def __init__(self, seed: int = 0):
        self.key = jax.random.key(seed)

    def split(self) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sub


def _as_key(key_or_seed) -> jax.Array:
    if isinstance(key_or_seed, RngState):
        return key_or_seed.split()
    if isinstance(key_or_seed, int):
        return jax.random.key(key_or_seed)
    return key_or_seed


def uniform(key_or_seed, shape, low=0.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(_as_key(key_or_seed), shape, dtype, low, high)


def normal(key_or_seed, shape, mean=0.0, std=1.0, dtype=jnp.float32):
    return mean + std * jax.random.normal(_as_key(key_or_seed), shape, dtype)


def permute(key_or_seed, n: int) -> jax.Array:
    """Random permutation of [0, n) (random/permute.cuh analog)."""
    return jax.random.permutation(_as_key(key_or_seed), n).astype(jnp.int32)


def sample_without_replacement(key_or_seed, n_population: int, n_samples: int) -> jax.Array:
    """Uniform sample of ``n_samples`` distinct ids from [0, n_population)
    (random/sample_without_replacement.cuh analog)."""
    key = _as_key(key_or_seed)
    return jax.random.choice(
        key, n_population, shape=(n_samples,), replace=False
    ).astype(jnp.int32)


def make_blobs(
    key_or_seed,
    n_rows: int,
    n_cols: int,
    n_clusters: int = 5,
    cluster_std: float = 1.0,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    centers: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Isotropic Gaussian blobs: (data (n_rows, n_cols), labels, centers)
    (random/make_blobs.cuh:65 analog)."""
    key = _as_key(key_or_seed)
    k_centers, k_labels, k_noise = jax.random.split(key, 3)
    if centers is None:
        centers = jax.random.uniform(
            k_centers, (n_clusters, n_cols), dtype, center_box[0], center_box[1]
        )
    else:
        centers = jnp.asarray(centers, dtype)
        n_clusters = centers.shape[0]
    labels = jax.random.randint(k_labels, (n_rows,), 0, n_clusters).astype(jnp.int32)
    noise = cluster_std * jax.random.normal(k_noise, (n_rows, n_cols), dtype)
    return centers[labels] + noise, labels, centers


def make_regression(
    key_or_seed,
    n_rows: int,
    n_cols: int,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Linear-model regression data: (X, y, coef)
    (random/make_regression.cuh analog)."""
    key = _as_key(key_or_seed)
    k_x, k_w, k_n = jax.random.split(key, 3)
    n_informative = n_cols if n_informative is None else n_informative
    x = jax.random.normal(k_x, (n_rows, n_cols), dtype)
    coef = jnp.zeros((n_cols, n_targets), dtype)
    coef = coef.at[:n_informative].set(
        100.0 * jax.random.uniform(k_w, (n_informative, n_targets), dtype)
    )
    y = x @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(k_n, y.shape, dtype)
    return x, jnp.squeeze(y), jnp.squeeze(coef)


def multi_variable_gaussian(key_or_seed, mean, cov, n_samples: int) -> jax.Array:
    """Samples from N(mean, cov) via Cholesky (random/multi_variable_gaussian.cuh)."""
    key = _as_key(key_or_seed)
    mean = jnp.asarray(mean)
    return jax.random.multivariate_normal(
        key, mean, jnp.asarray(cov), shape=(n_samples,), dtype=mean.dtype
    )


@functools.partial(jax.jit, static_argnames=("r_scale", "c_scale", "n_edges"))
def _rmat_impl(key, theta, r_scale, c_scale, n_edges):
    # theta: (max_scale, 4) per-level quadrant probabilities (a, b, c, d).
    max_scale = max(r_scale, c_scale)
    keys = jax.random.split(key, max_scale)

    def level(carry, inputs):
        rows, cols = carry
        lvl, k = inputs
        p = theta[lvl]  # (4,)
        q = jax.random.choice(k, 4, shape=(n_edges,), p=p)
        r_bit = (q >= 2).astype(jnp.int32)  # quadrants c,d are lower half
        c_bit = (q % 2).astype(jnp.int32)  # quadrants b,d are right half
        rows = jnp.where(lvl < r_scale, rows * 2 + r_bit, rows)
        cols = jnp.where(lvl < c_scale, cols * 2 + c_bit, cols)
        return (rows, cols), None

    init = (jnp.zeros((n_edges,), jnp.int32), jnp.zeros((n_edges,), jnp.int32))
    (rows, cols), _ = lax.scan(level, init, (jnp.arange(max_scale), keys))
    return rows, cols


def rmat(
    key_or_seed,
    r_scale: int,
    c_scale: int,
    n_edges: int,
    theta=None,
) -> Tuple[jax.Array, jax.Array]:
    """RMAT rectangular graph generator: edge list (rows, cols) with
    2^r_scale × 2^c_scale vertex space (random/rmat_rectangular_generator.cuh:81).

    ``theta`` is (max(r_scale,c_scale), 4) per-level quadrant probabilities;
    default is the standard (0.57, 0.19, 0.19, 0.05) at every level.
    """
    key = _as_key(key_or_seed)
    max_scale = max(r_scale, c_scale)
    if theta is None:
        theta = jnp.tile(jnp.array([[0.57, 0.19, 0.19, 0.05]], jnp.float32), (max_scale, 1))
    else:
        theta = jnp.asarray(theta, jnp.float32).reshape(max_scale, 4)
        theta = theta / theta.sum(axis=1, keepdims=True)
    return _rmat_impl(key, theta, int(r_scale), int(c_scale), int(n_edges))
