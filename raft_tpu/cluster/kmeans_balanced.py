"""Balanced k-means — the IVF coarse-quantizer trainer.

Reference surface: raft::cluster::kmeans_balanced — fit
(cluster/kmeans_balanced.cuh:76), predict (:134), fit_predict (:199),
build_clusters (:258), calc_centers_and_sizes (:337); the balancing EM +
mesocluster hierarchy live in cluster/detail/kmeans_balanced.cuh. Supported
metrics: L2 and inner product (kmeans_balanced_types.hpp:29).

Why it exists: IVF indexes need cluster lists of *roughly equal size* — search
cost is bounded by the largest probed list, and (on TPU specifically) padded
dense list storage wastes memory proportional to skew. Plain Lloyd happily
produces empty and mega clusters; balanced k-means reseeds underweight
clusters each iteration.

TPU design: the reference's `adjust_centers` walks small clusters on the host
and steals a random point from an over-average cluster. That per-cluster
data-dependent loop doesn't vectorize; instead each EM step here does a
static-shape reseed: rank all points by distance to their assigned center
(descending, one `top_k`) and hand the i-th underweight cluster the i-th
worst-served point. Same fixpoint pressure (small clusters teleport to dense
under-covered regions), one fused program per iteration, no host sync.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.core.resources import Resources, current_resources, use_resources
from raft_tpu.core.trace import traced
from raft_tpu.ops.distance import fused_l2_nn_argmin, matmul_t


@dataclass(frozen=True)
class KMeansBalancedParams:
    """Aggregate params (kmeans_balanced_types.hpp:34-39)."""

    n_iters: int = 20
    metric: str = "sqeuclidean"  # "sqeuclidean" | "inner_product"
    seed: int = 0
    # fraction of the average size below which a cluster is reseeded
    # (analog of kAdjustCentersWeight pressure in detail/kmeans_balanced.cuh)
    balancing_threshold: float = 0.25

    def __post_init__(self):
        if self.metric not in ("sqeuclidean", "inner_product"):
            raise ValueError("kmeans_balanced supports sqeuclidean | inner_product")


def _assign(X, centers, metric, res=None):
    """E step → (score, labels). Score is d² for L2, -ip for inner product
    (lower is always better, so downstream top-k logic is metric-agnostic)."""
    if metric == "inner_product":
        ip = matmul_t(X, centers)
        labels = jnp.argmax(ip, axis=1).astype(jnp.int32)
        return -jnp.max(ip, axis=1), labels
    d2, labels = fused_l2_nn_argmin(X, centers, res=res)
    return d2, labels


def calc_centers_and_sizes(X, labels, n_clusters: int, old_centers=None):
    """M step: per-cluster means + sizes (kmeans_balanced.cuh:337). Empty
    clusters keep ``old_centers`` (or zeros)."""
    X = jnp.asarray(X)
    labels = jnp.asarray(labels)
    sums = jax.ops.segment_sum(X, labels, num_segments=n_clusters)
    sizes = jax.ops.segment_sum(jnp.ones(X.shape[0], jnp.float32), labels, num_segments=n_clusters)
    means = sums / jnp.maximum(sizes, 1.0)[:, None]
    if old_centers is not None:
        means = jnp.where(sizes[:, None] > 0, means, jnp.asarray(old_centers))
    return means, sizes.astype(jnp.int32)


@functools.partial(
    jax.jit, static_argnames=("n_clusters", "n_iters", "metric", "threshold", "workspace_bytes")
)
def _balanced_em(X, centers0, key, n_clusters, n_iters, metric, threshold, workspace_bytes=None):
    """balancing_em_iters analog (detail/kmeans_balanced.cuh:619): EM where each
    iteration pulls underweight clusters toward random samples of over-average
    clusters (adjust_centers, :456-483). Like the reference's
    ``balancing_pullback`` (:651-654), the iteration budget extends while
    rebalancing is still firing, capped at 5×n_iters.

    ``workspace_bytes`` only keys the jit cache so a changed Resources budget
    retraces the inner fused_l2_nn_argmin tiling.
    """
    del workspace_bytes
    n = X.shape[0]
    average = n / n_clusters
    max_iters = 5 * n_iters

    def step(i, centers):
        _, labels = _assign(X, centers, metric)
        centers, sizes = calc_centers_and_sizes(X, labels, n_clusters, centers)
        fsizes = sizes.astype(jnp.float32)
        small = fsizes < threshold * average
        # Reseed by SPLITTING the largest clusters: the i-th underweight
        # center moves to the midpoint between the i-th largest cluster's
        # center and one of its random members, so the next E-step hands it
        # roughly half of that cluster. (Round-3 fix: the previous
        # teleport-onto-a-random-point reseed left persistent singleton
        # clusters on spread-out data — a center sitting exactly on a point
        # captures only that point and re-triggers forever. The reference's
        # adjust_centers pull, :474-481, avoids this with its
        # mesocluster-hierarchy init; splitting is the SPMD-friendly analog.)
        u = jax.random.uniform(jax.random.fold_in(key, i), (n,))
        maxu = jax.ops.segment_max(u, labels, num_segments=n_clusters)
        is_rep = u >= maxu[labels]
        rep = jax.ops.segment_min(
            jnp.where(is_rep, jnp.arange(n, dtype=jnp.int32), n), labels,
            num_segments=n_clusters)                 # random member / cluster
        donor_order = jnp.argsort(-fsizes)           # largest first
        rank = jnp.clip(jnp.cumsum(small.astype(jnp.int32)) - 1, 0, n_clusters - 1)
        donor = donor_order[rank]
        donor_pt = X[jnp.clip(rep[donor], 0, n - 1)]
        c_new = 0.5 * (centers[donor] + donor_pt)
        centers = jnp.where(small[:, None], c_new, centers)
        if metric == "inner_product":
            # IP/cosine EM drifts toward zero centers without renormalization
            # (detail/kmeans_balanced.cuh:656-668)
            centers = centers / jnp.maximum(
                jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-30
            )
        return centers, jnp.any(small)

    def cond(carry):
        _, it, rebalancing = carry
        return jnp.logical_or(it < n_iters, jnp.logical_and(rebalancing, it < max_iters))

    def body(carry):
        centers, it, _ = carry
        centers, rebalancing = step(it, centers)
        return centers, it + 1, rebalancing

    centers, _, _ = lax.while_loop(cond, body, (centers0, jnp.int32(0), jnp.bool_(True)))
    # final M step + re-predict so returned labels match returned centers
    _, labels = _assign(X, centers, metric)
    centers, _ = calc_centers_and_sizes(X, labels, n_clusters, centers)
    if metric == "inner_product":
        centers = centers / jnp.maximum(jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-30)
    _, labels = _assign(X, centers, metric)
    sizes = jax.ops.segment_sum(
        jnp.ones(n, jnp.int32), labels, num_segments=n_clusters
    )
    return centers, labels, sizes


@traced("kmeans_balanced::fit")
def fit(
    X,
    n_clusters: int,
    params: KMeansBalancedParams = KMeansBalancedParams(),
    res: Optional[Resources] = None,
) -> jax.Array:
    """Train balanced k-means centers (kmeans_balanced::fit,
    cluster/kmeans_balanced.cuh:76). Returns (n_clusters, dim) centers."""
    centers, _, _ = _fit_full(X, n_clusters, params, res)
    return centers


@traced("kmeans_balanced::fit_predict")
def fit_predict(
    X,
    n_clusters: int,
    params: KMeansBalancedParams = KMeansBalancedParams(),
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(centers, labels) in one pass (kmeans_balanced.cuh:199)."""
    centers, labels, _ = _fit_full(X, n_clusters, params, res)
    return centers, labels


def _fit_full(X, n_clusters, params, res):
    res = res or current_resources()
    X = jnp.asarray(X)
    n = X.shape[0]
    if n_clusters > n:
        raise ValueError(f"n_clusters={n_clusters} > n_samples={n}")
    key = jax.random.key(params.seed)
    k_init, k_adjust = jax.random.split(key)
    # with-replacement init: the odd duplicate seed collapses to an empty
    # cluster that the balancing reseed immediately relocates, and it avoids
    # choice(replace=False)'s O(n log n) permutation compile (round 3)
    rows = jax.random.randint(k_init, (n_clusters,), 0, n)
    centers0 = X[rows].astype(jnp.float32)
    em_attrs = None
    if obs.enabled():
        obs.add("kmeans_balanced.fits", 1)
        obs.add("kmeans_balanced.rows", n)
        # configured, not executed: the balancing loop may run up to 5× this
        # (_balanced_em does not surface its actual count)
        obs.add("kmeans_balanced.iterations_configured", int(params.n_iters))
        em_attrs = {"rows": int(n), "clusters": int(n_clusters),
                    "iters_configured": int(params.n_iters)}
    # host checkpoint before the (single, long) balanced-EM dispatch — the
    # interruptible docstring names k-means as a checkpoint site; the EM
    # loop itself is one compiled while_loop, so this is where a cancel or
    # hard deadline lands
    from raft_tpu.core.interruptible import check_interrupt
    from raft_tpu.resilience import faultpoint

    check_interrupt()
    faultpoint("kmeans_balanced.fit.em")
    with use_resources(res):
        # phase span: under a @traced fit/fit_predict entry this is the
        # child node that carries the EM dispatch (and, in sync mode, its
        # committed device time) plus rows/clusters attrs
        with obs.record_span("kmeans_balanced::em", attrs=em_attrs):
            return _balanced_em(
                X.astype(jnp.float32),
                centers0,
                k_adjust,
                int(n_clusters),
                int(params.n_iters),
                params.metric,
                float(params.balancing_threshold),
                int(res.workspace_bytes),
            )


def predict(
    X,
    centers,
    params: KMeansBalancedParams = KMeansBalancedParams(),
    res: Optional[Resources] = None,
) -> jax.Array:
    """Nearest-center labels under the params metric (kmeans_balanced.cuh:134)."""
    _, labels = _assign(jnp.asarray(X), jnp.asarray(centers), params.metric, res)
    return labels
