"""Single-linkage agglomerative clustering (reference
cluster/single_linkage.cuh:53, detail in cluster/detail/single_linkage.cuh
and detail/agglomerative.h).

Pipeline (same decomposition as the reference):
  connectivity graph (full pairwise, or kNN with k = log2(n) + c)
    → Borůvka MST (sparse/solver.py)
    → [kNN mode] connect-components repair: a disconnected kNN graph gets
      the minimum cross-component edges added (cross_component_nn.cuh
      analog, computed as a component-masked distance argmin) and the MST
      re-runs — at most O(log n) repair rounds
    → flat labels: cut the (n_clusters - 1) heaviest MST edges, run
      connected components over the remainder, relabel monotonically.

TPU design notes: the dendrogram cut and labeling are fully on-device
(sort/segment ops); the scipy-format linkage matrix (`to_scipy_linkage`) is
a host-side O(n α(n)) union-find walk — same split as the reference, whose
dendrogram relabeling also runs on host-resident data
(detail/agglomerative.h build_dendrogram_host).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.label import make_monotonic
from raft_tpu.ops import distance as dist_mod
from raft_tpu.sparse.solver import MstResult, mst
from raft_tpu.sparse.types import COO


@dataclass
class LinkageResult:
    """linkage_output analog (cluster/single_linkage_types.hpp)."""

    labels: jax.Array        # (n,) int32 in [0, n_clusters)
    mst_src: jax.Array       # (n-1,) merge edges, sorted by height
    mst_dst: jax.Array
    mst_heights: jax.Array   # (n-1,) float32
    n_clusters: int

    def to_scipy_linkage(self) -> np.ndarray:
        """Host-side conversion to a scipy-style (n-1, 4) linkage matrix Z
        (detail/agglomerative.h build_dendrogram_host analog)."""
        src = np.asarray(self.mst_src)
        dst = np.asarray(self.mst_dst)
        h = np.asarray(self.mst_heights)
        if (src < 0).any() or (dst < 0).any() or not np.isfinite(h).all():
            # -1/inf slots mean the spanning tree is a forest — a dendrogram
            # does not exist (ADVICE.md round-2: corrupt Z emitted silently)
            raise ValueError(
                "spanning tree is a forest (disconnected data); "
                "no dendrogram exists"
            )
        n = src.shape[0] + 1
        # roots in parent-space are scipy cluster ids (leaves 0..n-1,
        # internal node for merge i = n+i)
        parent = list(range(2 * n - 1))
        size = [1] * (2 * n - 1)

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        Z = np.zeros((n - 1, 4))
        for i in range(n - 1):
            ra, rb = find(int(src[i])), find(int(dst[i]))
            new = n + i
            parent[ra] = new
            parent[rb] = new
            size[new] = size[ra] + size[rb]
            Z[i] = (min(ra, rb), max(ra, rb), h[i], size[new])
        return Z


def _full_graph(X, metric: str, res: Resources) -> COO:
    """All-pairs connectivity (LinkageDistance::PAIRWISE analog)."""
    n = X.shape[0]
    d = dist_mod.pairwise_distance(X, X, metric, res=res)
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), n)
    cols = jnp.tile(jnp.arange(n, dtype=jnp.int32), n)
    off_diag = rows != cols
    return COO(jnp.where(off_diag, rows, -1), jnp.where(off_diag, cols, 0),
               jnp.where(off_diag, d.reshape(-1), 0), (n, n))


def _cross_component_edges(X, color, metric: str, res: Resources) -> COO:
    """Min outgoing edge per component to any other component
    (sparse/neighbors/cross_component_nn.cuh analog): component-masked
    pairwise argmin, one edge (both directions) per component."""
    n = X.shape[0]
    d = dist_mod.pairwise_distance(X, X, metric, res=res)
    d = jnp.where(color[:, None] == color[None, :], jnp.inf, d)
    # per point: nearest foreign point; per component: its best point pair
    pt_best = jnp.argmin(d, axis=1).astype(jnp.int32)
    pt_w = jnp.min(d, axis=1)
    comp_w = jax.ops.segment_min(pt_w, color, num_segments=n)
    at_min = pt_w == comp_w[color]
    src = jax.ops.segment_min(
        jnp.where(at_min, jnp.arange(n, dtype=jnp.int32), n), color,
        num_segments=n,
    )
    has = src < n
    srcc = jnp.clip(src, 0, n - 1)
    dst = pt_best[srcc]
    w = pt_w[srcc]
    rows = jnp.concatenate([jnp.where(has, srcc, -1), jnp.where(has, dst, -1)])
    cols = jnp.concatenate([jnp.where(has, dst, 0), jnp.where(has, srcc, 0)])
    vals = jnp.concatenate([jnp.where(has, w, 0)] * 2).astype(jnp.float32)
    return COO(rows, cols, vals, (n, n))


def single_linkage(
    X,
    n_clusters: int,
    metric: str = "sqeuclidean",
    connectivity: str = "knn",
    c: int = 15,
    res: Optional[Resources] = None,
) -> LinkageResult:
    """Fit single-linkage hierarchical clustering and cut at ``n_clusters``
    (cluster/single_linkage.cuh:53; ``c`` controls k = log2(n) + c for the
    kNN connectivity mode, DEFAULT_CONST_C analog).
    """
    res = res or current_resources()
    X = jnp.asarray(X).astype(jnp.float32)
    if X.ndim != 2:
        raise ValueError(f"X must be 2-D, got {X.shape}")
    n = X.shape[0]
    if not 0 < n_clusters <= n:
        raise ValueError(f"need 0 < n_clusters <= {n}, got {n_clusters}")
    if connectivity not in ("knn", "pairwise"):
        raise ValueError(f"connectivity must be 'knn'|'pairwise', got {connectivity!r}")

    if connectivity == "pairwise":
        graph = _full_graph(X, metric, res)
        result = mst(graph)
    else:
        from raft_tpu.sparse.neighbors import knn_graph

        k = min(n - 1, int(math.log2(n)) + c)
        graph = knn_graph(X, k, metric=metric, res=res)
        result = mst(graph)
        from raft_tpu.core.interruptible import check_interrupt

        # repair rounds: forest → add min cross-component edges, redo MST
        for _ in range(32):
            check_interrupt()
            if int(result.n_edges) == n - 1:
                break
            extra = _cross_component_edges(X, result.color, metric, res)
            graph = COO(
                jnp.concatenate([graph.rows, extra.rows]),
                jnp.concatenate([graph.cols, extra.cols]),
                jnp.concatenate([graph.vals, extra.vals]),
                (n, n),
            )
            result = mst(graph)
        if int(result.n_edges) != n - 1:
            # still a forest after the repair budget: surface it instead of
            # mislabeling (ADVICE.md round-2 — n_clusters would misreport)
            raise RuntimeError(
                f"connectivity repair left {n - int(result.n_edges)} "
                "components (non-finite distances?); use "
                "connectivity='pairwise' or a larger c"
            )

    return _cut(result, n, int(n_clusters))


def _cut(result: MstResult, n: int, n_clusters: int) -> LinkageResult:
    """Sort merge edges by height, drop the heaviest so exactly
    ``n_clusters`` components remain, label the rest."""
    order = jnp.argsort(jnp.where(jnp.arange(result.src.shape[0]) < result.n_edges,
                                  result.weight, jnp.inf))
    src = result.src[order]
    dst = result.dst[order]
    h = result.weight[order]

    n_comp = n - result.n_edges  # components in the (possibly forest) MST
    n_drop = jnp.maximum(n_clusters - n_comp, 0)
    keep = jnp.arange(src.shape[0]) < (result.n_edges - n_drop)

    from raft_tpu.sparse.solver import connected_components

    rows = jnp.concatenate([jnp.where(keep, src, -1), jnp.where(keep, dst, -1)])
    cols = jnp.concatenate([jnp.where(keep, dst, 0), jnp.where(keep, src, 0)])
    vals = jnp.concatenate([jnp.where(keep, h, 0)] * 2)
    color = connected_components(COO(rows, cols, vals, (n, n)))
    labels, _ = make_monotonic(color)
    return LinkageResult(labels, src, dst, h, n_clusters)
