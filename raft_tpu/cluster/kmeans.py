"""Lloyd k-means with kmeans++ initialization.

Reference surface: raft::cluster::kmeans — fit (cluster/kmeans.cuh:88), predict
(:152), fit_predict (:215), transform (:244), cluster_cost (:367),
init_plus_plus (:584), fit_main (:617); params struct cluster/kmeans_types.hpp
(n_clusters, init, max_iter, tol, n_init, oversampling_factor, batch_samples).

TPU design: the reference's inner loop is fusedL2NN (assignment) + a
scatter-reduce (centroid update), tiled by ``batch_samples`` to bound the
distance-matrix workspace. Here the assignment is
:func:`raft_tpu.ops.distance.fused_l2_nn_argmin` (gemm + rank-1 correction +
row-argmin, tiled by the Resources workspace budget) and the update is
``jax.ops.segment_sum`` — both fuse into one XLA program per EM step. The EM
loop itself is a ``lax.while_loop`` carrying (centers, inertia, iteration), so
`fit` is one compiled computation regardless of iteration count: no
host↔device sync per step (the reference syncs per iteration to check the
stop condition; on TPU that would leave the chip idle every step).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.core.resources import Resources, current_resources, use_resources
from raft_tpu.core.trace import traced
from raft_tpu.ops.distance import fused_l2_nn_argmin, pairwise_distance


@dataclass(frozen=True)
class KMeansParams:
    """Hyper-parameters (aggregate-struct analog of KMeansParams,
    cluster/kmeans_types.hpp:37-110)."""

    n_clusters: int = 8
    init: str = "k-means++"  # "k-means++" | "random" | "array"
    max_iter: int = 300
    tol: float = 1e-4
    n_init: int = 1
    metric: str = "sqeuclidean"
    seed: int = 0

    def __post_init__(self):
        if self.init not in ("k-means++", "random", "array"):
            raise ValueError(f"unknown init {self.init!r}")
        if self.metric not in ("sqeuclidean", "euclidean", "l2"):
            raise ValueError("kmeans supports L2 metrics only (reference parity)")


class KMeansOutput(NamedTuple):
    centroids: jax.Array  # (n_clusters, dim)
    inertia: jax.Array  # scalar fp32, sum of squared distances to centers
    n_iter: jax.Array  # scalar int32, EM iterations executed


# ---------------------------------------------------------------------------
# EM pieces
# ---------------------------------------------------------------------------


def _update_centers(X, labels, weights, n_clusters, old_centers):
    """M step: weighted per-cluster mean; empty clusters keep their center."""
    w = weights[:, None]
    sums = jax.ops.segment_sum(X * w, labels, num_segments=n_clusters)
    counts = jax.ops.segment_sum(weights, labels, num_segments=n_clusters)
    safe = jnp.maximum(counts, 1e-12)[:, None]
    return jnp.where(counts[:, None] > 0, sums / safe, old_centers), counts


@functools.partial(jax.jit, static_argnames=("max_iter", "tol", "n_clusters", "workspace_bytes"))
def _lloyd(X, centers0, weights, max_iter, tol, n_clusters, workspace_bytes=None):
    """Whole-fit-in-one-program Lloyd loop (fit_main analog, kmeans.cuh:617).

    ``workspace_bytes`` only keys the jit cache: the inner fused_l2_nn_argmin
    reads the scoped Resources at trace time for its tile budget, so a changed
    budget must force a retrace."""
    del workspace_bytes

    def em_step(centers):
        d2, labels = fused_l2_nn_argmin(X, centers)
        new_centers, _ = _update_centers(X, labels, weights, n_clusters, centers)
        inertia = jnp.sum(d2 * weights)
        return new_centers, inertia

    def cond(carry):
        _, inertia, prev_inertia, it = carry
        # converged once inertia stops improving by a relative tol
        not_converged = inertia < prev_inertia * (1.0 - tol)
        return jnp.logical_and(it < max_iter, not_converged)

    def body(carry):
        centers, inertia, _, it = carry
        new_centers, new_inertia = em_step(centers)
        return new_centers, new_inertia, inertia, it + 1

    centers1, inertia1 = em_step(centers0)
    centers, inertia, _, n_iter = lax.while_loop(
        cond, body, (centers1, inertia1, jnp.float32(jnp.inf), jnp.int32(1))
    )
    # final assignment determines reported inertia for the *returned* centers
    d2, _ = fused_l2_nn_argmin(X, centers)
    return centers, jnp.sum(d2 * weights), n_iter


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_clusters",))
def _init_plus_plus(key, X, weights, n_clusters):
    """kmeans++ seeding (init_plus_plus analog, cluster/kmeans.cuh:584):
    first center uniform; each next sampled ∝ weight·D²(x) to chosen centers.

    One `fori_loop` iteration per center is a full (n, dim) distance sweep;
    at the reference-typical n_lists of 1024–65536 that is k sequential
    passes over the whole dataset, so seeding runs on a size-capped random
    subsample (the reference trains on sampled trainsets for the same
    reason, ivf_flat_types.hpp:55 kmeans_trainset_fraction): Lloyd
    iterations afterwards see the full data, and ++-on-a-sample loses
    nothing measurable at these sizes.
    """
    n = X.shape[0]
    max_rows = max(4 * n_clusters, 16384)
    if n > max_rows:
        ks, key = jax.random.split(key)
        rows = jax.random.choice(ks, n, (max_rows,), replace=False)
        X = X[rows]
        weights = weights[rows] if weights is not None else None
        n = max_rows
    k0, key = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centers = jnp.zeros((n_clusters, X.shape[1]), X.dtype).at[0].set(X[first])
    d2 = jnp.sum((X - X[first]) ** 2, axis=1)

    def body(i, carry):
        centers, d2, key = carry
        kc, key = jax.random.split(key)
        p = d2 * weights
        nxt = jax.random.categorical(kc, jnp.log(jnp.maximum(p, 1e-30)))
        centers = centers.at[i].set(X[nxt])
        d2 = jnp.minimum(d2, jnp.sum((X - X[nxt]) ** 2, axis=1))
        return centers, d2, key

    centers, _, _ = lax.fori_loop(1, n_clusters, body, (centers, d2, key))
    return centers


def _init_random(key, X, n_clusters):
    rows = jax.random.choice(key, X.shape[0], (n_clusters,), replace=False)
    return X[rows]


# ---------------------------------------------------------------------------
# Public API (mirrors cluster/kmeans.cuh + pylibraft cluster/kmeans.pyx)
# ---------------------------------------------------------------------------


@traced("kmeans::fit")
def fit(
    X,
    params: KMeansParams = KMeansParams(),
    sample_weight=None,
    centroids=None,
    res: Optional[Resources] = None,
) -> KMeansOutput:
    """Train k-means (raft::cluster::kmeans::fit, cluster/kmeans.cuh:88).

    Runs ``params.n_init`` independent seeded fits and keeps the lowest-inertia
    one (kmeans_types.hpp n_init). ``centroids`` seeds the fit when
    ``params.init == "array"`` (InitMethod::Array).
    """
    res = res or current_resources()
    X = jnp.asarray(X)
    n = X.shape[0]
    if params.n_clusters > n:
        raise ValueError(f"n_clusters={params.n_clusters} > n_samples={n}")
    weights = (
        jnp.ones((n,), jnp.float32)
        if sample_weight is None
        else jnp.asarray(sample_weight, jnp.float32)
    )
    key = jax.random.key(params.seed)

    from raft_tpu.core.interruptible import check_interrupt
    from raft_tpu.resilience import active_deadline, faultpoint

    best: Optional[KMeansOutput] = None
    for _ in range(max(1, params.n_init)):
        # the EM itself is one sync-free compiled program; the host-side
        # checkpoint site (core/interruptible docstring) is the n_init
        # restart loop. A spent Deadline keeps the best fit so far
        # (degraded = fewer restarts, still a valid model) instead of
        # being killed opaquely mid-restart.
        dl = active_deadline()
        if dl is not None and best is not None and dl.reached():
            dl.mark_degraded("kmeans.fit")
            break
        check_interrupt()
        faultpoint("kmeans.fit.em")
        kinit, key = jax.random.split(key)
        if params.init == "array":
            if centroids is None:
                raise ValueError('init="array" requires centroids')
            centers0 = jnp.asarray(centroids)
        elif params.init == "random":
            centers0 = _init_random(kinit, X, params.n_clusters)
        else:
            centers0 = _init_plus_plus(kinit, X, weights, params.n_clusters)
        with use_resources(res):
            out = KMeansOutput(
                *_lloyd(
                    X, centers0, weights, params.max_iter, float(params.tol),
                    params.n_clusters, int(res.workspace_bytes),
                )
            )
        if best is None or float(out.inertia) < float(best.inertia):
            best = out
        if params.init == "array":
            break  # deterministic start: n_init re-runs would be identical
    assert best is not None
    if params.metric == "euclidean":
        # euclidean objective = sum of distances, not sum of squares
        d, _ = fused_l2_nn_argmin(X, best.centroids, sqrt=True, res=res)
        best = best._replace(inertia=jnp.sum(d * weights))
    if obs.enabled():
        obs.add("kmeans.fits", 1)
        obs.add("kmeans.rows", n)
        # int() is a host fetch — paid only with telemetry on; the EM loop
        # itself stays one sync-free compiled program
        obs.add("kmeans.iterations", int(best.n_iter))
    return best


def predict(
    X, centroids, sample_weight=None, res: Optional[Resources] = None
) -> Tuple[jax.Array, jax.Array]:
    """Assign each row to its nearest centroid → (labels, inertia)
    (raft::cluster::kmeans::predict, cluster/kmeans.cuh:152)."""
    X = jnp.asarray(X)
    centroids = jnp.asarray(centroids)
    d2, labels = fused_l2_nn_argmin(X, centroids, res=res)
    if sample_weight is not None:
        d2 = d2 * jnp.asarray(sample_weight, jnp.float32)
    return labels, jnp.sum(d2)


@traced("kmeans::fit_predict")
def fit_predict(
    X,
    params: KMeansParams = KMeansParams(),
    sample_weight=None,
    centroids=None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, KMeansOutput]:
    """fit + predict in one call (cluster/kmeans.cuh:215)."""
    out = fit(X, params, sample_weight=sample_weight, centroids=centroids, res=res)
    labels, _ = predict(X, out.centroids, res=res)
    return labels, out


def transform(X, centroids, res: Optional[Resources] = None) -> jax.Array:
    """Distance from every row to every centroid (cluster/kmeans.cuh:244)."""
    return pairwise_distance(X, centroids, metric="sqeuclidean", res=res)


def cluster_cost(X, centroids, res: Optional[Resources] = None) -> jax.Array:
    """Sum of squared distances to nearest centroid (cluster/kmeans.cuh:367)."""
    d2, _ = fused_l2_nn_argmin(X, centroids, res=res)
    return jnp.sum(d2)
