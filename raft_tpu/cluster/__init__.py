"""Clustering algorithms (reference cpp/include/raft/cluster/, SURVEY.md §2.4).

  * :mod:`raft_tpu.cluster.kmeans` — Lloyd k-means with kmeans++ init
    (cluster/kmeans.cuh).
  * :mod:`raft_tpu.cluster.kmeans_balanced` — balanced hierarchical k-means,
    the IVF coarse-quantizer trainer (cluster/kmeans_balanced.cuh).
  * :mod:`raft_tpu.cluster.single_linkage` — MST-based agglomerative
    clustering (cluster/single_linkage.cuh).
"""

from raft_tpu.cluster import kmeans, kmeans_balanced, single_linkage
from raft_tpu.cluster.kmeans import KMeansParams
from raft_tpu.cluster.single_linkage import LinkageResult
from raft_tpu.cluster.single_linkage import single_linkage as single_linkage_fn

__all__ = ["kmeans", "kmeans_balanced", "single_linkage", "single_linkage_fn",
           "KMeansParams", "LinkageResult"]
