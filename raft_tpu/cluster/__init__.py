"""Clustering algorithms (reference cpp/include/raft/cluster/, SURVEY.md §2.4).

  * :mod:`raft_tpu.cluster.kmeans` — Lloyd k-means with kmeans++ init
    (cluster/kmeans.cuh).
  * :mod:`raft_tpu.cluster.kmeans_balanced` — balanced hierarchical k-means,
    the IVF coarse-quantizer trainer (cluster/kmeans_balanced.cuh).
  * single-linkage agglomerative clustering arrives with the sparse/MST layer.
"""

from raft_tpu.cluster import kmeans, kmeans_balanced
from raft_tpu.cluster.kmeans import KMeansParams

__all__ = ["kmeans", "kmeans_balanced", "KMeansParams"]
