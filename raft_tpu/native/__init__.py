"""Native (C++) runtime components with ctypes bindings.

The compute path of this framework is JAX/XLA/Pallas; the runtime around it
— bulk host IO like the hnswlib-format writer — is native C++ like the
reference's, compiled on demand with the system toolchain and cached next
to the source. Every native entry point has a pure-Python fallback so the
package works without a compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_lib() -> Optional[str]:
    src = os.path.join(_DIR, "hnsw_writer.cpp")
    out = os.path.join(_DIR, "_raft_tpu_native.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", out],
            check=True, capture_output=True, timeout=120,
        )
        return out
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None


def get_native_lib() -> Optional[ctypes.CDLL]:
    """The compiled native library, building it on first use; None when no
    toolchain is available (callers fall back to Python)."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = _build_lib()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.raft_tpu_write_hnsw.restype = ctypes.c_int
            lib.raft_tpu_write_hnsw.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_float),
                ctypes.c_uint64,
            ]
        except (OSError, AttributeError):
            # stale/foreign-arch cached .so: fall back to pure Python
            return None
        _LIB = lib
        return _LIB
