// Native hnswlib-format writer (reference analog:
// neighbors/detail/cagra/cagra_serialize.cuh serialize_to_hnswlib).
//
// Writes a base-layer-only hnswlib HierarchicalNSW index file from a
// fixed-degree kNN graph + row-major dataset, streaming row by row so the
// interleaved element blocks (links | vector | label) never materialize in
// memory — the kind of buffered host IO the reference keeps in C++, kept in
// C++ here too. Exposed via a C ABI for the ctypes binding in
// raft_tpu/native/__init__.py.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {

// returns 0 on success, negative errno-style codes on failure
int raft_tpu_write_hnsw(const char* path,
                        uint64_t n,
                        uint32_t dim,
                        uint32_t degree,
                        const uint32_t* graph,   // (n, degree) row-major
                        const float* data,       // (n, dim) row-major
                        uint64_t entrypoint) {
  FILE* f = std::fopen(path, "wb");
  if (f == nullptr) { return -1; }

  auto w = [&](const void* p, size_t bytes) {
    return std::fwrite(p, 1, bytes, f) == bytes;
  };

  bool ok = true;
  const uint64_t offset_level_0 = 0;
  const uint64_t max_element = n;
  const uint64_t curr_element_count = n;
  // per element: [links_count u32][degree x u32][dim x f32][label u64]
  const uint64_t size_data_per_element =
      static_cast<uint64_t>(degree) * 4 + 4 + static_cast<uint64_t>(dim) * 4 + 8;
  const uint64_t label_offset = size_data_per_element - 8;
  const uint64_t offset_data = static_cast<uint64_t>(degree) * 4 + 4;
  // 0, not the reference's 1: a base-layer-only index with max_level=0
  // skips upper-level traversal in STOCK hnswlib (the reference's 1 only
  // works with its patched base_layer_only loader)
  const int32_t max_level = 0;
  const int32_t entry = static_cast<int32_t>(entrypoint);
  const uint64_t max_m = degree / 2;
  const uint64_t max_m0 = degree;
  const uint64_t m = degree / 2;
  const double mult = 0.42424242;  // unused by base-layer-only search
  const uint64_t ef_construction = 500;

  ok = ok && w(&offset_level_0, 8) && w(&max_element, 8) &&
       w(&curr_element_count, 8) && w(&size_data_per_element, 8) &&
       w(&label_offset, 8) && w(&offset_data, 8) && w(&max_level, 4) &&
       w(&entry, 4) && w(&max_m, 8) && w(&max_m0, 8) && w(&m, 8) &&
       w(&mult, 8) && w(&ef_construction, 8);

  const int32_t degree_i = static_cast<int32_t>(degree);
  for (uint64_t i = 0; ok && i < n; ++i) {
    ok = ok && w(&degree_i, 4);
    ok = ok && w(graph + i * degree, static_cast<size_t>(degree) * 4);
    ok = ok && w(data + i * dim, static_cast<size_t>(dim) * 4);
    ok = ok && w(&i, 8);
  }
  const int32_t zero = 0;
  for (uint64_t i = 0; ok && i < n; ++i) { ok = ok && w(&zero, 4); }

  if (std::fclose(f) != 0) { return -3; }
  return ok ? 0 : -2;
}

}  // extern "C"
