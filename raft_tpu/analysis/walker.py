"""File discovery, parsing, and rule dispatch.

One :class:`ModuleContext` per file carries everything rules need — the AST
(with ``.parent`` links added so rules can climb), raw source lines for
snippets, the module's import table, the lazy jit-region index, and inline
suppressions (``# graftlint: ignore`` or ``# graftlint: ignore[rule-id]`` on
the offending line).

``analyze_paths`` is the library entry the CLI and tests share: collect,
parse, run every rule, drop suppressed findings, return the rest sorted.
A file that fails to parse yields a single ``parse-error`` finding instead
of killing the run (tier-1 must report, not crash, on a bad checkout).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

from raft_tpu.analysis.findings import Finding, sort_findings
from raft_tpu.analysis.jit_regions import JitRegions

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "results",
              "build", "dist", ".eggs", "archive"}

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ignore(?:\[([A-Za-z0-9_,\- ]+)\])?")


@dataclass
class ModuleContext:
    """Parsed module + per-file indexes handed to every rule."""

    path: Path
    rel: str                       # repo-relative, forward slashes
    tree: ast.Module
    lines: List[str]
    imports: Dict[str, str] = field(default_factory=dict)
    _jit: Optional[JitRegions] = None
    #: the repo-wide ProjectContext for this scan (set by analyze_paths);
    #: None when a rule is driven over a lone hand-built context
    project: Optional[object] = None

    @property
    def jit(self) -> JitRegions:
        if self._jit is None:
            self._jit = JitRegions(self.tree)
        return self._jit

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, line: int, rule_id: str) -> bool:
        m = _SUPPRESS_RE.search(self.snippet(line))
        if not m:
            return False
        if m.group(1) is None:
            return True
        wanted = {s.strip() for s in m.group(1).split(",")}
        return rule_id in wanted


def _import_table(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted origin (``np`` -> ``numpy``, ``jnp`` ->
    ``jax.numpy``, ``partial`` -> ``functools.partial``)."""
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                table[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                table[a.asname or a.name] = f"{node.module}.{a.name}"
    return table


def _link_parents(tree: ast.Module) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def parse_module(path: Path, root: Path) -> ModuleContext:
    """Parse one file into a ModuleContext (raises SyntaxError upward)."""
    source = path.read_text(encoding="utf-8", errors="replace")
    tree = ast.parse(source, filename=str(path))
    _link_parents(tree)
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return ModuleContext(
        path=path,
        rel=rel,
        tree=tree,
        lines=source.splitlines(),
        imports=_import_table(tree),
    )


def collect_files(paths: Sequence, root: Optional[Path] = None) -> List[Path]:
    """Expand files/dirs into a sorted, deduped .py file list.

    A path that is neither an existing ``.py`` file nor a directory raises
    ``FileNotFoundError``: a typo'd scan target must fail the gate loudly,
    not shrink it to a green no-op (``bench.pyy`` scanning nothing and
    exiting 0 would be the exact silent-pass failure the baseline machinery
    exists to prevent).
    """
    root = Path(root) if root else Path.cwd()
    out: Set[Path] = set()
    for p in paths:
        p = Path(p)
        if not p.is_absolute():
            p = root / p
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not _SKIP_DIRS.intersection(f.relative_to(p).parts[:-1]):
                    out.add(f)
        elif p.suffix == ".py" and p.exists():
            out.add(p)
        else:
            raise FileNotFoundError(
                f"graftlint: scan path {p} is neither a .py file nor a "
                f"directory")
    return sorted(out)


def analyze_paths(paths: Sequence, rules: Optional[Iterable] = None,
                  root: Optional[Path] = None) -> List[Finding]:
    """Run ``rules`` (default: the full registry) over ``paths``."""
    from raft_tpu.analysis.registry import all_rules

    from raft_tpu.analysis.projectgraph import ProjectContext

    root = Path(root) if root else Path.cwd()
    active = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    # two-phase: parse everything first so interprocedural rules see the
    # whole scan set (call graph, lock table, faultpoint/arming inventory)
    # through ctx.project, then dispatch rules file by file as before
    contexts: List[ModuleContext] = []
    for path in collect_files(paths, root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            contexts.append(parse_module(path, root))
        except SyntaxError as e:
            findings.append(Finding(
                path=rel, line=e.lineno or 0, rule="parse-error",
                severity="error", message=f"cannot parse: {e.msg}"))
    project = ProjectContext(contexts, root)
    for ctx in contexts:
        ctx.project = project
        for rule in active:
            for f in rule.check(ctx):
                if not ctx.suppressed(f.line, f.rule):
                    findings.append(f)
    return sort_findings(findings)
