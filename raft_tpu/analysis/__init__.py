"""graftlint: JAX/TPU-aware static analysis for the raft_tpu tree.

Round 5 burned a scarce TPU bench window discovering failure classes that are
decidable from source alone — host syncs hiding in hot loops, Python control
flow on traced values, un-instrumented hot paths (VERDICT.md r5; the ROADMAP
"telemetry is a prerequisite" open item). This package is the cheap CPU-side
gate: an AST walk over the whole repo on every tier-1 run, with a pluggable
rule registry targeting this codebase's real bug classes and a checked-in
baseline so grandfathered findings stay visible-but-silent while any NEW
finding fails the build.

Layout (one module per concern):

* :mod:`raft_tpu.analysis.findings`    — Finding record + text/JSON report formats
* :mod:`raft_tpu.analysis.registry`    — pluggable rule registry (``@register``)
* :mod:`raft_tpu.analysis.jit_regions` — jit/pallas region resolver (which
  functions run under a tracer, incl. same-module call-graph reachability)
* :mod:`raft_tpu.analysis.walker`      — file discovery, parse, rule dispatch,
  inline ``# graftlint: ignore[rule]`` suppression
* :mod:`raft_tpu.analysis.baseline`    — grandfathered-finding store
* :mod:`raft_tpu.analysis.cli`         — ``python -m raft_tpu.analysis``
* :mod:`raft_tpu.analysis.rules`       — the rule catalog

Usage::

    python -m raft_tpu.analysis raft_tpu tests bench.py scripts
    python -m raft_tpu.analysis --list-rules
    python -m raft_tpu.analysis --json raft_tpu

Exit codes: 0 = no new findings, 1 = new findings (not in the baseline),
2 = bad invocation. Regenerate the baseline DELIBERATELY via
``scripts/analysis_baseline.py`` — never automatically.
"""

from raft_tpu.analysis.findings import Finding, Severity, format_json, format_text
from raft_tpu.analysis.registry import Rule, all_rules, get_rule, register
from raft_tpu.analysis.walker import ModuleContext, analyze_paths, collect_files
from raft_tpu.analysis.baseline import Baseline

__all__ = [
    "Baseline",
    "Finding",
    "ModuleContext",
    "Rule",
    "Severity",
    "all_rules",
    "analyze_paths",
    "collect_files",
    "format_json",
    "format_text",
    "get_rule",
    "register",
]
