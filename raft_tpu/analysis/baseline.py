"""Baseline store: grandfathered findings that stay silent until they move.

The baseline is a checked-in JSON file mapping finding identities
(``rule`` + ``path`` + source ``snippet`` — line numbers deliberately
excluded, see findings.Finding.key) to an allowed ``count`` and a one-line
human ``justification``. The analyzer subtracts the baseline from its raw
findings; anything left is NEW and fails the run. A baselined line that is
fixed simply stops matching (stale entries are pruned on regeneration);
a baselined pattern that spreads (count exceeded) gets loud again.

Regeneration is a deliberate act (``scripts/analysis_baseline.py``), never a
side effect of a normal run — an auto-refreshing baseline would grandfather
every regression the moment it lands.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Iterable, List, Optional, Tuple

from raft_tpu.analysis.findings import Finding

_VERSION = 1
_TODO = "TODO: justify or fix"


class Baseline:
    """In-memory view of the baseline file."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []

    # -- persistence --------------------------------------------------------

    @classmethod
    def load(cls, path) -> "Baseline":
        path = Path(path)
        if not path.exists():
            return cls([])
        data = json.loads(path.read_text(encoding="utf-8"))
        entries = data.get("entries", []) if isinstance(data, dict) else data
        return cls([e for e in entries if isinstance(e, dict)])

    def save(self, path) -> None:
        from raft_tpu.core.fsio import atomic_write

        entries = sorted(
            self.entries,
            key=lambda e: (e.get("path", ""), e.get("rule", ""),
                           e.get("snippet", "")),
        )
        payload = {
            "version": _VERSION,
            "tool": "graftlint (raft_tpu.analysis)",
            "note": "regenerate DELIBERATELY via scripts/analysis_baseline.py;"
                    " every entry needs a one-line justification",
            "entries": entries,
        }
        # atomic (ISSUE 7): a baseline truncated by a mid-write kill would
        # turn every grandfathered finding loud on the next tier-1 run
        with atomic_write(Path(path), "w") as f:
            f.write(json.dumps(payload, indent=2) + "\n")

    # -- matching -----------------------------------------------------------

    def _allowance(self) -> Counter:
        c: Counter = Counter()
        for e in self.entries:
            key = (e.get("rule", ""), e.get("path", ""), e.get("snippet", ""))
            c[key] += int(e.get("count", 1))
        return c

    def filter(self, findings: Iterable[Finding]) -> Tuple[List[Finding], int]:
        """Split findings into (new, n_baselined). Each baseline entry
        absorbs up to ``count`` findings with the same identity."""
        allowance = self._allowance()
        new: List[Finding] = []
        absorbed = 0
        for f in findings:
            if allowance.get(f.key(), 0) > 0:
                allowance[f.key()] -= 1
                absorbed += 1
            else:
                new.append(f)
        return new, absorbed

    # -- regeneration -------------------------------------------------------

    @classmethod
    def from_findings(cls, findings: Iterable[Finding],
                      previous: Optional["Baseline"] = None) -> "Baseline":
        """Build a fresh baseline covering ``findings`` exactly, carrying
        justifications (and nothing else) forward from ``previous``."""
        just = {}
        if previous is not None:
            for e in previous.entries:
                key = (e.get("rule", ""), e.get("path", ""),
                       e.get("snippet", ""))
                if e.get("justification") and e["justification"] != _TODO:
                    just[key] = e["justification"]
        counts: Counter = Counter(f.key() for f in findings)
        sev = {f.key(): f.severity for f in findings}
        entries = []
        for (rule, path, snippet), count in sorted(counts.items()):
            key = (rule, path, snippet)
            entries.append({
                "rule": rule,
                "path": path,
                "snippet": snippet,
                "count": count,
                "severity": sev[key],
                "justification": just.get(key, _TODO),
            })
        return cls(entries)

    def todo_entries(self) -> List[dict]:
        """Entries still carrying the placeholder justification."""
        return [e for e in self.entries if e.get("justification") == _TODO]
