"""Repo-wide interprocedural indexes for the concurrency-discipline rules.

Everything per-file stays in :class:`walker.ModuleContext`; this module adds
the cross-file view the round-19 rule families need:

* a **class table** (``rel::ClassName``) with each class's methods, its
  ``threading`` lock attributes, its ``# guarded-by:`` field annotations
  and its ``# holds:`` method declarations;
* **lock-dominance** resolution: whether an attribute access is inside a
  ``with self._lock:`` scope, or inside a method that provably only runs
  with the lock held (construction methods, ``*_locked`` names, ``# holds:``
  declarations, and a fixed point over intra-class call sites);
* the **lock-acquisition graph**: which locks are held when another is
  taken, following calls through a best-effort intra-repo call graph, with
  reentrancy-aware self-edges and SCC-based cycle detection;
* **faultpoint** and **env-knob** site inventories for the contract rules.

Annotation grammar (one comment, on the line of the assignment)::

    self._ring = deque()          # guarded-by: _lock
    self._window = 0              # guarded-by: _lock, reads-ok
    _SPANS = deque(maxlen=cap)    # guarded-by: _LOCK        (module level)

``reads-ok`` tolerates unlocked *reads* — the snapshot-then-release and
monotonic-counter escape patterns — while still requiring every write to
hold the lock. A method that is only ever entered with the lock held but is
called through a non-self receiver (construction-phase helpers like the
store's ``_ingest_packed``) declares it on its ``def`` line::

    def _ingest_packed(self, index):  # holds: _lock

The analysis is deliberately a *may* analysis: unresolvable calls and
attribute receivers are skipped, so it under-approximates the acquisition
graph rather than inventing edges. Flow inside a function is syntactic
(``with`` nesting), which matches how every lock in this repo is taken
except the compaction manager's try-acquire, which guards no annotated
state directly.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_GUARD_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)(\s*,\s*reads-ok)?")
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: threading constructors that create a lock-like object, and whether a
#: second acquisition by the owning thread is legal (reentrant).
_LOCK_CTORS = {
    "Lock": False,
    "RLock": True,
    "Condition": True,      # backed by an RLock unless one is passed in
    "Semaphore": True,      # counting: self-acquire is legal by design
    "BoundedSemaphore": True,
}

#: methods that run before the object is published (or after the last
#: reference dies) — field access there needs no lock by construction.
_CONSTRUCTION_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}

_FAULT_KINDS = ("oom", "transient", "fatal", "delay", "hang")
_ARM_RE = re.compile(
    r"^([A-Za-z0-9_.\-]+)=(" + "|".join(_FAULT_KINDS) + r")(:\d+(:[0-9.]+)?)?$")
_KNOB_PREFIX = "RAFT_TPU_"


@dataclass
class FieldGuard:
    """One ``# guarded-by:`` annotation on a class field or module global."""

    name: str
    lock: str
    reads_ok: bool
    line: int


@dataclass
class ClassInfo:
    rel: str
    name: str
    node: ast.ClassDef
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    locks: Dict[str, str] = field(default_factory=dict)      # attr -> ctor
    guarded: Dict[str, FieldGuard] = field(default_factory=dict)
    holds: Dict[str, Set[str]] = field(default_factory=dict)  # method -> locks
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> class key

    @property
    def key(self) -> str:
        return f"{self.rel}::{self.name}"


@dataclass
class LockSite:
    """One acquisition edge example, for reports and the --graph dump."""

    held: str
    taken: str
    rel: str
    line: int


def _call_name(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Flatten a call target into a dotted name tuple, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _lock_ctor(call: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """``threading.RLock()`` / ``Lock()`` (from-imported) -> ctor name."""
    if not isinstance(call, ast.Call):
        return None
    name = _call_name(call.func)
    if name is None:
        return None
    if len(name) == 2 and imports.get(name[0]) == "threading" \
            and name[1] in _LOCK_CTORS:
        return name[1]
    if len(name) == 1 and name[0] in _LOCK_CTORS \
            and imports.get(name[0]) == f"threading.{name[0]}":
        return name[0]
    return None


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


def _enclosing_method(node: ast.AST, cls: ast.ClassDef) -> Optional[ast.AST]:
    """The class method whose body (transitively, through nested defs and
    lambdas) contains ``node`` — or None for class-body code."""
    best = None
    cur = getattr(node, "parent", None)
    while cur is not None and cur is not cls:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and getattr(cur, "parent", None) is cls:
            best = cur
        cur = getattr(cur, "parent", None)
    return best if cur is cls else None


def _with_locks_on_path(node: ast.AST, stop: ast.AST) -> Set[str]:
    """Lock names (self attrs and bare module names) acquired by ``with``
    statements on the ancestor path from ``node`` up to ``stop``."""
    out: Set[str] = set()
    cur = getattr(node, "parent", None)
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                if _is_self_attr(expr):
                    out.add(expr.attr)
                elif isinstance(expr, ast.Name):
                    out.add(expr.id)
        cur = getattr(cur, "parent", None)
    return out


class ProjectContext:
    """Lazy cross-file indexes shared by every interprocedural rule.

    Built once per :func:`walker.analyze_paths` run over the parsed module
    set; each heavyweight product (class table, acquisition graph, rule
    verdicts) is computed on first use and cached, so scans that select
    only per-file rules pay nothing for it.
    """

    def __init__(self, contexts: List, root) -> None:
        self.contexts = {ctx.rel: ctx for ctx in contexts}
        self.root = root
        self._classes: Optional[Dict[str, ClassInfo]] = None
        self._module_guards: Optional[Dict[str, List[FieldGuard]]] = None
        self._module_locks: Optional[Dict[str, Dict[str, str]]] = None
        self._guarded_cache: Optional[List[tuple]] = None
        self._graph_cache: Optional[dict] = None
        self._summaries: Optional[Dict[str, Set[str]]] = None
        self._faultpoints: Optional[List[tuple]] = None
        self._armings: Optional[List[tuple]] = None
        self._knob_cache: Optional[List[tuple]] = None

    # -- module name resolution ---------------------------------------------

    def rel_for_module(self, dotted: str) -> Optional[str]:
        """``raft_tpu.obs.flight`` -> ``raft_tpu/obs/flight.py`` when that
        file is part of this scan, else None."""
        base = dotted.replace(".", "/")
        for cand in (f"{base}.py", f"{base}/__init__.py"):
            if cand in self.contexts:
                return cand
        return None

    # -- class / guard tables -----------------------------------------------

    @property
    def classes(self) -> Dict[str, ClassInfo]:
        if self._classes is None:
            self._build_tables()
        return self._classes

    @property
    def module_guards(self) -> Dict[str, List[FieldGuard]]:
        """rel -> guarded module-level globals."""
        if self._module_guards is None:
            self._build_tables()
        return self._module_guards

    @property
    def module_locks(self) -> Dict[str, Dict[str, str]]:
        """rel -> {module lock name: ctor}."""
        if self._module_locks is None:
            self._build_tables()
        return self._module_locks

    def _guard_on_line(self, ctx, line: int) -> Optional[Tuple[str, bool]]:
        m = _GUARD_RE.search(ctx.snippet(line))
        if not m:
            return None
        return m.group(1), bool(m.group(2))

    def _build_tables(self) -> None:
        self._classes = {}
        self._module_guards = {}
        self._module_locks = {}
        for rel, ctx in self.contexts.items():
            guards: List[FieldGuard] = []
            locks: Dict[str, str] = {}
            for stmt in ctx.tree.body:
                targets = []
                value = None
                if isinstance(stmt, ast.Assign):
                    targets, value = stmt.targets, stmt.value
                elif isinstance(stmt, ast.AnnAssign):
                    targets, value = [stmt.target], stmt.value
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    ctor = _lock_ctor(value, ctx.imports)
                    if ctor:
                        locks[t.id] = ctor
                    g = self._guard_on_line(ctx, stmt.lineno)
                    if g:
                        guards.append(FieldGuard(t.id, g[0], g[1], stmt.lineno))
            if guards:
                self._module_guards[rel] = guards
            if locks:
                self._module_locks[rel] = locks
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef):
                    info = self._build_class(ctx, node)
                    self._classes[info.key] = info

    def _build_class(self, ctx, node: ast.ClassDef) -> ClassInfo:
        info = ClassInfo(rel=ctx.rel, name=node.name, node=node)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[stmt.name] = stmt
                held = self._holds_decl(ctx, stmt)
                if held:
                    info.holds[stmt.name] = held
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                # class-level field: X = ... / X: T [= ...]  # guarded-by: L
                t = stmt.targets[0] if isinstance(stmt, ast.Assign) \
                    else stmt.target
                if isinstance(t, ast.Name):
                    g = self._guard_on_line(ctx, stmt.lineno)
                    if g:
                        info.guarded[t.id] = FieldGuard(
                            t.id, g[0], g[1], stmt.lineno)
        for meth in info.methods.values():
            for sub in ast.walk(meth):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        if not _is_self_attr(t):
                            continue
                        ctor = _lock_ctor(sub.value, ctx.imports)
                        if ctor:
                            info.locks[t.attr] = ctor
                        g = self._guard_on_line(ctx, sub.lineno)
                        if g:
                            info.guarded.setdefault(t.attr, FieldGuard(
                                t.attr, g[0], g[1], sub.lineno))
                        tkey = self._attr_class_key(ctx, sub.value)
                        if tkey:
                            info.attr_types[t.attr] = tkey
        return info

    def _holds_decl(self, ctx, meth) -> Set[str]:
        """``# holds: _lock`` on the def line (or the signature lines of a
        multi-line def)."""
        out: Set[str] = set()
        first_body = meth.body[0].lineno if meth.body else meth.lineno + 1
        for line in range(meth.lineno, first_body):
            m = _HOLDS_RE.search(ctx.snippet(line))
            if m:
                out.add(m.group(1))
        return out

    def _attr_class_key(self, ctx, value) -> Optional[str]:
        """``self.x = ClassName(...)`` -> the key of ClassName when it is a
        class in this scan (same module, or a from-import)."""
        if not isinstance(value, ast.Call):
            return None
        name = _call_name(value.func)
        if name is None:
            return None
        if len(name) == 1:
            origin = ctx.imports.get(name[0])
            if origin and "." in origin:
                mod, cls = origin.rsplit(".", 1)
                rel = self.rel_for_module(mod)
                if rel:
                    key = f"{rel}::{cls}"
                    return key
            return f"{ctx.rel}::{name[0]}"
        if len(name) == 2:
            mod = ctx.imports.get(name[0])
            if mod:
                rel = self.rel_for_module(mod)
                if rel:
                    return f"{rel}::{name[1]}"
        return None

    # -- guarded-state ------------------------------------------------------

    def _held_methods(self, info: ClassInfo, lock: str) -> Set[str]:
        """Methods that provably run with ``lock`` held on entry: fixed
        point over construction methods, ``*_locked`` names, ``# holds:``
        declarations, and intra-class self-call sites."""
        held = {
            name for name in info.methods
            if name in _CONSTRUCTION_METHODS
            or name.endswith("_locked")
            or lock in info.holds.get(name, set())
        }
        # collect self-call sites per callee once
        sites: Dict[str, List[ast.AST]] = {}
        for name, meth in info.methods.items():
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Call) and _is_self_attr(sub.func) \
                        and sub.func.attr in info.methods:
                    sites.setdefault(sub.func.attr, []).append(sub)
        changed = True
        while changed:
            changed = False
            for callee, calls in sites.items():
                if callee in held:
                    continue
                ok = True
                for call in calls:
                    meth = _enclosing_method(call, info.node)
                    if meth is None:
                        ok = False
                        break
                    if meth.name in held:
                        continue
                    if lock not in _with_locks_on_path(call, meth):
                        ok = False
                        break
                if ok and calls:
                    held.add(callee)
                    changed = True
        return held

    def guarded_state_results(self) -> List[tuple]:
        """All guarded-state violations project-wide, as
        ``(rel, line, message)`` tuples (cached)."""
        if self._guarded_cache is not None:
            return self._guarded_cache
        out: List[tuple] = []
        for info in self.classes.values():
            out.extend(self._check_class_guards(info))
        for rel, guards in self.module_guards.items():
            out.extend(self._check_module_guards(rel, guards))
        self._guarded_cache = out
        return out

    def _check_class_guards(self, info: ClassInfo) -> List[tuple]:
        out: List[tuple] = []
        held_cache: Dict[str, Set[str]] = {}
        for fname, guard in info.guarded.items():
            if guard.lock not in info.locks:
                out.append((info.rel, guard.line,
                            f"field '{fname}' is guarded-by '{guard.lock}' "
                            f"but {info.name} constructs no threading lock "
                            f"named '{guard.lock}'"))
                continue
            if guard.lock not in held_cache:
                held_cache[guard.lock] = self._held_methods(info, guard.lock)
            held = held_cache[guard.lock]
            for meth in info.methods.values():
                for sub in ast.walk(meth):
                    if not _is_self_attr(sub, fname):
                        continue
                    is_read = isinstance(sub.ctx, ast.Load)
                    if guard.reads_ok and is_read:
                        continue
                    outer = _enclosing_method(sub, info.node)
                    if outer is None or outer.name in held:
                        continue
                    if guard.lock in _with_locks_on_path(sub, outer):
                        continue
                    kind = "read" if is_read else "write"
                    out.append((
                        info.rel, sub.lineno,
                        f"{kind} of {info.name}.{fname} (guarded-by "
                        f"'{guard.lock}') in {outer.name}() is not inside "
                        f"'with self.{guard.lock}:' and {outer.name} is not "
                        f"lock-held on entry"))
        return out

    def _check_module_guards(self, rel: str, guards) -> List[tuple]:
        out: List[tuple] = []
        ctx = self.contexts[rel]
        locks = self.module_locks.get(rel, {})
        for guard in guards:
            if guard.lock not in locks:
                out.append((rel, guard.line,
                            f"global '{guard.name}' is guarded-by "
                            f"'{guard.lock}' but no module-level threading "
                            f"lock of that name exists"))
                continue
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Name) and node.id == guard.name):
                    continue
                fn = self._enclosing_function(node)
                if fn is None:
                    continue  # module top level: import-time, single thread
                is_read = isinstance(node.ctx, ast.Load)
                if guard.reads_ok and is_read:
                    continue
                if guard.lock in _with_locks_on_path(node, fn):
                    continue
                kind = "read" if is_read else "write"
                out.append((
                    rel, node.lineno,
                    f"{kind} of module global '{guard.name}' (guarded-by "
                    f"'{guard.lock}') in {fn.name}() is not inside "
                    f"'with {guard.lock}:'"))
        return out

    @staticmethod
    def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
        cur = getattr(node, "parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = getattr(cur, "parent", None)
        return None

    # -- lock-acquisition graph ---------------------------------------------

    def lock_graph(self) -> dict:
        """``{"locks": {id: ctor}, "edges": [LockSite...],
        "cycles": [[lock ids]], "self_deadlocks": [LockSite...]}``."""
        if self._graph_cache is not None:
            return self._graph_cache
        builder = _GraphBuilder(self)
        self._graph_cache = builder.build()
        return self._graph_cache

    def lock_graph_json(self) -> dict:
        """The --graph artifact: JSON-serializable acquisition graph."""
        g = self.lock_graph()
        edges: Dict[Tuple[str, str], dict] = {}
        for site in g["edges"]:
            rec = edges.setdefault((site.held, site.taken), {
                "held": site.held, "taken": site.taken, "count": 0,
                "example": f"{site.rel}:{site.line}"})
            rec["count"] += 1
        return {
            "locks": [{"id": k, "type": v}
                      for k, v in sorted(g["locks"].items())],
            "edges": sorted(edges.values(),
                            key=lambda e: (e["held"], e["taken"])),
            "cycles": g["cycles"],
            "self_deadlocks": [
                {"lock": s.taken, "site": f"{s.rel}:{s.line}"}
                for s in g["self_deadlocks"]],
        }

    # -- faultpoints ---------------------------------------------------------

    def faultpoint_sites(self) -> List[tuple]:
        """``(rel, line, site_or_pattern, is_pattern)`` for every
        ``faultpoint(...)`` call in non-test files (cached). A Name
        argument resolves through a single local assignment in the
        enclosing function — the dynamic-site idiom
        ``site = f"distributed.{algo}.{phase}.shard"``."""
        if self._faultpoints is not None:
            return self._faultpoints
        out = []
        for rel, ctx in self.contexts.items():
            if _is_test_rel(rel):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node.func)
                if not name or name[-1] != "faultpoint" or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    arg = _local_str_binding(arg)
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    out.append((rel, node.lineno, arg.value, False))
                elif isinstance(arg, ast.JoinedStr):
                    out.append((rel, node.lineno,
                                _joined_to_regex(arg), True))
        self._faultpoints = out
        return out

    def arming_sites(self) -> List[tuple]:
        """``(rel, line, site_or_pattern, is_pattern)`` for every string in
        test files that parses as a valid RAFT_TPU_FAULTS spec, excluding
        strings inside ``@pytest.mark.slow`` functions/classes (those never
        run in tier-1, so they prove nothing)."""
        if self._armings is not None:
            return self._armings
        out = []
        for rel, ctx in self.contexts.items():
            if not _is_test_rel(rel):
                continue
            for node in ast.walk(ctx.tree):
                spec = None
                pattern = False
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    spec = node.value
                elif isinstance(node, ast.JoinedStr):
                    raw = _joined_to_sample(node)
                    if _ARM_RE.match(raw.replace("\x00", "x")):
                        spec = _joined_to_regex(node)
                        pattern = True
                if spec is None:
                    continue
                if not pattern and not _ARM_RE.match(spec):
                    continue
                if _in_slow_marked(node):
                    continue
                site = re.sub(
                    r"=(" + "|".join(_FAULT_KINDS) + r")(:.*)?$", "", spec)
                out.append((rel, node.lineno, site, pattern))
        self._armings = out
        return out

    # -- env knobs -----------------------------------------------------------

    def knob_reads(self) -> List[tuple]:
        """``(rel, line, knob, has_default)`` for every environ read of a
        ``RAFT_TPU_*`` name in non-test files, resolving module-level
        ``*_ENV`` string constants (cached)."""
        if self._knob_cache is not None:
            return self._knob_cache
        out = []
        for rel, ctx in self.contexts.items():
            if _is_test_rel(rel):
                continue
            consts = _env_constants(ctx)
            for node in ast.walk(ctx.tree):
                hit = _environ_read(node, consts)
                if hit:
                    out.append((rel, node.lineno, hit[0], hit[1]))
        self._knob_cache = out
        return out


# ---------------------------------------------------------------------------
# lock graph construction
# ---------------------------------------------------------------------------

class _GraphBuilder:
    """Two passes: per-function acquisition summaries (fixed point over the
    call graph), then a flow walk of every function recording which locks
    are held at each acquisition."""

    def __init__(self, project: ProjectContext) -> None:
        self.p = project
        self.locks: Dict[str, str] = {}
        self.edges: List[LockSite] = []
        self.self_deadlocks: List[LockSite] = []
        # function key -> (ctx, node, owner ClassInfo or None)
        self.functions: Dict[str, tuple] = {}
        self.summaries: Dict[str, Set[str]] = {}
        self._index_functions()

    def _index_functions(self) -> None:
        for info in self.p.classes.values():
            for name, meth in info.methods.items():
                self.functions[f"{info.key}.{name}"] = (
                    self.p.contexts[info.rel], meth, info)
            for attr, ctor in info.locks.items():
                self.locks[f"{info.key}.{attr}"] = ctor
        for rel, ctx in self.p.contexts.items():
            for stmt in ctx.tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.functions[f"{rel}::{stmt.name}"] = (ctx, stmt, None)
            for name, ctor in self.p.module_locks.get(rel, {}).items():
                self.locks[f"{rel}::{name}"] = ctor

    # -- resolution ----------------------------------------------------------

    def _lock_id(self, expr, info) -> Optional[str]:
        """A with-item / acquire receiver -> lock id, when it names a known
        lock (self attr of the owning class, or module-level lock)."""
        if _is_self_attr(expr) and info is not None \
                and expr.attr in info.locks:
            return f"{info.key}.{expr.attr}"
        if isinstance(expr, ast.Name):
            rel = self._cur_rel
            if expr.id in self.p.module_locks.get(rel, {}):
                return f"{rel}::{expr.id}"
        return None

    def _callee_keys(self, call: ast.Call, ctx, info) -> List[str]:
        name = _call_name(call.func)
        if name is None:
            return []
        out = []
        if len(name) == 2 and name[0] == "self" and info is not None:
            key = f"{info.key}.{name[1]}"
            if key in self.functions:
                out.append(key)
        elif len(name) == 3 and name[0] == "self" and info is not None:
            tkey = info.attr_types.get(name[1])
            if tkey:
                key = f"{tkey}.{name[2]}"
                if key in self.functions:
                    out.append(key)
        elif len(name) == 1:
            key = f"{ctx.rel}::{name[0]}"
            if key in self.functions:
                out.append(key)
            else:
                origin = ctx.imports.get(name[0])
                if origin and "." in origin:
                    mod, fn = origin.rsplit(".", 1)
                    rel = self.p.rel_for_module(mod)
                    if rel:
                        key = f"{rel}::{fn}"
                        if key in self.functions:
                            out.append(key)
        elif len(name) == 2:
            origin = ctx.imports.get(name[0])
            if origin:
                rel = self.p.rel_for_module(origin)
                if rel:
                    key = f"{rel}::{name[1]}"
                    if key in self.functions:
                        out.append(key)
        return out

    # -- pass 1: summaries ---------------------------------------------------

    def _direct_acquires(self, fkey: str) -> Tuple[Set[str], List[str]]:
        ctx, node, info = self.functions[fkey]
        self._cur_rel = ctx.rel
        acquired: Set[str] = set()
        callees: List[str] = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lid = self._lock_id(item.context_expr, info)
                    if lid:
                        acquired.add(lid)
            elif isinstance(sub, ast.Call):
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "acquire":
                    lid = self._lock_id(sub.func.value, info)
                    if lid:
                        acquired.add(lid)
                callees.extend(self._callee_keys(sub, ctx, info))
        return acquired, callees

    def _compute_summaries(self) -> None:
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, List[str]] = {}
        for fkey in self.functions:
            d, c = self._direct_acquires(fkey)
            direct[fkey] = d
            calls[fkey] = c
        summaries = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for fkey, callees in calls.items():
                s = summaries[fkey]
                before = len(s)
                for c in callees:
                    s |= summaries.get(c, set())
                if len(s) != before:
                    changed = True
        self.summaries = summaries

    # -- pass 2: edges ---------------------------------------------------------

    def _walk(self, node, held: Tuple[str, ...], ctx, info) -> None:
        if isinstance(node, ast.With):
            taken: List[str] = []
            for item in node.items:
                lid = self._lock_id(item.context_expr, info)
                if lid:
                    self._record(held, lid, ctx, node.lineno)
                    taken.append(lid)
            inner = held + tuple(t for t in taken if t not in held)
            for child in node.body:
                self._walk(child, inner, ctx, info)
            return
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                lid = self._lock_id(node.func.value, info)
                if lid:
                    self._record(held, lid, ctx, node.lineno)
            if held:
                for ckey in self._callee_keys(node, ctx, info):
                    for lid in self.summaries.get(ckey, ()):
                        self._record(held, lid, ctx, node.lineno)
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, ctx, info)

    def _record(self, held: Tuple[str, ...], taken: str, ctx,
                line: int) -> None:
        for h in held:
            if h == taken:
                if not _LOCK_CTORS.get(self.locks.get(taken, "Lock"), False):
                    self.self_deadlocks.append(
                        LockSite(h, taken, ctx.rel, line))
                continue
            self.edges.append(LockSite(h, taken, ctx.rel, line))

    # -- cycles ----------------------------------------------------------------

    @staticmethod
    def _cycles(nodes: Set[str], edges: List[LockSite]) -> List[List[str]]:
        adj: Dict[str, Set[str]] = {n: set() for n in nodes}
        for e in edges:
            adj.setdefault(e.held, set()).add(e.taken)
            adj.setdefault(e.taken, set())
        # Tarjan SCC, iterative
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(adj[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        out.append(sorted(scc))

        for n in sorted(adj):
            if n not in index:
                strongconnect(n)
        return out

    def build(self) -> dict:
        self._compute_summaries()
        for fkey, (ctx, node, info) in self.functions.items():
            self._cur_rel = ctx.rel
            for child in ast.iter_child_nodes(node):
                self._walk(child, (), ctx, info)
        nodes = set(self.locks)
        for e in self.edges:
            nodes.add(e.held)
            nodes.add(e.taken)
        return {
            "locks": dict(self.locks),
            "edges": self.edges,
            "cycles": self._cycles(nodes, self.edges),
            "self_deadlocks": self.self_deadlocks,
        }


# ---------------------------------------------------------------------------
# shared helpers for the contract rules
# ---------------------------------------------------------------------------

def _local_str_binding(name: ast.Name) -> Optional[ast.AST]:
    """Resolve a Name to the value of a single local assignment in the
    enclosing function: ``site = f"..."; faultpoint(site)``. Returns the
    value node when exactly one assignment binds the name, else None."""
    fn = ProjectContext._enclosing_function(name)
    if fn is None:
        return None
    bindings = []
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Name) and t.id == name.id:
                    bindings.append(sub.value)
    return bindings[0] if len(bindings) == 1 else None


def _is_test_rel(rel: str) -> bool:
    parts = rel.split("/")
    return parts[0] == "tests" or parts[-1].startswith("test_") \
        or parts[-1].startswith("conftest")


_HOLE = r"[\w\-]+"


def _joined_to_regex(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(re.escape(str(v.value)))
        else:
            parts.append(_HOLE)
    return "".join(parts)


def _joined_to_sample(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("\x00")
    return "".join(parts)


def sites_compatible(a: str, a_pat: bool, b: str, b_pat: bool) -> bool:
    """Whether faultpoint site ``a`` and arming site ``b`` can denote the
    same runtime site (either may be a regex pattern from an f-string)."""
    if not a_pat and not b_pat:
        return a == b
    if a_pat and not b_pat:
        return re.fullmatch(a, b) is not None
    if b_pat and not a_pat:
        return re.fullmatch(b, a) is not None
    sample_a = a.replace(_HOLE, "x").replace("\\", "")
    sample_b = b.replace(_HOLE, "x").replace("\\", "")
    return (re.fullmatch(a, sample_b) is not None
            or re.fullmatch(b, sample_a) is not None)


def _in_slow_marked(node: ast.AST) -> bool:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            for dec in cur.decorator_list:
                name = _call_name(dec if not isinstance(dec, ast.Call)
                                  else dec.func)
                if name and "slow" in name and "mark" in name:
                    return True
        cur = getattr(cur, "parent", None)
    return False


def _env_constants(ctx) -> Dict[str, str]:
    """Module-level ``X_ENV = "RAFT_TPU_..."`` constants."""
    out: Dict[str, str] = {}
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str) \
                and stmt.value.value.startswith(_KNOB_PREFIX):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _environ_read(node: ast.AST, consts: Dict[str, str]) -> Optional[tuple]:
    """``(knob, has_default)`` when ``node`` reads a RAFT_TPU_* env var:
    ``os.environ.get(K[, d])``, ``os.environ[K]``, ``os.getenv(K[, d])``."""

    def knob_of(arg) -> Optional[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith(_KNOB_PREFIX):
            return arg.value
        if isinstance(arg, ast.Name) and arg.id in consts:
            return consts[arg.id]
        return None

    if isinstance(node, ast.Call):
        name = _call_name(node.func)
        if name and node.args:
            if name[-2:] in (("environ", "get"),) or name[-1] == "getenv":
                k = knob_of(node.args[0])
                if k:
                    return k, len(node.args) > 1
            # per-module default helpers: _env_float(NAME_ENV, 0.5) and kin
            # supply a default for the knob exactly like a 2-arg get
            if name[-1].startswith(("_env_", "default_")):
                k = knob_of(node.args[0])
                if k:
                    return k, True
    elif isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load) \
            and isinstance(node.value, ast.Attribute) \
            and node.value.attr == "environ":
        k = knob_of(node.slice)
        if k:
            return k, False
    return None
