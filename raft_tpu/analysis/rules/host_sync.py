"""jit-host-sync + loop-host-transfer: device→host round-trips.

Two rules share this module because they share the sync-call predicate:

* ``jit-host-sync`` — ``float()``/``int()`` on non-static values,
  ``.item()``/``.tolist()``/``.block_until_ready()``, ``np.asarray``/
  ``np.array``/``jax.device_get`` INSIDE a traced region. Under jit these
  either fail (concretization) or silently pin a host sync into what should
  be a device-resident loop — TPU-KNN's peak-FLOP/s design (PAPER.md)
  depends on the host staying out of the device loop.

* ``loop-host-transfer`` — the same transfers inside ``for``/``while``
  loops of ``@traced`` HOST entry points (build/search drivers). One
  ``device_get`` per iteration serializes the dispatch pipeline. Transfers
  gated behind ``if obs.enabled():`` (or in a helper that no-ops when
  telemetry is off) are exempt — that is exactly the telemetry-off fast
  path the cagra ``_sync`` probe uses.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import (
    HOST_SYNC_ATTRS,
    HOST_SYNC_CALLS,
    enclosing,
    expr_is_traced,
    has_obs_early_return,
    is_traced_decorated,
    iter_functions,
    resolve_call,
    taint_for_function,
    under_obs_gate,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _sync_call_kind(ctx, node: ast.Call) -> str:
    """'' when not a sync; else a short label for the message."""
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr in HOST_SYNC_ATTRS and not node.args:
        return f".{node.func.attr}()"
    resolved = resolve_call(ctx, node.func)
    if resolved in HOST_SYNC_CALLS:
        return resolved
    return ""


@register
class JitHostSyncRule(Rule):
    id = "jit-host-sync"
    severity = "error"
    description = ("host sync (float/int/.item/np.asarray/device_get) "
                   "reachable from a jit/pallas region")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.jit.in_region(node):
                continue
            encl = ctx.jit.enclosing_functions(node)
            if not encl:
                continue
            taint = taint_for_function(ctx, encl[0])

            kind = ""
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in HOST_SYNC_ATTRS and not node.args:
                if expr_is_traced(ctx, node.func.value, taint):
                    kind = f".{node.func.attr}()"
            elif resolve_call(ctx, node.func) in HOST_SYNC_CALLS:
                if any(expr_is_traced(ctx, a, taint) for a in node.args):
                    kind = resolve_call(ctx, node.func)
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int") and len(node.args) == 1 \
                    and expr_is_traced(ctx, node.args[0], taint):
                kind = f"{node.func.id}()"
            if kind:
                yield self.finding(
                    ctx, node,
                    f"{kind} on a traced value inside a jit region forces a "
                    f"device→host sync (or ConcretizationTypeError); keep "
                    f"the value on device or hoist it out of the traced "
                    f"code")


def _syncing_locals(ctx) -> set:
    """Names of module-local functions that transfer to host un-gated
    (one level deep — catches helpers like cagra's ``_sync``)."""
    out = set()
    for fn in iter_functions(ctx.tree):
        if has_obs_early_return(ctx, fn):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _sync_call_kind(ctx, node) \
                    and not under_obs_gate(ctx, node):
                out.add(fn.name)
                break
    return out


@register
class LoopHostTransferRule(Rule):
    id = "loop-host-transfer"
    severity = "warning"
    description = ("device→host transfer inside a loop of a @traced entry "
                   "point (gate it behind obs.enabled() or hoist it)")

    def check(self, ctx):
        syncing = None  # computed lazily: most files have no @traced fns
        for fn in iter_functions(ctx.tree):
            if not is_traced_decorated(fn):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                loop = enclosing(node, (ast.For, ast.While))
                if loop is None or not any(
                        f is fn for f in ctx.jit.enclosing_functions(loop)):
                    continue
                kind = _sync_call_kind(ctx, node)
                if not kind and isinstance(node.func, ast.Name):
                    if syncing is None:
                        syncing = _syncing_locals(ctx)
                    if node.func.id in syncing:
                        kind = f"{node.func.id}() [transfers internally]"
                if kind and not under_obs_gate(ctx, node):
                    yield self.finding(
                        ctx, node,
                        f"{kind} in a loop of @traced `{fn.name}` syncs the "
                        f"device every iteration; hoist it or gate it behind "
                        f"obs.enabled()")
