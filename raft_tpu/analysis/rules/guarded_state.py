"""guarded-state: lock-discipline enforcement for annotated shared fields.

Classes (and modules) declare which lock protects a field with a comment on
the line that first assigns it::

    self._pending = deque()   # guarded-by: _cv
    self._window = 0          # guarded-by: _lock, reads-ok
    _SPANS = deque()          # guarded-by: _LOCK     (module global)

The rule then resolves **every** read and write of that field across the
class's methods (including nested functions and lambdas) and flags any
access not dominated by a ``with self._lock:`` scope. Escape hatches, in
order of preference:

* ``reads-ok`` — unlocked reads tolerated (snapshot-then-release folds like
  the paged store's ``_live_rows``, monotonic counters read for display);
* lock-held-on-entry methods — construction methods, ``*_locked`` names,
  ``# holds: _lock`` declarations on the ``def`` line, and any method whose
  intra-class self-call sites are all themselves dominated (fixed point);
* ``# graftlint: ignore[guarded-state]`` for the truly deliberate.

The heavy lifting (class table, dominance, fixed point) lives in
:mod:`raft_tpu.analysis.projectgraph`; results are computed once per scan
and emitted per file here.
"""

from __future__ import annotations

from raft_tpu.analysis.registry import Rule, register


@register
class GuardedStateRule(Rule):
    id = "guarded-state"
    severity = "error"
    description = ("access to a '# guarded-by:' annotated field outside its "
                   "lock (and not in a lock-held-on-entry method)")

    def check(self, ctx):
        if ctx.project is None:
            return
        for rel, line, message in ctx.project.guarded_state_results():
            if rel == ctx.rel:
                node = _Anchor(line)
                yield self.finding(ctx, node, message)


class _Anchor:
    """Minimal lineno carrier for Rule.finding."""

    def __init__(self, lineno: int) -> None:
        self.lineno = lineno
