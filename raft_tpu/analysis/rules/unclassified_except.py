"""unclassified-except: broad handlers must classify or re-raise.

ISSUE 3 mechanized: the resilience layer only works if failures actually
route through :func:`raft_tpu.resilience.classify` — a broad
``except Exception`` that stamps ``repr(e)`` and moves on erases the
failure class (the round-4 OOM and round-5 hang were both lost exactly
this way). Scope is where the incidents live: ``bench.py`` section guards
and the ``raft_tpu/distributed/`` paths. A broad handler there must call
``classify(...)`` (directly or via a helper whose name ends in
``classify`` / the bench ``section_error`` wrapper) or contain a
``raise``; anything else is a finding. Deliberate holdouts (the parent
orchestrator, which must stay off the raft_tpu import lock) are baselined
with a justification via ``scripts/analysis_baseline.py``.

ISSUE 7 widened the scope to the other incident homes: the resilience
package (the degraded-mode dispatch gate lives there) and the crash-safe
write path (``core/serialize.py`` / ``core/fsio.py``) — a broad handler
that eats a snapshot-corruption error would erase exactly the failure
class the v2 container exists to classify.

ISSUE 8 added ``raft_tpu/serving/`` — the query-queue dispatch guard is
the layer's whole failure story (DEADLINE verdicts, OOM batch halving),
so an unclassified except there would break serving's one contract.

ISSUE 10 added ``raft_tpu/obs/`` — the SLO/shadow/report plane degrades
on failure by DESIGN (a broken signal source becomes ``state=unknown``,
a failed shadow search marks the estimate stale), and every one of those
degradations is only diagnosable if the kind survives classification.
The handful of pre-existing jax-presence probes in registry/tracing carry
inline justifications.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import resolve_call
from raft_tpu.analysis.rules.exceptions import _is_broad

#: handler-body call names that count as classification
_CLASSIFY_NAMES = {"classify", "section_error"}


def _in_scope(rel: str) -> bool:
    parts = rel.split("/")
    dirs = parts[:-1]
    if parts[-1] == "bench.py" or "distributed" in dirs or \
            "resilience" in dirs or "serving" in dirs or "obs" in dirs:
        return True
    return "core" in dirs and parts[-1] in ("serialize.py", "fsio.py")


def _handles(handler: ast.ExceptHandler, ctx) -> bool:
    """Does this handler classify the exception or re-raise?"""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = resolve_call(ctx, node.func).rsplit(".", 1)[-1]
                if name in _CLASSIFY_NAMES:
                    return True
    return False


@register
class UnclassifiedExceptRule(Rule):
    id = "unclassified-except"
    severity = "error"
    description = ("broad except in bench.py / distributed paths that "
                   "neither calls resilience.classify() nor re-raises")

    def check(self, ctx):
        if not _in_scope(ctx.rel):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _is_broad(handler):
                    continue
                if _handles(handler, ctx):
                    continue
                yield self.finding(
                    ctx, handler,
                    "broad except drops the failure class — route it "
                    "through resilience.classify() (or re-raise) so "
                    "OOM/TRANSIENT/DEADLINE recovery can see it")
