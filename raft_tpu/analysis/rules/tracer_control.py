"""tracer-branch: Python control flow on traced values in jit regions.

``if jnp.any(mask):`` inside a jitted function either raises
ConcretizationTypeError or — worse, via weak typing on some paths — forces a
blocking device→host sync at trace time. The fix is ``lax.cond`` /
``jnp.where`` / ``lax.while_loop``. The rule flags ``if``/``while``/
``assert`` tests that contain a jax/jnp/lax call, and explicit ``bool(...)``
on non-static expressions, but only INSIDE traced regions — host code
branching on a materialized result is fine.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import (
    is_array_ns,
    is_metadata_call,
    taint_for_function,
)


_STATIC_PROBES = {"len", "isinstance", "issubclass", "getattr", "hasattr",
                  "callable", "type", "id"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}


def _test_is_traced(ctx, node: ast.AST, taint) -> bool:
    """Does this if/while/assert test read a traced value? Recursive so that
    statically-decidable subtrees can be pruned: ``x is None`` probes pytree
    STRUCTURE (the canonical optional-argument idiom under jit), and
    ``len()``/``isinstance()``/``.shape`` read metadata, not data."""
    if isinstance(node, ast.Compare) and \
            all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return False
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id in _STATIC_PROBES:
            return False
        if is_array_ns(ctx, node.func) and not is_metadata_call(ctx, node):
            return True
    if isinstance(node, ast.Name):
        return node.id in taint
    return any(_test_is_traced(ctx, child, taint)
               for child in ast.iter_child_nodes(node))


@register
class TracerBranchRule(Rule):
    id = "tracer-branch"
    severity = "error"
    description = ("Python if/while/assert on a traced value inside a "
                   "jit/pallas region (use lax.cond/jnp.where)")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            else:
                continue
            if not ctx.jit.in_region(node):
                continue
            encl = ctx.jit.enclosing_functions(node)
            taint = taint_for_function(ctx, encl[0]) if encl else frozenset()
            if _test_is_traced(ctx, test, taint):
                kind = type(node).__name__.lower()
                yield self.finding(
                    ctx, node,
                    f"Python `{kind}` on a traced expression inside a jit "
                    f"region — concretizes the tracer; use lax.cond/"
                    f"lax.while_loop/jnp.where instead")
