"""unused-import: dead imports.

Dead imports in this tree are not just noise — an ``import jax`` at the top
of a stdlib-only module (bench.py's parent process, ``bench/progress.py``)
would re-introduce exactly the import-lock wedge the round-5 postmortem
engineered away. The rule is pyflakes-shaped but deliberately narrower:

* ``__init__.py`` is skipped wholesale (re-export surface);
* a line carrying ``# noqa`` is skipped (side-effect imports, e.g. rule
  registration);
* names referenced only inside QUOTED annotations (``TYPE_CHECKING``
  blocks) count as used — annotation strings are parsed and mined;
* ``__all__`` string entries count as used.
"""

from __future__ import annotations

import ast
from typing import Dict, Set

from raft_tpu.analysis.registry import Rule, register


def _imported_bindings(tree: ast.Module) -> Dict[str, ast.AST]:
    """Local binding name -> the import node that created it."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = node
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = node
    return out


def _annotation_names(tree: ast.Module) -> Set[str]:
    """Names inside string annotations (``"Iterator[Finding]"``)."""
    out: Set[str] = set()
    anns = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            anns.append(node.annotation)
        elif isinstance(node, ast.arg) and node.annotation is not None:
            anns.append(node.annotation)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node.returns is not None:
            anns.append(node.returns)
    for ann in anns:
        for sub in ast.walk(ann):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                try:
                    parsed = ast.parse(sub.value, mode="eval")
                except SyntaxError:
                    continue
                out.update(n.id for n in ast.walk(parsed)
                           if isinstance(n, ast.Name))
    return out


@register
class UnusedImportRule(Rule):
    id = "unused-import"
    severity = "warning"
    description = "imported name never referenced (non-__init__ modules)"

    def check(self, ctx):
        if ctx.rel.endswith("__init__.py"):
            return
        bindings = _imported_bindings(ctx.tree)
        if not bindings:
            return
        used: Set[str] = {
            n.id for n in ast.walk(ctx.tree) if isinstance(n, ast.Name)}
        used |= _annotation_names(ctx.tree)
        for node in ast.walk(ctx.tree):  # __all__ re-export strings
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        used.add(sub.value)
        for name, node in sorted(bindings.items()):
            if name in used or name.startswith("_"):
                continue
            if "# noqa" in ctx.snippet(node.lineno):
                continue
            yield self.finding(
                ctx, node,
                f"`{name}` is imported but never used — dead imports cost "
                f"cold-start and can re-introduce import-lock wedges in "
                f"stdlib-only paths")
