"""banned-api: nondeterminism sources in kernel/ops modules.

``raft_tpu/ops`` and ``raft_tpu/native`` are the numerical core — the same
inputs must produce the same dispatch graph on every call (compile-cache
hits, reproducible benches, and the determinism contract distributed
replay depends on). Wall-clock reads, stdlib ``random`` and ``datetime``
have no business there; timing belongs in ``@traced``/``obs`` at the entry
points, randomness must flow through explicit ``jax.random`` keys
(``raft_tpu/random``), and ``np.random`` hides global mutable state.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import resolve_call

_SCOPED_DIRS = {"ops", "native"}

_BANNED_PREFIXES = ("time.", "random.", "numpy.random.")
_BANNED_EXACT = {
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}


@register
class BannedApiRule(Rule):
    id = "banned-api"
    severity = "error"
    description = ("time/random/datetime/np.random calls in kernel & ops "
                   "modules (determinism contract)")

    def check(self, ctx):
        parts = ctx.rel.split("/")[:-1]
        if not _SCOPED_DIRS.intersection(parts) and \
                "kernels" not in ctx.rel.split("/")[-1]:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolve_call(ctx, node.func)
            if not resolved:
                continue
            if resolved in _BANNED_EXACT or \
                    resolved.startswith(_BANNED_PREFIXES):
                yield self.finding(
                    ctx, node,
                    f"`{resolved}` in a kernel/ops module breaks the "
                    f"determinism contract — use jax.random keys / move "
                    f"timing to @traced entry points")
