"""recompile-hazard: patterns that silently recompile or hash tracers.

Three concrete, decidable shapes:

* ``jax.jit(...)`` constructed inside a loop — each iteration builds a fresh
  wrapper with an empty cache, so every call retraces+recompiles. Hoist the
  jit to module level (or cache the wrapper).
* An f-string formatting a traced value inside a jit region — formats the
  abstract tracer (useless text) and, in error paths, tends to grow into
  ``.item()`` syncs. Shape/dtype interpolation is fine and exempt.
* A ``static_argnums``/``static_argnames`` parameter rebound via
  ``jnp.asarray(p)`` in the jitted body — an array-valued static arg hashes
  by value, i.e. one compile cache entry per distinct payload.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import (
    enclosing,
    expr_is_traced,
    resolve_call,
    taint_for_function,
)

_ASARRAY = {"jax.numpy.asarray", "jax.numpy.array", "numpy.asarray",
            "numpy.array"}


@register
class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    severity = "warning"
    description = ("jit-in-loop, f-string on a tracer, or array-valued "
                   "static argument (per-call recompiles)")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    resolve_call(ctx, node.func) == "jax.jit" and \
                    enclosing(node, (ast.For, ast.While)) is not None:
                yield self.finding(
                    ctx, node,
                    "jax.jit(...) constructed inside a loop starts with an "
                    "empty compile cache every iteration — hoist it")

            elif isinstance(node, ast.JoinedStr) and ctx.jit.in_region(node):
                encl = ctx.jit.enclosing_functions(node)
                taint = (taint_for_function(ctx, encl[0]) if encl
                         else frozenset())
                for val in node.values:
                    if isinstance(val, ast.FormattedValue) and \
                            expr_is_traced(ctx, val.value, taint):
                        yield self.finding(
                            ctx, node,
                            "f-string formats a traced value inside a jit "
                            "region — it renders the abstract tracer; "
                            "interpolate shapes/dtypes or move it to host "
                            "code")
                        break

            elif isinstance(node, ast.Assign) and ctx.jit.in_region(node):
                static = ctx.jit.static_params(node)
                if not static or len(node.targets) != 1:
                    continue
                tgt, val = node.targets[0], node.value
                if isinstance(tgt, ast.Name) and tgt.id in static and \
                        isinstance(val, ast.Call) and \
                        resolve_call(ctx, val.func) in _ASARRAY and \
                        val.args and isinstance(val.args[0], ast.Name) and \
                        val.args[0].id == tgt.id:
                    yield self.finding(
                        ctx, node,
                        f"static argument `{tgt.id}` is rebound as an array "
                        f"in the jitted body — array-valued static args "
                        f"recompile per distinct value; pass it traced or "
                        f"keep it a scalar/tuple")
