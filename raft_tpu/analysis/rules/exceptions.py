"""swallowed-exception: bare ``except:`` and silent broad handlers.

A bare ``except:`` catches ``KeyboardInterrupt``/``SystemExit`` — on the
tunneled TPU runtime that turns a Ctrl-C or watchdog kill into a hang
(round-5's wedge failure mode). A broad ``except Exception: pass`` around
device calls is subtler: XLA errors (OOM, donation, cross-host) vanish and
the caller proceeds on garbage. Narrow handlers that swallow deliberately
(``except AttributeError: pass`` on frozen-dataclass cache writes) are the
documented idiom here and stay legal.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import resolve_call

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(n, ast.Name) and n.id in _BROAD for n in names)


def _swallows(handler: ast.ExceptHandler) -> bool:
    return all(
        isinstance(s, (ast.Pass, ast.Continue)) or
        (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
        for s in handler.body)


def _try_touches_device(ctx, try_node: ast.Try) -> bool:
    for stmt in try_node.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                resolved = resolve_call(ctx, node.func)
                if resolved.startswith(("jax.", "jax.numpy.", "jax.lax.")):
                    return True
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "block_until_ready":
                    return True
    return False


@register
class SwallowedExceptionRule(Rule):
    id = "swallowed-exception"
    severity = "error"
    description = ("bare except, or broad except that silently swallows "
                   "(fatal around device calls)")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if handler.type is None:
                    yield self.finding(
                        ctx, handler,
                        "bare `except:` also catches KeyboardInterrupt/"
                        "SystemExit — name the exception(s)")
                elif _is_broad(handler) and _swallows(handler):
                    where = (" around device calls"
                             if _try_touches_device(ctx, node) else "")
                    yield self.finding(
                        ctx, handler,
                        f"broad except silently swallows{where} — narrow "
                        f"the type or at least log it",
                        severity="error" if where else "warning")
