"""span-name: the span-tree contract, mechanically enforced.

Two halves of the round-8 observability contract:

* **Naming.** Every literal span name — ``@traced("…")`` decorators and
  ``record_span("…")`` calls — in library code (``raft_tpu/``, ``bench.py``)
  must follow the ``module::phase`` convention (lower-case dotted segments
  either side of one ``::``). The convention is what makes trace trees,
  fleet merges and the bench comparator line up across rounds: a span that
  renames itself or free-forms its name silently forks its metric series.
  Tests and scripts are out of scope (they open scratch spans).

* **Export channel.** In bench scope (``bench.py``, ``raft_tpu/bench/``),
  direct calls to ``export_jsonl`` / ``export_chrome_trace`` bypass
  ``bench/progress.py``'s crash-safe channel (fsync'd, salvage-aware —
  the round-5 lesson) and get flagged; ``progress.py`` itself is exempt.
  Route through ``progress.export_metrics`` / ``progress.write_artifact``.
"""

from __future__ import annotations

import ast
import re

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.jit_regions import dotted_name

_NAME_RE = re.compile(
    r"^[a-z0-9_]+(\.[a-z0-9_]+)*::[a-z0-9_]+(\.[a-z0-9_]+)*$")

_EXPORT_CALLS = {"export_jsonl", "export_chrome_trace"}


def _literal_span_names(tree):
    """Yield (node, name) for every literal span name in the module: the
    first argument of record_span(...) calls and of traced(...) decorators."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                dotted_name(node.func).rsplit(".", 1)[-1] == "record_span" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            yield node, node.args[0].value
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) and \
                        dotted_name(deco.func).rsplit(".", 1)[-1] == "traced" \
                        and deco.args and \
                        isinstance(deco.args[0], ast.Constant) and \
                        isinstance(deco.args[0].value, str):
                    yield deco, deco.args[0].value


@register
class SpanNameRule(Rule):
    id = "span-name"
    severity = "error"
    description = ("span names must follow module::phase; bench telemetry "
                   "exports must route through bench/progress.py")

    def check(self, ctx):
        parts = ctx.rel.split("/")
        in_library = parts[0] == "raft_tpu" or ctx.rel == "bench.py"
        in_bench = ctx.rel == "bench.py" or "bench" in parts[:-1]

        in_serving = len(parts) > 1 and parts[0] == "raft_tpu" \
            and parts[1] == "serving"
        if in_library:
            for node, name in _literal_span_names(ctx.tree):
                if not _NAME_RE.match(name):
                    yield self.finding(
                        ctx, node,
                        f"span name {name!r} breaks the module::phase "
                        f"convention (lower-case dotted segments around one "
                        f"'::') — renamed spans fork their metric series "
                        f"across rounds")
                elif in_serving and not name.startswith(
                        ("serving::", "capacity::")):
                    # the serving layer's span family is its SLO dashboard:
                    # a span filed under another module's prefix silently
                    # drops out of every serving-latency query. Round 18
                    # adds the capacity:: family — the multi-tenant
                    # admission/tiering plane lives in serving/ but its
                    # spans (capacity::admit/demote/promote/search) are
                    # their own dashboard
                    yield self.finding(
                        ctx, node,
                        f"span name {name!r} in raft_tpu/serving/ must use "
                        f"the serving:: or capacity:: prefix "
                        f"(serving::phase naming)")

        if in_bench and not ctx.rel.endswith("/progress.py"):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                tail = dotted_name(node.func).rsplit(".", 1)[-1]
                if tail in _EXPORT_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"direct {tail}() in bench code bypasses the "
                        f"crash-safe bench/progress.py channel — use "
                        f"progress.export_metrics / progress.write_artifact "
                        f"(fsync'd, salvageable) instead")
