"""obs-coverage: telemetry is a prerequisite, mechanically enforced.

The ROADMAP open item says bench-affecting hot paths must keep their
``raft_tpu.obs`` spans. This rule turns that from review-time lore into a
tier-1 failure: every PUBLIC build/search/fit-family entry point in
``neighbors/``, ``cluster/``, ``distributed/`` and ``serving/`` must either
carry the ``@traced("…")`` decorator or open an ``obs.record_span`` itself.
Removing a span from an instrumented entry point — or adding a new entry
point without one — is a NEW finding and fails the run (the baseline never
absorbs it, because the identity line is the ``def`` itself).

The serving layer's public surface is method-shaped
(``PagedListStore.upsert`` / ``.delete`` / ``.compact``,
``QueryQueue.submit``), so inside ``serving/`` the rule also walks
class bodies.

ISSUE 10 extended the scope to the observability plane's own entry points
(``obs/slo.py`` / ``obs/report.py``): the SLO engine and status report are
what the autotuner and the driver consume, so their public surface
(``sample`` / ``evaluate`` / ``collect`` / ``render``, module functions
and methods alike) must be span-covered too — the watcher is watched.
ISSUE 11 extends it again to the dispatch cost model and compile ledger
(``obs/costmodel.py`` / ``obs/compile.py``): ``estimate`` /
``check_admission`` / ``predict_index_bytes`` / ``summary`` are the
item-4 admission controller's inputs and must be as observable as what
they observe (``trace_event`` stays exempt — it runs at jit trace time).
ISSUE 12 adds the roofline plane (``obs/roofline.py``):
``estimate_flops`` / ``utilization`` / ``summary`` feed the per-config
efficiency record the autotuner frontier fit consumes, so they are
span-covered too (``note_dispatch`` stays exempt — it sits on the hot
path behind the callers' own ``obs.enabled()`` gate).
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import calls_record_span, is_traced_decorated

_SCOPED_DIRS = {"neighbors", "cluster", "distributed", "serving"}
#: ``promote``/``demote`` (round 18): the capacity plane's tier moves are
#: serving-path policy actions — an unobserved demotion is an invisible
#: recall hit, so they are entry points like search/upsert
_ENTRY_NAMES = {"build", "search", "fit", "fit_predict", "extend", "knn",
                "upsert", "delete", "submit", "compact", "promote",
                "demote"}
_ENTRY_PREFIXES = ("build_", "search_", "fit_")

#: the obs plane's own public entry points (ISSUE 10; ISSUE 11 extended
#: the scope to the cost model and compile ledger): scoped per-file so
#: helper modules (aggregate, tracing) keep their non-span shape.
#: ``trace_event`` is deliberately NOT an entry name — it runs at jit
#: TRACE time, where opening a span would record tracing as work.
#: ISSUE 16 adds the flight recorder (``obs/flight.py``): ``sample`` /
#: ``render`` / ``extract_frontier`` are the timeline and the frontier the
#: autotuner consumes (``maybe_sample`` stays exempt — it is the serving
#: loop's one-branch pump and opens the span only when it samples).
_OBS_FILES = {"slo.py", "report.py", "costmodel.py", "compile.py",
              "roofline.py", "flight.py"}
_OBS_ENTRY_NAMES = {"sample", "evaluate", "collect", "render",
                    "estimate", "check_admission", "predict_index_bytes",
                    "summary", "estimate_flops", "utilization",
                    "extract_frontier"}


def _is_entry_name(name: str) -> bool:
    if name.startswith("_"):
        return False
    return name in _ENTRY_NAMES or name.startswith(_ENTRY_PREFIXES)


def _is_obs_entry_name(name: str) -> bool:
    return not name.startswith("_") and name in _OBS_ENTRY_NAMES


@register
class ObsCoverageRule(Rule):
    id = "obs-coverage"
    severity = "error"
    description = ("public build/search/fit entry points in neighbors/"
                   "cluster/distributed must be @traced or record_span")

    def check(self, ctx):
        parts = ctx.rel.split("/")
        dirs = parts[:-1]
        obs_scoped = "obs" in dirs and parts[-1] in _OBS_FILES
        if not (_SCOPED_DIRS.intersection(dirs) or obs_scoped):
            return
        is_entry = _is_obs_entry_name if obs_scoped else _is_entry_name
        nodes = list(ctx.tree.body)  # module level: the public surface
        if "serving" in dirs or obs_scoped:  # ...plus method-shaped entries
            for node in ctx.tree.body:
                if isinstance(node, ast.ClassDef):
                    nodes.extend(n for n in node.body if isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)))
        for node in nodes:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not is_entry(node.name):
                continue
            if is_traced_decorated(node) or calls_record_span(node):
                continue
            yield self.finding(
                ctx, node,
                f"public entry point `{node.name}` has no telemetry span — "
                f"decorate it @traced(\"…\") or open obs.record_span "
                f"(ROADMAP: telemetry is a prerequisite)")
