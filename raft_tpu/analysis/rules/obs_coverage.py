"""obs-coverage: telemetry is a prerequisite, mechanically enforced.

The ROADMAP open item says bench-affecting hot paths must keep their
``raft_tpu.obs`` spans. This rule turns that from review-time lore into a
tier-1 failure: every PUBLIC build/search/fit-family entry point in
``neighbors/``, ``cluster/``, ``distributed/`` and ``serving/`` must either
carry the ``@traced("…")`` decorator or open an ``obs.record_span`` itself.
Removing a span from an instrumented entry point — or adding a new entry
point without one — is a NEW finding and fails the run (the baseline never
absorbs it, because the identity line is the ``def`` itself).

The serving layer's public surface is method-shaped
(``PagedListStore.upsert`` / ``.delete`` / ``.compact``,
``QueryQueue.submit``), so inside ``serving/`` the rule also walks
class bodies.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import calls_record_span, is_traced_decorated

_SCOPED_DIRS = {"neighbors", "cluster", "distributed", "serving"}
_ENTRY_NAMES = {"build", "search", "fit", "fit_predict", "extend", "knn",
                "upsert", "delete", "submit", "compact"}
_ENTRY_PREFIXES = ("build_", "search_", "fit_")


def _is_entry_name(name: str) -> bool:
    if name.startswith("_"):
        return False
    return name in _ENTRY_NAMES or name.startswith(_ENTRY_PREFIXES)


@register
class ObsCoverageRule(Rule):
    id = "obs-coverage"
    severity = "error"
    description = ("public build/search/fit entry points in neighbors/"
                   "cluster/distributed must be @traced or record_span")

    def check(self, ctx):
        parts = ctx.rel.split("/")[:-1]  # directories only
        if not _SCOPED_DIRS.intersection(parts):
            return
        nodes = list(ctx.tree.body)  # module level: the public surface
        if "serving" in parts:  # ...plus serving's method-shaped entries
            for node in ctx.tree.body:
                if isinstance(node, ast.ClassDef):
                    nodes.extend(n for n in node.body if isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)))
        for node in nodes:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_entry_name(node.name):
                continue
            if is_traced_decorated(node) or calls_record_span(node):
                continue
            yield self.finding(
                ctx, node,
                f"public entry point `{node.name}` has no telemetry span — "
                f"decorate it @traced(\"…\") or open obs.record_span "
                f"(ROADMAP: telemetry is a prerequisite)")
