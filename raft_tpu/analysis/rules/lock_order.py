"""lock-order: deadlock cycles in the repo-wide lock-acquisition graph.

The projectgraph builder records an edge ``A -> B`` whenever lock ``B`` is
taken while ``A`` is held — directly (a nested ``with``), or through a call
the intra-repo call graph can resolve (self-methods, attribute receivers
typed by ``self.x = ClassName(...)`` in ``__init__``, module functions via
the import table) using per-function may-acquire summaries. Two findings:

* **cycle** — a strongly connected component of two or more locks: some
  interleaving of the involved threads can deadlock. Emitted once per
  cycle, anchored at the lexicographically first edge site in the cycle.
* **self-deadlock** — a non-reentrant ``threading.Lock`` acquired while
  already held on the same path (``RLock``/``Condition``/semaphores are
  reentrant-by-design and exempt).

Dump the graph for inspection::

    python -m raft_tpu.analysis --rule lock-order --graph out.json raft_tpu
"""

from __future__ import annotations

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules.guarded_state import _Anchor


@register
class LockOrderRule(Rule):
    id = "lock-order"
    severity = "error"
    description = ("cycle in the repo-wide lock-acquisition graph, or a "
                   "non-reentrant lock re-acquired while held")

    def check(self, ctx):
        if ctx.project is None:
            return
        graph = ctx.project.lock_graph()
        for cycle in graph["cycles"]:
            members = set(cycle)
            sites = sorted(
                (s for s in graph["edges"]
                 if s.held in members and s.taken in members),
                key=lambda s: (s.rel, s.line))
            if not sites or sites[0].rel != ctx.rel:
                continue
            yield self.finding(
                ctx, _Anchor(sites[0].line),
                "lock-acquisition cycle: " + " -> ".join(cycle + [cycle[0]])
                + " (some thread interleaving can deadlock; break the cycle "
                  "or impose a global order)")
        for site in graph["self_deadlocks"]:
            if site.rel != ctx.rel:
                continue
            yield self.finding(
                ctx, _Anchor(site.line),
                f"non-reentrant lock {site.taken} acquired while already "
                f"held on this path (threading.Lock self-deadlocks; use an "
                f"RLock or restructure)")
