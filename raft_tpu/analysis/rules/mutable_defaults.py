"""mutable-default: mutable default argument values.

The classic: ``def f(x, acc=[])`` evaluates the default ONCE at def time, so
state leaks across calls. In this codebase the sharper version of the bug is
a default ``CagraParams()``-style dataclass with array fields — mutate it in
one call and every later call sees the mutation. The rule flags literal
list/dict/set displays and ``list()``/``dict()``/``set()``/``bytearray()``
constructor defaults; immutable sentinels (None, tuples, frozen params
objects) pass.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register

_MUTABLE_CTORS = {"list", "dict", "set", "bytearray"}
_MUTABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                  ast.SetComp)


@register
class MutableDefaultRule(Rule):
    id = "mutable-default"
    severity = "error"
    description = "mutable default argument (shared across calls)"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                bad = isinstance(d, _MUTABLE_NODES) or (
                    isinstance(d, ast.Call) and
                    isinstance(d.func, ast.Name) and
                    d.func.id in _MUTABLE_CTORS)
                if bad:
                    name = getattr(node, "name", "<lambda>")
                    yield self.finding(
                        ctx, d,
                        f"mutable default in `{name}` is evaluated once and "
                        f"shared across calls — use None and create it in "
                        f"the body")
