"""faultpoint-contract: two-way drift check between faultpoints and tests.

Every ``resilience.faultpoint("site")`` in library code is a recovery
contract — the round-7 standing gate says some tier-1 test must arm it via
``RAFT_TPU_FAULTS`` and assert the degraded/classified behavior. This rule
mechanizes both directions of that contract over the scan set:

* **unarmed faultpoint** — a library faultpoint site that no collected
  arming string can name. Emitted only when the scan includes at least one
  test file (a library-only scan proves nothing about arming).
* **unknown arming site** — an arming string in tests naming a site no
  library faultpoint declares (stale after a rename; the test silently
  stops testing anything). Emitted only when the scan includes at least
  one library file.

Arming strings are collected from **all** string literals in test files
that parse as a valid spec (``site=kind[:count[:arg]]`` with a known
kind) — that includes ``arm_faults()`` arguments, ``monkeypatch.setenv``
values, and ``pytest.mark.parametrize`` tables — excluding anything inside
``@pytest.mark.slow`` (not tier-1, proves nothing). F-string sites on
either side (e.g. the distributed per-algo sites) match as patterns.

Deliberately synthetic sites in unit tests of the fault machinery itself
carry ``# graftlint: ignore[faultpoint-contract]``.
"""

from __future__ import annotations

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.projectgraph import _is_test_rel, sites_compatible
from raft_tpu.analysis.rules.guarded_state import _Anchor


@register
class FaultpointContractRule(Rule):
    id = "faultpoint-contract"
    severity = "error"
    description = ("library faultpoint no tier-1 test arms, or an arming "
                   "string naming a nonexistent faultpoint site")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return
        faults = project.faultpoint_sites()
        arms = project.arming_sites()
        have_tests = any(_is_test_rel(r) for r in project.contexts)
        have_lib = any(not _is_test_rel(r) for r in project.contexts)
        if have_tests and not _is_test_rel(ctx.rel):
            for rel, line, site, pat in faults:
                if rel != ctx.rel:
                    continue
                if any(sites_compatible(site, pat, a_site, a_pat)
                       for _, _, a_site, a_pat in arms):
                    continue
                yield self.finding(
                    ctx, _Anchor(line),
                    f"faultpoint '{site}' is armed by no tier-1 test "
                    f"(add a RAFT_TPU_FAULTS recovery test or baseline "
                    f"with a justification)")
        if have_lib and _is_test_rel(ctx.rel):
            for rel, line, site, pat in arms:
                if rel != ctx.rel:
                    continue
                if any(sites_compatible(f_site, f_pat, site, pat)
                       for _, _, f_site, f_pat in faults):
                    continue
                yield self.finding(
                    ctx, _Anchor(line),
                    f"arming string targets '{site}' but no library "
                    f"faultpoint declares that site (stale name? the test "
                    f"arms nothing)")
