"""Shared predicates for the rule catalog."""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from raft_tpu.analysis.jit_regions import dotted_name

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: calls that move device data to the host (or block on it)
HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
HOST_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "jax.device_get",
}


def resolve_call(ctx, node: ast.AST) -> str:
    """Canonical dotted name of a call target, with the module's import
    aliases folded in: ``np.asarray`` -> ``numpy.asarray``, a bare
    ``device_get`` imported from jax -> ``jax.device_get``."""
    name = dotted_name(node)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    origin = ctx.imports.get(head)
    if origin:
        return f"{origin}.{rest}" if rest else origin
    return name


def is_array_ns(ctx, node: ast.AST) -> bool:
    """Does this call target live under jax / jax.numpy / jax.lax?"""
    resolved = resolve_call(ctx, node)
    return resolved.startswith(("jax.numpy.", "jax.lax.", "jax.")) and \
        not resolved.startswith("jax.profiler.")


def enclosing(node: ast.AST, kinds) -> Optional[ast.AST]:
    """Nearest ancestor of one of ``kinds`` (walker sets .parent links)."""
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def has_ancestor(node: ast.AST, target: ast.AST) -> bool:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if cur is target:
            return True
        cur = getattr(cur, "parent", None)
    return False


def is_traced_decorated(fn) -> bool:
    """Does ``fn`` carry the ``@traced("...")`` telemetry decorator?"""
    for deco in fn.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        if dotted_name(target).rsplit(".", 1)[-1] == "traced":
            return True
    return False


def calls_record_span(fn) -> bool:
    """Does the function body record a span itself — ``obs.record_span``
    or its explicit-lineage twin ``obs.tracing.manual_span`` (the
    cross-thread request-lifecycle path, which records the same ring node
    without the contextvar wrapper)?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                dotted_name(node.func).rsplit(".", 1)[-1] in (
                    "record_span", "manual_span"):
            return True
    return False


def is_obs_enabled_test(ctx, test: ast.AST) -> bool:
    """Is this expression an ``obs.enabled()`` (or alias) call?"""
    return isinstance(test, ast.Call) and \
        resolve_call(ctx, test.func).endswith("obs.enabled")


def under_obs_gate(ctx, node: ast.AST) -> bool:
    """Is ``node`` inside an ``if obs.enabled():`` block?"""
    cur = getattr(node, "parent", None)
    child = node
    while cur is not None:
        if isinstance(cur, ast.If) and is_obs_enabled_test(ctx, cur.test):
            # must be in the THEN branch (the else branch is the off path)
            if any(has_ancestor(child, s) or child is s for s in cur.body):
                return True
        child = cur
        cur = getattr(cur, "parent", None)
    return False


def has_obs_early_return(ctx, fn) -> bool:
    """Does ``fn`` start with ``if not obs.enabled(): return``?"""
    for stmt in fn.body:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring
        if isinstance(stmt, ast.If) and \
                isinstance(stmt.test, ast.UnaryOp) and \
                isinstance(stmt.test.op, ast.Not) and \
                is_obs_enabled_test(ctx, stmt.test.operand) and \
                any(isinstance(s, ast.Return) for s in stmt.body):
            return True
        return False
    return False


def is_static_expr(node: ast.AST, static_names=frozenset()) -> bool:
    """Conservatively: does this expression involve only host-static values
    (constants, shapes/dtypes/ndim, len(), and known-static parameters)?
    Shape/dtype access anywhere marks the whole expression static — the
    dominant idiom is ``int(x.shape[0] * grow)`` which is host arithmetic."""
    subs = list(ast.walk(node))
    if any(isinstance(s, ast.Attribute) and
           s.attr in ("shape", "ndim", "dtype", "size", "itemsize",
                      "inf", "nan", "pi", "e")  # namespace constants
           for s in subs):
        return True
    for sub in subs:
        if isinstance(sub, ast.Call) and not (
                isinstance(sub.func, ast.Name) and sub.func.id == "len"):
            return False
    names = [n.id for n in subs if isinstance(n, ast.Name)]
    return all(n in static_names or n == "len" for n in names)


def iter_functions(tree: ast.Module) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES):
            yield node


#: array-namespace calls that return host metadata, not tracers
METADATA_FNS = {
    "issubdtype", "isdtype", "result_type", "promote_types", "can_cast",
    "finfo", "iinfo", "dtype", "zeros_like_shape",
}


def is_metadata_call(ctx, call: ast.Call) -> bool:
    tail = resolve_call(ctx, call.func).rsplit(".", 1)[-1]
    return tail in METADATA_FNS


def _is_module_constant(name: str) -> bool:
    return name.isupper()  # ALL_CAPS module constant convention


def taint_for_function(ctx, fn) -> frozenset:
    """Names in ``fn`` plausibly bound to TRACED values: non-static
    parameters of direct jit roots, results of jax/jnp/lax calls, and
    anything assigned from those (two propagation passes over assignments,
    for-targets and comprehension targets — no fixpoint, by design: this is
    a linter, and two passes cover the code shapes this tree actually has).
    Shape/dtype-derived bindings stay untainted (static under jit)."""
    cache = getattr(ctx, "_taint_cache", None)
    if cache is None:
        cache = ctx._taint_cache = {}
    if fn in cache:
        return cache[fn]

    taint = set()
    if ctx.jit.is_direct_root(fn):
        static = ctx.jit.static_params(fn)
        a = fn.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        taint.update(p for p in params if p not in static and p != "self")

    def value_traced(expr) -> bool:
        return expr_is_traced(ctx, expr, taint)

    def target_names(tgt):
        return [n.id for n in ast.walk(tgt) if isinstance(n, ast.Name)]

    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if is_static_expr(node.value):
                    continue  # shape/dtype-derived: static under jit
                if value_traced(node.value):
                    for t in node.targets:
                        taint.update(target_names(t))
            elif isinstance(node, ast.AugAssign):
                if value_traced(node.value) and isinstance(node.target, ast.Name):
                    taint.add(node.target.id)
            elif isinstance(node, ast.For):
                if value_traced(node.iter):
                    taint.update(target_names(node.target))
            elif isinstance(node, ast.comprehension):
                if value_traced(node.iter):
                    taint.update(target_names(node.target))
            elif isinstance(node, ast.withitem):
                if node.optional_vars is not None and \
                        value_traced(node.context_expr):
                    taint.update(target_names(node.optional_vars))

    result = frozenset(taint)
    cache[fn] = result
    return result


_STATIC_SUBTREE_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}
_STATIC_SUBTREE_FNS = {"len", "isinstance", "issubclass", "getattr",
                       "hasattr", "callable", "type"}


def expr_is_traced(ctx, node: ast.AST, taint) -> bool:
    """Could this expression hold a tracer? True when it references a
    tainted name or calls into the array namespace (inside jit, every
    jnp/lax call returns a tracer — except metadata probes). Static
    subtrees are pruned: ``x.shape[0]`` and ``len(x)`` are host ints even
    when ``x`` is traced."""
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_SUBTREE_ATTRS:
        return False
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and \
                node.func.id in _STATIC_SUBTREE_FNS:
            return False
        if is_array_ns(ctx, node.func):
            return not is_metadata_call(ctx, node)
    if isinstance(node, ast.Name):
        return node.id in taint
    return any(expr_is_traced(ctx, child, taint)
               for child in ast.iter_child_nodes(node))
