"""Rule catalog — importing this package registers every rule.

Catalog (id — what it catches):

* ``tracer-branch``       — Python ``if``/``while``/``assert``/``bool()`` on
  traced values inside jit/pallas regions (ConcretizationTypeError or a
  silent host sync at trace time)
* ``jit-host-sync``       — ``float()``/``int()``/``.item()``/``.tolist()``/
  ``np.asarray``/``jax.device_get`` reachable from a jit region
* ``loop-host-transfer``  — device→host transfers inside loops in ``@traced``
  host entry points (the per-iteration sync that ate round-5's bench window)
* ``obs-coverage``        — public build/search/fit entry points in
  neighbors/cluster/distributed must be ``@traced`` or open a
  ``record_span`` (ROADMAP: telemetry is a prerequisite)
* ``recompile-hazard``    — ``jax.jit`` constructed inside a loop, f-strings
  formatting tracers, static params rebound as arrays
* ``banned-api``          — wall-clock / stdlib-random / datetime reads in
  kernel & ops modules (determinism contract)
* ``swallowed-exception`` — bare ``except:`` and broad except-pass around
  device calls
* ``mutable-default``     — mutable default argument values
* ``bench-io``            — bench results writes bypassing the crash-safe
  ``bench/progress.py`` channel
* ``span-name``           — literal span names breaking the ``module::phase``
  convention, and bench-scope ``export_jsonl``/trace exports bypassing
  ``bench/progress.py``'s fsync'd channel
* ``unclassified-except`` — broad except in bench.py / distributed paths
  that neither routes through ``resilience.classify()`` nor re-raises
  (the failure class must survive for recovery to see it)
* ``unused-import``       — dead imports (non-``__init__`` modules)

Concurrency-discipline family (round 19, interprocedural — these consult
the repo-wide :mod:`~raft_tpu.analysis.projectgraph` built per scan):

* ``guarded-state``       — access to a ``# guarded-by:`` annotated field
  outside its lock and outside any lock-held-on-entry method
* ``lock-order``          — cycles in the repo-wide lock-acquisition graph,
  and non-reentrant self-acquisition
* ``faultpoint-contract`` — library faultpoints no tier-1 test arms, and
  arming strings naming nonexistent sites
* ``env-knob``            — ``RAFT_TPU_*`` knobs missing from the README
  knob table or defaulted in more than one module
"""

from raft_tpu.analysis.rules import (  # noqa: F401  (registration side effect)
    banned_api,
    bench_io,
    env_knob,
    exceptions,
    faultpoint_contract,
    guarded_state,
    host_sync,
    imports,
    lock_order,
    mutable_defaults,
    obs_coverage,
    recompile,
    span_name,
    tracer_control,
    unclassified_except,
)
