"""bench-io: bench results writes must go through a crash-safe channel.

Round 5's lesson (BENCH_r05.json rc=124, no output): any bench result that
lives only in process memory — or in a file written without flush+fsync —
is lost the moment the watchdog kills the run. ``bench/progress.py`` is the
crash-safe channel for results (append, flush, fsync per record,
salvageable by ``scripts/bench_salvage.py``) and
``core/fsio.atomic_write`` for whole-file artifacts (ISSUE 7). Direct
write-mode ``open()`` / ``np.save*`` / ``.tofile()`` / ``Path.write_text``
in bench code bypasses both guarantees, so it gets flagged; writes INSIDE
a ``with atomic_write(...)`` block, ``progress.py`` itself and read-mode
opens are exempt. Legitimate non-results writes (dataset caches,
user-pointed ``--output``) are baselined with justifications rather than
silently allowed.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import resolve_call

_WRITE_MODES = set("wax")
_NP_WRITERS = {"numpy.save", "numpy.savez", "numpy.savez_compressed",
               "numpy.savetxt"}
_PATH_WRITERS = {"write_text", "write_bytes"}
_ARRAY_WRITERS = {"tofile"}
#: context managers that ARE the crash-safe channel — everything written
#: inside their ``with`` block is sanctioned
_SAFE_CTX = {"atomic_write"}


def _sanctioned_nodes(tree) -> set:
    """ids of Call nodes that write THROUGH an atomic stream: inside a
    ``with atomic_write(...) as f`` block, only calls that take ``f`` as
    receiver or argument (``f.write(...)``, ``arr.tofile(f)``,
    ``np.save(f, ...)``) are sanctioned — an unrelated ``open(b, "wb")``
    nested in the same block stays flagged."""
    out: set = set()
    for w in ast.walk(tree):
        if not isinstance(w, (ast.With, ast.AsyncWith)):
            continue
        aliases = set()
        for item in w.items:
            c = item.context_expr
            if isinstance(c, ast.Call) and isinstance(
                    c.func, (ast.Name, ast.Attribute)):
                name = (c.func.id if isinstance(c.func, ast.Name)
                        else c.func.attr)
                if name in _SAFE_CTX and isinstance(
                        item.optional_vars, ast.Name):
                    aliases.add(item.optional_vars.id)
        if not aliases:
            continue
        for node in ast.walk(w):
            if not isinstance(node, ast.Call):
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            uses = any(isinstance(a, ast.Name) and a.id in aliases
                       for a in args)
            if isinstance(node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Name) and \
                    node.func.value.id in aliases:
                uses = True
            if uses:
                out.add(id(node))
    return out


def _open_mode(node: ast.Call) -> str:
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) and \
            isinstance(node.args[1].value, str):
        return node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


@register
class BenchIoRule(Rule):
    id = "bench-io"
    severity = "warning"
    description = ("bench code writing files directly instead of through "
                   "the crash-safe bench/progress.py channel")

    def check(self, ctx):
        in_scope = ctx.rel == "bench.py" or (
            "bench" in ctx.rel.split("/")[:-1])
        if not in_scope or ctx.rel.endswith("/progress.py"):
            return
        sanctioned = _sanctioned_nodes(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in sanctioned:
                continue
            label = ""
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                if _WRITE_MODES.intersection(_open_mode(node)):
                    label = f"open(…, {_open_mode(node)!r})"
            elif resolve_call(ctx, node.func) in _NP_WRITERS:
                label = resolve_call(ctx, node.func)
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _PATH_WRITERS | _ARRAY_WRITERS:
                label = f".{node.func.attr}()"
            if label:
                yield self.finding(
                    ctx, node,
                    f"direct {label} in bench code — route results through "
                    f"bench/progress.py or core/fsio.atomic_write (fsync'd, "
                    f"crash-safe) so a killed run keeps its artifacts")
