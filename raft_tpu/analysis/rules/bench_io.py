"""bench-io: bench results writes must go through ``bench/progress.py``.

Round 5's lesson (BENCH_r05.json rc=124, no output): any bench result that
lives only in process memory — or in a file written without flush+fsync —
is lost the moment the watchdog kills the run. ``bench/progress.py`` is the
crash-safe channel (append, flush, fsync per record, salvageable by
``scripts/bench_salvage.py``). Direct write-mode ``open()`` / ``np.save*`` /
``Path.write_text`` in bench code bypasses that guarantee, so it gets
flagged; ``progress.py`` itself and read-mode opens are exempt. Legitimate
non-results writes (dataset caches, user-pointed ``--output``) are
baselined with justifications rather than silently allowed.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules._common import resolve_call

_WRITE_MODES = set("wax")
_NP_WRITERS = {"numpy.save", "numpy.savez", "numpy.savez_compressed",
               "numpy.savetxt"}
_PATH_WRITERS = {"write_text", "write_bytes"}


def _open_mode(node: ast.Call) -> str:
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) and \
            isinstance(node.args[1].value, str):
        return node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


@register
class BenchIoRule(Rule):
    id = "bench-io"
    severity = "warning"
    description = ("bench code writing files directly instead of through "
                   "the crash-safe bench/progress.py channel")

    def check(self, ctx):
        in_scope = ctx.rel == "bench.py" or (
            "bench" in ctx.rel.split("/")[:-1])
        if not in_scope or ctx.rel.endswith("/progress.py"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            label = ""
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                if _WRITE_MODES.intersection(_open_mode(node)):
                    label = f"open(…, {_open_mode(node)!r})"
            elif resolve_call(ctx, node.func) in _NP_WRITERS:
                label = resolve_call(ctx, node.func)
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _PATH_WRITERS:
                label = f".{node.func.attr}()"
            if label:
                yield self.finding(
                    ctx, node,
                    f"direct {label} in bench code — route results through "
                    f"bench/progress.py (fsync'd, salvageable) so a killed "
                    f"run keeps its checkpoints")
