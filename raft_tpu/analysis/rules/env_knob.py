"""env-knob: drift check for ``RAFT_TPU_*`` environment knobs.

Every knob the library reads is public API — it must be documented, and it
must have exactly one place that supplies its default (two modules each
defaulting the same knob is how the round-13 PROCESS_INDEX split happened:
the values agree today and silently diverge on the next edit). Two
findings:

* **undocumented** — a knob read somewhere in the scan never appears in a
  README.md table row (a line starting with ``|``) at the scan root.
  Skipped entirely when the root has no README.md (fixture trees).
* **doubly-defaulted** — more than one read site passes an explicit
  default for the same knob (2-arg ``os.environ.get`` / ``os.getenv`` or a
  ``_env_*``/``default_*`` helper call). Reads without a default (probe
  patterns, save/restore) don't count; the fix is to route every consumer
  through the one registered default.

Knob reads are collected from library files only — tests *set* knobs, they
don't define them.
"""

from __future__ import annotations

from raft_tpu.analysis.registry import Rule, register
from raft_tpu.analysis.rules.guarded_state import _Anchor


@register
class EnvKnobRule(Rule):
    id = "env-knob"
    severity = "error"
    description = ("RAFT_TPU_* env knob missing from the README knob table "
                   "or defaulted in more than one read site")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return
        reads = project.knob_reads()
        documented = self._documented(project)
        # knob -> {module rel: first defaulted line}; drift = two MODULES
        # each defaulting the same knob (repeat reads through one module's
        # helper are that module's business)
        defaulted: dict = {}
        for rel, line, knob, has_default in reads:
            if has_default:
                mods = defaulted.setdefault(knob, {})
                mods.setdefault(rel, line)
        emitted = set()
        for rel, line, knob, has_default in reads:
            if rel != ctx.rel:
                continue
            if documented is not None and knob not in documented:
                first = min((r, ln) for r, ln, k, _ in reads if k == knob)
                if (rel, line) == first:
                    yield self.finding(
                        ctx, _Anchor(line),
                        f"env knob '{knob}' is read here but appears in no "
                        f"README knob-table row (document it or drop it)")
            mods = defaulted.get(knob, {})
            if len(mods) > 1 and rel in mods and (knob, rel) not in emitted \
                    and line == mods[rel]:
                emitted.add((knob, rel))
                others = ", ".join(f"{r}:{ln}" for r, ln in sorted(mods.items())
                                   if r != rel)
                yield self.finding(
                    ctx, _Anchor(line),
                    f"env knob '{knob}' is defaulted in more than one "
                    f"module (also at {others}); route all consumers "
                    f"through one registered default")

    @staticmethod
    def _documented(project):
        """Knob names in README table rows, or None when no README exists
        (fixture scans check only double-defaulting)."""
        readme = project.root / "README.md"
        if not readme.exists():
            return None
        names = set()
        for line in readme.read_text(encoding="utf-8",
                                     errors="replace").splitlines():
            if line.lstrip().startswith("|"):
                for tok in line.replace("`", " ").replace("|", " ").split():
                    if tok.startswith("RAFT_TPU_"):
                        names.add(tok.strip(".,:;()"))
        return names
