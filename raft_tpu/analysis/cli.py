"""``python -m raft_tpu.analysis`` — the graftlint command line.

Examples::

    python -m raft_tpu.analysis raft_tpu tests bench.py scripts
    python -m raft_tpu.analysis --json raft_tpu/neighbors
    python -m raft_tpu.analysis --list-rules
    python -m raft_tpu.analysis --select mutable-default,banned-api raft_tpu
    python -m raft_tpu.analysis --rule guarded-state --graph out.json raft_tpu

``--rule`` is an alias for ``--select``; ``--graph`` dumps the repo-wide
lock-acquisition graph (locks, held->taken edges with example sites,
cycles, self-deadlocks) as JSON alongside whatever rules run.

Exit codes: 0 = clean (no findings outside the baseline), 1 = new findings,
2 = bad invocation. ``--write-baseline`` exists for
``scripts/analysis_baseline.py``; prefer that script (it preserves
justifications and prints what changed) over calling the flag directly.

The analysis package itself is pure stdlib (ast + argparse + json), but
``import raft_tpu.analysis`` necessarily executes ``raft_tpu/__init__``,
which pulls jax — so a `-m` run pays the package cold-start once. All the
analysis work after that is AST-only and runs on CPU-only hosts.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from pathlib import Path

from raft_tpu.analysis.baseline import Baseline
from raft_tpu.analysis.findings import format_json, format_text
from raft_tpu.analysis.registry import all_rules, resolve
from raft_tpu.analysis.walker import analyze_paths, collect_files

DEFAULT_BASELINE = "analysis_baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raft_tpu.analysis",
        description="graftlint: JAX/TPU-aware static analysis",
    )
    p.add_argument("paths", nargs="*", default=[],
                   help="files / directories to analyze")
    p.add_argument("--root", default=".",
                   help="repo root for relative paths + default baseline "
                        "(default: cwd)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <root>/{DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, grandfathered or not")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline to cover current findings "
                        "(use scripts/analysis_baseline.py)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit JSON instead of text")
    p.add_argument("--select", "--rule", default=None, dest="select",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--graph", default=None, metavar="PATH",
                   help="dump the repo-wide lock-acquisition graph (locks, "
                        "held->taken edges with example sites, cycles) as "
                        "JSON to PATH")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id:22s} {rule.severity:8s} {rule.description}")
        return 0

    if not args.paths:
        print("graftlint: no paths given (try: raft_tpu tests bench.py "
              "scripts)", file=sys.stderr)
        return 2

    rules = None
    if args.select:
        if args.write_baseline:
            # from_findings covers the current findings EXACTLY — a partial
            # rule selection would silently delete every other grandfathered
            # entry (and its handwritten justification) from the file.
            print("graftlint: --write-baseline with --select would drop all "
                  "entries for unselected rules; run without --select "
                  "(prefer scripts/analysis_baseline.py)", file=sys.stderr)
            return 2
        try:
            rules = resolve(s.strip() for s in args.select.split(",") if s.strip())
        except KeyError as e:
            print(f"graftlint: {e.args[0]}", file=sys.stderr)
            return 2

    root = Path(args.root).resolve()
    baseline_path = Path(args.baseline) if args.baseline else root / DEFAULT_BASELINE

    if args.graph:
        import json

        from raft_tpu.analysis.projectgraph import ProjectContext
        from raft_tpu.analysis.walker import parse_module

        try:
            files = collect_files(args.paths, root=root)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 2
        contexts = []
        for path in files:
            try:
                contexts.append(parse_module(path, root))
            except SyntaxError:
                pass  # the lint pass below reports it as parse-error
        project = ProjectContext(contexts, root)
        payload = project.lock_graph_json()
        Path(args.graph).write_text(
            json.dumps(payload, indent=2) + "\n", encoding="utf-8")
        print(f"graftlint: lock graph ({len(payload['locks'])} locks, "
              f"{len(payload['edges'])} edges, "
              f"{len(payload['cycles'])} cycle(s)) -> {args.graph}",
              file=sys.stderr)

    t0 = time.monotonic()
    try:
        findings = analyze_paths(args.paths, rules=rules, root=root)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.write_baseline:
        previous = Baseline.load(baseline_path)
        # A rewrite covers the scanned findings EXACTLY, so scanning a
        # subset of the tree would silently delete every entry (and its
        # handwritten justification) for files outside that subset. Refuse
        # when an existing entry's file is real but was not scanned —
        # entries for deleted files still prune legitimately.
        scanned = {os.path.relpath(f, root).replace(os.sep, "/")
                   for f in collect_files(args.paths, root=root)}
        orphaned = sorted({e.get("path", "") for e in previous.entries
                           if e.get("path") not in scanned
                           and (root / e.get("path", "")).exists()})
        if orphaned:
            print("graftlint: --write-baseline over a partial scan would "
                  "drop existing entries for unscanned files "
                  f"({', '.join(orphaned)}); scan the full set or use "
                  "scripts/analysis_baseline.py", file=sys.stderr)
            return 2
        Baseline.from_findings(findings, previous=previous).save(baseline_path)
        print(f"graftlint: baseline rewritten with {len(findings)} finding(s)"
              f" -> {baseline_path}", file=sys.stderr)
        return 0

    absorbed = 0
    if not args.no_baseline:
        findings, absorbed = Baseline.load(baseline_path).filter(findings)

    out = (format_json(findings, absorbed) if args.as_json
           else format_text(findings, absorbed))
    print(out)
    elapsed = time.monotonic() - t0
    print(f"graftlint: analyzed in {elapsed:.2f}s", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
