"""Jit/pallas region resolver: which functions in a module run under a tracer.

Host-sync and tracer-control-flow rules are only meaningful INSIDE a traced
region — ``int(x)`` on the host is free, ``int(x)`` under ``jax.jit`` is a
blocking device round-trip (or a ConcretizationTypeError). This module
answers "is this ast node inside traced code?" from a single file's AST:

1. **Direct roots** — functions decorated ``@jax.jit`` /
   ``@functools.partial(jax.jit, ...)``, rebound via ``f = jax.jit(f)``,
   passed to ``jax.jit(f)`` inline, handed to ``pl.pallas_call`` as the
   kernel, or passed to a tracing transform (``vmap``/``grad``/``lax.scan``/
   ``fori_loop``/``while_loop``/``cond``/``switch``/``map``/``remat``).
2. **Call-graph closure** — a helper called (by bare name, same module) from
   a traced function is itself traced at runtime; reachability is a BFS over
   local call edges. Cross-module calls are out of scope by design: the
   walker runs per-file and the registry stays import-light.
3. **Lexical nesting** — a function defined inside a traced function
   (scan bodies, pallas kernels-in-closures) is traced.

``static_params(fn)`` exposes the ``static_argnames``/``static_argnums`` of a
direct root so rules can exempt genuinely-static parameters (``int(k)`` on a
static ``k`` is host arithmetic, not a sync).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# tracing transforms whose function-valued args run under a tracer
_TRANSFORMS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "scan", "fori_loop", "while_loop", "cond", "switch", "map",
    "associative_scan", "custom_vjp", "custom_jvp", "pallas_call",
    "shard_map",
}

# Attribute bases a transform may hang off. Generic names (`map`, `cond`,
# `scan`, …) collide with ordinary host APIs — `executor.map(worker, items)`
# must NOT mark `worker` as traced — so an attribute call only counts when
# its base object is one of the jax homes. Bare names stay trusted: they are
# overwhelmingly `from jax.lax import scan`-style imports in this codebase.
_TRANSFORM_BASES = {"jax", "lax", "jax.lax", "pl", "pltpu", "pallas",
                    "jax.experimental.pallas"}


def _is_transform_call(func: ast.AST) -> bool:
    dotted = dotted_name(func)
    if not dotted:
        return False
    base, _, head = dotted.rpartition(".")
    if head not in _TRANSFORMS:
        return False
    return not base or base in _TRANSFORM_BASES


def dotted_name(node: ast.AST) -> str:
    """``jax.numpy.sum`` for a Name/Attribute chain, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this expression denote ``jax.jit`` (possibly bare ``jit``)?"""
    name = dotted_name(node)
    return name in ("jit", "jax.jit")


def _partial_of_jit(call: ast.Call) -> bool:
    """``functools.partial(jax.jit, ...)`` / ``partial(jit, ...)``."""
    if dotted_name(call.func) not in ("partial", "functools.partial"):
        return False
    return bool(call.args) and _is_jit_expr(call.args[0])


def _static_from_call(call: ast.Call, fn: Optional[ast.AST]) -> Set[str]:
    """Static parameter names out of a jit(...) or partial(jax.jit, ...) call."""
    names: Set[str] = set()
    argnums: List[int] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
        elif kw.arg == "static_argnums":
            v = kw.value
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    argnums.append(e.value)
    if argnums and isinstance(fn, _FUNC_NODES):
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for i in argnums:
            if 0 <= i < len(params):
                names.add(params[i])
    return names


class JitRegions:
    """Per-module traced-region index. Expects parent links on the tree
    (``walker`` sets ``node.parent``)."""

    def __init__(self, tree: ast.Module):
        self._funcs: Dict[str, List[ast.AST]] = {}
        self._static: Dict[ast.AST, Set[str]] = {}
        roots: Set[ast.AST] = set()

        for node in ast.walk(tree):
            if isinstance(node, _FUNC_NODES):
                self._funcs.setdefault(node.name, []).append(node)

        def mark_name(name_node: ast.AST, static: Set[str]) -> None:
            if isinstance(name_node, ast.Name):
                for fn in self._funcs.get(name_node.id, ()):
                    roots.add(fn)
                    if static:
                        self._static.setdefault(fn, set()).update(static)

        for node in ast.walk(tree):
            if isinstance(node, _FUNC_NODES):
                for deco in node.decorator_list:
                    if _is_jit_expr(deco):
                        roots.add(node)
                    elif isinstance(deco, ast.Call) and (
                            _is_jit_expr(deco.func) or _partial_of_jit(deco)):
                        roots.add(node)
                        self._static.setdefault(node, set()).update(
                            _static_from_call(deco, node))
            elif isinstance(node, ast.Call):
                if _is_jit_expr(node.func) and node.args:
                    fn = (self._funcs.get(node.args[0].id, [None])[0]
                          if isinstance(node.args[0], ast.Name) else None)
                    mark_name(node.args[0], _static_from_call(node, fn))
                elif _is_transform_call(node.func):
                    for arg in node.args:
                        mark_name(arg, set())

        # call-graph closure over bare-name calls, then lexical nesting is
        # resolved lazily in in_region() by climbing parents
        self._roots: Set[ast.AST] = set(roots)
        self._region: Set[ast.AST] = set(roots)
        frontier = list(roots)
        while frontier:
            fn = frontier.pop()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    for callee in self._funcs.get(node.func.id, ()):
                        if callee not in self._region:
                            self._region.add(callee)
                            frontier.append(callee)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of function defs containing ``node``."""
        chain = []
        cur = getattr(node, "parent", None)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                chain.append(cur)
            cur = getattr(cur, "parent", None)
        return chain

    def in_region(self, node: ast.AST) -> bool:
        """Is ``node`` (any ast node) inside traced code?"""
        if isinstance(node, _FUNC_NODES) and node in self._region:
            return True
        return any(fn in self._region for fn in self.enclosing_functions(node))

    def is_direct_root(self, fn: ast.AST) -> bool:
        """Was ``fn`` itself handed to jit/pallas (vs merely reachable)?
        Direct roots are the one place parameter tracedness is knowable:
        every non-static parameter arrives as a tracer."""
        return fn in self._roots

    def static_params(self, node: ast.AST) -> FrozenSet[str]:
        """Union of static param names over the enclosing traced roots."""
        out: Set[str] = set()
        chain = self.enclosing_functions(node)
        if isinstance(node, _FUNC_NODES):
            chain = [node] + chain
        for fn in chain:
            out.update(self._static.get(fn, ()))
        return frozenset(out)
