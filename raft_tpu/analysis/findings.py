"""Finding record + report formats (text and JSON).

The text format is the one the ISSUE pins — ``file:line · rule-id · severity
· message`` — grep-friendly and clickable in most terminals. The JSON format
is a list of objects (one per finding) for tooling.

A finding's identity for baseline purposes is deliberately line-number-FREE:
``(rule, path, snippet)`` where snippet is the stripped source line. Editing
code above a grandfathered finding must not un-baseline it; moving or
duplicating the offending line itself should.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


class Severity:
    """Severity ladder. Only the spelling matters (baseline + output)."""

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    _ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

    @classmethod
    def rank(cls, sev: str) -> int:
        return cls._ORDER.get(sev, 99)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    rule: str
    severity: str
    message: str
    snippet: str = field(default="", compare=False)

    def key(self) -> tuple:
        """Baseline identity: stable under edits elsewhere in the file."""
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "snippet": self.snippet,
        }


def sort_findings(findings) -> list:
    return sorted(
        findings,
        key=lambda f: (f.path, f.line, Severity.rank(f.severity), f.rule),
    )


def format_text(findings, baselined: int = 0) -> str:
    """``file:line · rule-id · severity · message`` lines + a summary tail."""
    lines = [
        f"{f.path}:{f.line} · {f.rule} · {f.severity} · {f.message}"
        for f in sort_findings(findings)
    ]
    n = len(lines)
    summary = f"graftlint: {n} new finding{'s' if n != 1 else ''}"
    if baselined:
        summary += f" ({baselined} baselined, suppressed)"
    lines.append(summary)
    return "\n".join(lines)


def format_json(findings, baselined: int = 0) -> str:
    fs = sort_findings(findings)
    return json.dumps(
        {
            "findings": [f.to_dict() for f in fs],
            "new": len(fs),
            "baselined": baselined,
        },
        indent=2,
    )
