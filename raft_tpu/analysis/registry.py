"""Pluggable rule registry.

A rule is a class with ``id``/``severity``/``description`` and a
``check(ctx)`` generator yielding :class:`~raft_tpu.analysis.findings.Finding`
objects for one parsed module. Decorating it with :func:`register` puts an
instance in the process-wide catalog; the walker runs every registered rule
over every collected file (rules scope themselves by path — see e.g.
``banned-api``, which only looks at kernel/ops modules).

Third parties (scripts, tests) can register extra rules before calling
``analyze_paths`` — the registry is deliberately just a dict.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, TYPE_CHECKING

if TYPE_CHECKING:  # import cycle: walker imports registry
    from raft_tpu.analysis.findings import Finding
    from raft_tpu.analysis.walker import ModuleContext


class Rule:
    """Base class; subclasses set the three class attrs and yield findings."""

    id: str = ""
    severity: str = "warning"
    description: str = ""

    def check(self, ctx: "ModuleContext") -> "Iterator[Finding]":
        raise NotImplementedError

    def finding(self, ctx: "ModuleContext", node, message: str,
                severity: str = "") -> "Finding":
        """Build a Finding anchored at ``node`` (any ast node with lineno)."""
        from raft_tpu.analysis.findings import Finding

        line = getattr(node, "lineno", 0)
        return Finding(
            path=ctx.rel,
            line=line,
            rule=self.id,
            severity=severity or self.severity,
            message=message,
            snippet=ctx.snippet(line),
        )


_RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the catalog (id must be set
    and unique — a duplicate id is a programming error, fail loudly)."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    """Every registered rule, id-sorted (ensures rule modules are loaded)."""
    import raft_tpu.analysis.rules  # noqa: F401  (registration side effect)

    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(rule_id: str) -> Rule:
    import raft_tpu.analysis.rules  # noqa: F401

    return _RULES[rule_id]


def resolve(selection: Iterable[str]) -> List[Rule]:
    """Map ids to rules, unknown id -> KeyError with the catalog listed."""
    rules = []
    for rid in selection:
        try:
            rules.append(get_rule(rid))
        except KeyError:
            known = ", ".join(sorted(_RULES))
            raise KeyError(f"unknown rule {rid!r}; known: {known}") from None
    return rules
