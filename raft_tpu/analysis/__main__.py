"""Entry point for ``python -m raft_tpu.analysis``."""

import sys

from raft_tpu.analysis.cli import main

sys.exit(main())
