"""Hybrid dense+sparse retrieval as ONE fused BQ contraction.

The production hybrid-search shape is "dense ANN + sparse term scores,
merged": run a vector index and an inverted text index side by side, then
reconcile two candidate lists with RRF or a learned mixer. That shape
pays two scans, two top-k selects, and a host-side merge — and the merge
sees only each side's survivors, so a row that is mediocre on both axes
but strong combined is lost before reconciliation.

This module folds the sparse side INTO the dense scan instead. Sparse
rows (CSR/COO over a term vocabulary, :mod:`raft_tpu.sparse`) are
sign-hashed into a fixed ``sparse_dim``-wide block — feature hashing
(Weinberger et al.): term ``t`` lands in column ``h(t) mod sparse_dim``
with sign ``±1`` from a second hash bit, so ``⟨proj(a), proj(b)⟩`` is an
unbiased estimator of the sparse inner product ``⟨a, b⟩`` with collision
variance ``O(‖a‖²‖b‖²/sparse_dim)``. The fused row is the concat

    ``[ dense | β · proj(sparse) ]``

and one IVF-BQ index over it under ``inner_product`` scores

    ``⟨q_d, x_d⟩ + β² · ⟨proj(q_s), proj(x_s)⟩``

— the dense score plus the β²-weighted sparse term score, ranked in ONE
wider strip contraction feeding the same ``merge_strip_candidates``
select the dense-only scan uses. No second index, no candidate-list
reconciliation, and every first-class property of the BQ family rides
along for free: predicate push-down (``filter=`` masks fused rows in
VMEM before ranking), selectivity-aware widening, the paged mutable
store (:func:`to_store` → ``serving.search`` with fused queries), and
the distributed path — ``distributed.ivf_bq`` over the fused rows
shards/merges/health-gates (``probe_shards``) the concat unchanged,
because after :func:`build` a hybrid index IS an ``IvfBqIndex``.

``sparse_dim`` defaults to ``RAFT_TPU_HYBRID_SPARSE_DIM`` (256): at BQ's
1 bit/dim the sparse block adds 32 bytes/row. ``β`` tunes the
dense↔sparse balance and is baked into the stored rows, so changing it
is a rebuild (document-side weights are β-scaled at encode time).

Persistence: a :class:`HybridIndex` is not in the v2 snapshot registry —
serialize the wrapped ``.index`` (a plain ``IvfBqIndex``) and rewrap
with the same ``(dense_dim, sparse_dim, beta, seed)``; the projection is
stateless given those.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.resources import Resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors import ivf_bq
from raft_tpu.sparse.types import COO, CSR

HYBRID_SPARSE_DIM_ENV = "RAFT_TPU_HYBRID_SPARSE_DIM"


def default_hybrid_sparse_dim() -> int:
    """Width of the hashed sparse block (``RAFT_TPU_HYBRID_SPARSE_DIM``,
    default 256 — lane-width aligned; collision variance on the sparse
    score falls as 1/width, row cost grows as width·bits/8 bytes)."""
    return int(os.environ.get(HYBRID_SPARSE_DIM_ENV, "256"))


def _hash_cols_signs(term_ids, sparse_dim: int, seed: int):
    """Deterministic term → (column, sign) feature hash.

    One 32-bit finalizer-style integer mix (xorshift-multiply rounds) per
    term id; the low bits pick the column, bit 31 the sign. Stateless —
    the same (term, sparse_dim, seed) maps identically at build time,
    query time, and on every shard."""
    h = jnp.asarray(term_ids, jnp.uint32) ^ jnp.uint32(seed * 0x9E3779B9 + 1)
    h ^= h >> 16
    h *= jnp.uint32(0x7FEB352D)
    h ^= h >> 15
    h *= jnp.uint32(0x846CA68B)
    h ^= h >> 16
    col = (h % jnp.uint32(sparse_dim)).astype(jnp.int32)
    sign = jnp.where((h >> 31) > 0, 1.0, -1.0).astype(jnp.float32)
    return col, sign


def project_sparse(sp, sparse_dim: Optional[int] = None,
                   seed: int = 0) -> jax.Array:
    """Sign-hash sparse rows into a dense ``(n, sparse_dim)`` fp32 block.

    ``sp`` is a :class:`~raft_tpu.sparse.types.CSR` or
    :class:`~raft_tpu.sparse.types.COO` (padding contributes zero, per the
    sparse tier's contract) or an already-dense ``(n, vocab)`` array.
    Colliding terms scatter-ADD with their hash signs — the unbiasedness
    argument needs the signed sum, not overwrite."""
    dim = default_hybrid_sparse_dim() if sparse_dim is None else int(sparse_dim)
    if dim <= 0:
        raise ValueError(f"sparse_dim must be positive, got {dim}")
    if isinstance(sp, CSR):
        rows, cols, vals = sp.row_ids(), sp.indices, sp.data
        n = sp.shape[0]
        valid = jnp.arange(sp.capacity) < sp.nnz()
    elif isinstance(sp, COO):
        rows, cols, vals = sp.rows, sp.cols, sp.vals
        n = sp.shape[0]
        valid = sp.valid
    else:
        dense = jnp.asarray(sp, jnp.float32)
        if dense.ndim != 2:
            raise ValueError(f"expected 2-D sparse rows, got {dense.shape}")
        n, vocab = dense.shape
        col, sign = _hash_cols_signs(jnp.arange(vocab), dim, seed)
        proj = jnp.zeros((vocab, dim), jnp.float32)
        proj = proj.at[jnp.arange(vocab), col].set(sign)
        return dense @ proj
    col, sign = _hash_cols_signs(jnp.clip(cols, 0), dim, seed)
    v = jnp.where(valid, jnp.asarray(vals, jnp.float32) * sign, 0.0)
    r = jnp.clip(jnp.asarray(rows, jnp.int32), 0, n - 1)
    out = jnp.zeros((n, dim), jnp.float32)
    return out.at[r, col].add(v)


@dataclass(frozen=True)
class HybridIndex:
    """An :class:`~raft_tpu.neighbors.ivf_bq.IvfBqIndex` over fused
    ``[dense | β·proj(sparse)]`` rows, plus the projection parameters a
    query needs to land in the same space."""

    index: ivf_bq.IvfBqIndex
    dense_dim: int
    sparse_dim: int
    beta: float
    seed: int = 0

    @property
    def n_lists(self) -> int:
        return self.index.n_lists

    @property
    def dim(self) -> int:
        return self.index.dim


@traced("hybrid::build")
def build(
    dense,
    sparse,
    params: Optional[ivf_bq.IvfBqParams] = None,
    beta: float = 1.0,
    sparse_dim: Optional[int] = None,
    seed: int = 0,
    res: Optional[Resources] = None,
) -> HybridIndex:
    """Build the fused index: hash-project ``sparse``, β-scale, concat
    onto ``dense``, and IVF-BQ-build the result under ``inner_product``
    (the only metric where the concat's score decomposes into
    dense + β²·sparse — a caller-side L2 request is rejected rather than
    silently rescored)."""
    dense = jnp.asarray(dense, jnp.float32)
    if dense.ndim != 2:
        raise ValueError(f"dense rows must be (n, d), got {dense.shape}")
    sdim = default_hybrid_sparse_dim() if sparse_dim is None else int(sparse_dim)
    params = params or ivf_bq.IvfBqParams(metric="inner_product")
    if params.metric != "inner_product":
        raise ValueError(
            "hybrid fusion requires metric='inner_product' (the concat "
            f"score only decomposes there), got {params.metric!r}")
    proj = project_sparse(sparse, sdim, seed)
    if proj.shape[0] != dense.shape[0]:
        raise ValueError(
            f"dense has {dense.shape[0]} rows, sparse {proj.shape[0]}")
    fused = jnp.concatenate([dense, float(beta) * proj], axis=1)
    if obs.enabled():
        obs.add("hybrid.build.rows", int(fused.shape[0]))
    with obs.record_span("hybrid::build",
                         attrs={"rows": int(fused.shape[0]),
                                "dense_dim": int(dense.shape[1]),
                                "sparse_dim": sdim, "beta": float(beta)}):
        inner = ivf_bq.build(fused, params, res=res)
    return HybridIndex(inner, int(dense.shape[1]), sdim, float(beta),
                       int(seed))


def fuse_queries(hybrid: HybridIndex, dense_q, sparse_q) -> jax.Array:
    """Project queries into the fused space: ``[q_d | β·proj(q_s)]``.

    The serving entry for hybrid stores: ``serving.search(to_store(h),
    fuse_queries(h, qd, qs), k)`` — the store is a plain ivf_bq store and
    never learns about the fusion."""
    dense_q = jnp.asarray(dense_q, jnp.float32)
    if dense_q.ndim != 2 or dense_q.shape[1] != hybrid.dense_dim:
        raise ValueError(
            f"queries must be (q, {hybrid.dense_dim}), got {dense_q.shape}")
    proj = project_sparse(sparse_q, hybrid.sparse_dim, hybrid.seed)
    return jnp.concatenate([dense_q, hybrid.beta * proj], axis=1)


@traced("hybrid::search")
def search(
    hybrid: HybridIndex,
    dense_q,
    sparse_q,
    k: int,
    n_probes: int = 20,
    filter: Optional[Bitset] = None,
    res: Optional[Resources] = None,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """Fused hybrid k-NN: one BQ strip scan over the concat ranks
    ``⟨q_d, x_d⟩ + β²·⟨proj(q_s), proj(x_s)⟩`` directly. Returns
    (scores, indices), scores in ivf_bq's negated-inner-product order.
    ``filter`` and every other ivf_bq search knob pass straight through —
    push-down and selectivity widening apply to the fused scan
    unchanged."""
    fused_q = fuse_queries(hybrid, dense_q, sparse_q)
    if obs.enabled():
        obs.add("hybrid.searches")
    with obs.record_span("hybrid::search",
                         attrs={"queries": int(fused_q.shape[0]),
                                "k": int(k), "n_probes": int(n_probes),
                                "filtered": filter is not None}):
        return ivf_bq.search(hybrid.index, fused_q, k, n_probes=n_probes,
                             filter=filter, res=res, **kwargs)


def to_store(hybrid: HybridIndex, **kwargs):
    """Wrap the fused index as a paged serving store
    (:class:`~raft_tpu.serving.PagedListStore`, kind ``"ivf_bq"``).
    Upserts must be pre-fused rows (``[dense | β·proj(sparse)]`` — build
    them with :func:`project_sparse` and the index's β/seed); queries go
    through :func:`fuse_queries`."""
    from raft_tpu.serving import PagedListStore

    return PagedListStore.from_index(hybrid.index, **kwargs)
