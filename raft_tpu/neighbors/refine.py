"""Exact re-ranking of ANN candidate lists.

Reference: raft::neighbors::refine (refine-inl.cuh:70; device impl
detail/refine_device.cuh, host impl detail/refine_host-inl.hpp): given a
candidate id list per query (typically an over-fetched ANN result, e.g.
IVF-PQ's approximate top-(k·refine_ratio)), compute exact distances against
the original dataset and keep the best k.

TPU design: one gather of (q_tile, n_cand, dim) candidate rows + a batched
einsum per tile — the gather is the cost, so tiles are sized from the
Resources workspace budget. Candidate id -1 (padding from upstream searches)
is skipped and never dereferenced.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops.select_k import select_k

SUPPORTED_METRICS = ("sqeuclidean", "euclidean", "inner_product", "cosine")


@functools.partial(jax.jit, static_argnames=("k", "metric", "q_tile"))
def _refine_impl(queries, dataset, candidates, k, metric, q_tile):
    q, dim = queries.shape
    n_cand = candidates.shape[1]
    l2 = metric in ("sqeuclidean", "euclidean")

    if metric == "cosine":
        queries = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
        dataset = dataset / jnp.maximum(jnp.linalg.norm(dataset, axis=1, keepdims=True), 1e-30)
    qn = dist_mod.sqnorm(queries) if l2 else None

    def one_tile(args):
        q_blk, qn_blk, cand_blk = args
        safe = jnp.maximum(cand_blk, 0)
        vecs = dataset[safe].astype(jnp.float32)  # (qt, c, dim) gather
        ip = jnp.einsum("qd,qcd->qc", q_blk, vecs, preferred_element_type=jnp.float32)
        if l2:
            vn = dist_mod.sqnorm(vecs, axis=2)
            d = jnp.maximum(qn_blk[:, None] + vn - 2.0 * ip, 0.0)
            if metric == "euclidean":
                d = jnp.sqrt(d)
        elif metric == "cosine":
            d = 1.0 - ip
        else:
            d = -ip  # inner product: min of negated
        d = jnp.where(cand_blk >= 0, d, jnp.inf)
        vals, sel = select_k(d, k, select_min=True)
        out_ids = jnp.where(jnp.isinf(vals), -1, jnp.take_along_axis(cand_blk, sel, axis=1))
        if metric == "inner_product":
            vals = -vals
        return vals, out_ids

    if qn is None:
        qn = jnp.zeros((q,), jnp.float32)
    if q_tile >= q:
        return one_tile((queries, qn, candidates))
    n_tiles = -(-q // q_tile)
    pad = n_tiles * q_tile - q
    qp = jnp.pad(queries, ((0, pad), (0, 0)))
    qnp = jnp.pad(qn, (0, pad))
    cp = jnp.pad(candidates, ((0, pad), (0, 0)), constant_values=-1)
    vals, ids = lax.map(
        one_tile,
        (
            qp.reshape(n_tiles, q_tile, dim),
            qnp.reshape(n_tiles, q_tile),
            cp.reshape(n_tiles, q_tile, n_cand),
        ),
    )
    return vals.reshape(-1, k)[:q], ids.reshape(-1, k)[:q]


def refine(
    dataset,
    queries,
    candidates,
    k: int,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` (q, n_cand) by exact distance and return the
    top-k (refine-inl.cuh:70 analog). ``candidates`` entries of -1 are
    ignored; outputs use -1/inf sentinels the same way searches do."""
    res = res or current_resources()
    metric = dist_mod.canonical_metric(metric)
    if metric not in SUPPORTED_METRICS:
        raise ValueError(f"refine supports {SUPPORTED_METRICS}, got {metric!r}")
    # keep integer datasets (uint8/int8 big-ann formats) in their storage
    # dtype: the gather below is op-bound, so 1-byte rows cost the same ops
    # at 4× fewer bytes, and casting 10M+ rows to fp32 per call would burn
    # an index-sized HBM allocation (round-4, the 10M bench path)
    dataset = jnp.asarray(dataset)
    if not jnp.issubdtype(dataset.dtype, jnp.integer):
        dataset = dataset.astype(jnp.float32)
    queries = jnp.asarray(queries).astype(jnp.float32)
    candidates = jnp.asarray(candidates, jnp.int32)
    if queries.shape[1] != dataset.shape[1]:
        raise ValueError(f"dim mismatch: {queries.shape[1]} != {dataset.shape[1]}")
    if candidates.shape[0] != queries.shape[0]:
        raise ValueError("candidates must have one row per query")
    if not 0 < k <= candidates.shape[1]:
        raise ValueError(f"k={k} out of range for n_candidates={candidates.shape[1]}")
    per_query = max(1, candidates.shape[1] * (dataset.shape[1] + 4) * 4)
    q_tile = int(max(1, min(queries.shape[0], res.workspace_bytes // per_query)))
    return _refine_impl(queries, dataset, candidates, int(k), metric, q_tile)


def refine_host(dataset, queries, candidates, k: int,
                metric: str = "sqeuclidean") -> Tuple:
    """Pure-numpy exact re-rank for CPU serving pipelines (the reference's
    refine_host, detail/refine_host-inl.hpp): same contract as
    :func:`refine` but never touches an accelerator — the companion of the
    HNSW export story (build on TPU, re-rank candidates wherever the
    serving CPU lives).
    """
    import numpy as np

    metric = dist_mod.canonical_metric(metric)
    if metric not in SUPPORTED_METRICS:
        raise ValueError(f"refine_host supports {SUPPORTED_METRICS}, got {metric!r}")
    dataset = np.asarray(dataset, np.float32)
    queries = np.asarray(queries, np.float32)
    cand = np.asarray(candidates, np.int64)
    if not 0 < k <= cand.shape[1]:
        raise ValueError(f"k={k} out of range for n_candidates={cand.shape[1]}")
    if metric == "cosine":
        queries = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
        dataset = dataset / np.maximum(
            np.linalg.norm(dataset, axis=1, keepdims=True), 1e-30)
    rows = dataset[np.clip(cand, 0, dataset.shape[0] - 1)]  # (q, c, d)
    ip = np.einsum("qd,qcd->qc", queries, rows)
    if metric in ("sqeuclidean", "euclidean"):
        d = (np.sum(queries**2, 1)[:, None] + np.sum(rows**2, 2) - 2.0 * ip)
        d = np.maximum(d, 0.0)
        if metric == "euclidean":
            d = np.sqrt(d)
    elif metric == "cosine":
        d = 1.0 - ip
    else:  # inner_product: rank by max → negate for the shared min-select
        d = -ip
    d = np.where(cand >= 0, d, np.inf)
    sel = np.argsort(d, axis=1, kind="stable")[:, :k]
    vals = np.take_along_axis(d, sel, axis=1)
    ids = np.take_along_axis(cand, sel, axis=1).astype(np.int32)
    ids = np.where(np.isfinite(vals), ids, -1)
    if metric == "inner_product":
        vals = np.where(ids >= 0, -vals, -np.inf)
    else:
        vals = np.where(ids >= 0, vals, np.inf)
    return vals.astype(np.float32), ids
