"""CAGRA-class graph ANN index: NN-descent build, detour pruning, best-first search.

Reference: raft::neighbors::cagra — build (cagra.cuh:274 →
detail/cagra/cagra_build.cuh:296: kNN graph via IVF-PQ+refine or NN-descent,
then graph::optimize = detour-count pruning + reverse-edge add,
detail/cagra/graph_core.cuh:320, rev-graph kernel :191); search
(cagra.cuh:299 → detail/cagra/cagra_search.cuh:104, single-CTA persistent
best-first kernel detail/cagra/search_single_cta_kernel-inl.cuh:466 with
pickup_next_parents :51, bitonic top-k merge :405, visited hashmap
detail/cagra/hashmap.hpp). Params mirror cagra_types.hpp:55-134
(intermediate_graph_degree=128, graph_degree=64, itopk_size=64,
search_width=1, max/min_iterations, num_random_samplings).

TPU redesign (SURVEY.md §7 hard-part 2 — data-dependent traversal vs XLA
static shapes):

* **Build**: NN-descent (nn_descent.py) gives the intermediate graph with
  distances; pruning streams the detour-count computation as a
  ``lax.scan`` over rank positions (K² comparisons per node per step)
  instead of the GPU's per-edge bitwise kernel — everything static-shape.
* **Search**: a fixed-capacity itopk candidate buffer per query, advanced by
  a ``lax.while_loop``; each step expands the best ``search_width``
  unvisited entries, gathers their graph rows, computes distances with one
  batched einsum across the whole query batch (MXU-friendly: the per-query
  matvec becomes a (Q, w·deg, dim) batched contraction), and merges via
  compare-matrix dedup + a narrow top-k — the hashmap+bitonic-sort
  replacement. Termination: all itopk entries visited, or max_iterations.
* The visited set is the buffer's per-slot flag (the single-CTA parent bit);
  a node evicted and later re-inserted may be re-expanded — a bounded waste
  the GPU hashmap avoids, accepted here to keep shapes static.

**Round-5 compressed traversal** (the production path at scale; the
reference's CAGRA-Q compressed-dataset search is the analog,
cagra_types.hpp's int8/uint8 dataset + vpq compression):

XLA row gathers on this hardware are op-bound (~12 ns/row regardless of
row width or dtype), so the exact loop's q·w·deg per-iteration
neighbor-vector gathers — not FLOPs or HBM bytes — are the entire cost.
The round-5 layout makes the gather count per iteration q·w instead:

* each node's record inlines its neighbors' vectors, compressed to
  ``compress_dim``-d int8 via a PCA projection
  (``nbr_codes[i, j] = quantize(proj(X[graph[i, j]]))``; top principal
  axes — measured +10 recall points over a random subspace at p=dim/3 on
  siftlike) — one contiguous per-parent fetch yields all deg candidate
  vectors, 64× fewer gather ops at graph_degree 64;
* traversal distances are computed from the codes on the MXU
  (projected-space ranking only); the final answer is exactly re-ranked
  over the itopk buffer against the raw dataset — the same
  compressed-search + refine split as CAGRA-Q;
* seeding is centroid-guided: one (q, n_centroids) MXU gemm against the
  build-time coarse centroids picks per-query entry points (their stored
  nearest-dataset-row representatives), replacing random seeds and their
  gather storm — fewer iterations to reach the query's neighborhood;
* the itopk merge runs on the mantissa-packed iter select
  (ops/select_k.iter_topk_min_packed) — 2 VPU ops per pass over a
  (q, itopk + w·deg) row instead of lax.top_k's full sort.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import os as _os

from raft_tpu import obs
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.trace import traced
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.serialize import load_arrays, save_arrays
from raft_tpu.neighbors import nn_descent as nnd
from raft_tpu.ops.cagra_hop import MAX_FUSED_ROWS, fused_hop
from raft_tpu.ops.segment import merge_topk_dedup, segment_take
# hoisted to module scope (code-review r6): the loop-body copies of this
# import re-executed on every trace of the compressed search
from raft_tpu.ops.select_k import iter_topk_min, iter_topk_min_packed
from raft_tpu.utils.tiling import ceil_div


@dataclass(frozen=True)
class CagraParams:
    """cagra::index_params analog (cagra_types.hpp:55-63)."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    # "auto": exact one-pass kNN below ``brute_threshold`` rows, IVF-PQ +
    # refine above (the reference's default builder, cagra_build.cuh:87).
    # "nn_descent" (detail/nn_descent.cuh) remains available but its
    # host-driven iteration loop is dispatch-bound on this TPU runtime —
    # the IVF-PQ path is the production TPU builder.
    build_algo: str = "auto"  # "auto" | "ivf_pq" | "nn_descent" | "brute"
    nn_descent_niter: int = 20
    brute_threshold: int = 65536
    # IVF builder knobs (0 = auto-sized from n/dim). The "ivf_pq" algo uses
    # an IVF-FLAT scan (exact in-list distances, no refine pass) while the
    # raw dataset fits comfortably in HBM, and the PQ+refine pipeline above
    # that — same candidate-generation structure as the reference's
    # cagra_build.cuh:87, picked by memory footprint.
    ivf_pq_n_lists: int = 0
    ivf_pq_n_probes: int = 0
    ivf_pq_refine_rate: float = 2.0
    # device-resident neighbor-of-neighbor refinement sweeps after an
    # approximate (IVF) build — the NN-descent local join recast with
    # static shapes (detail/nn_descent.cuh:1215); lifts graph recall toward
    # exact. -1 = auto: 0 after the exact-distance IVF-Flat candidate scan
    # (measured 0.97 graph recall at 1M — sweeps add ~1.5 points of graph
    # recall but no search recall), 2 after the PQ+refine builder whose
    # candidate recall is lower
    graph_refine_iters: int = -1
    graph_refine_sample: int = 448
    # compressed-traversal payload (round 5, the CAGRA-Q analog): inline
    # each node's neighbors as compress_dim-d int8 codes so search gathers
    # one record per expanded parent instead of one row per neighbor.
    # "auto" = on above compress_threshold rows (the payload costs
    # n·graph_degree·compress_dim bytes of HBM — worth it exactly when the
    # gather count dominates, i.e. at scale).
    compress: str = "auto"  # "auto" | "on" | "off"
    compress_dim: int = 0  # 0 = auto: min(64, dim)
    compress_threshold: int = 200_000
    seed: int = 0

    def __post_init__(self):
        if self.graph_degree <= 0:
            raise ValueError("graph_degree must be positive")
        if self.intermediate_graph_degree < self.graph_degree:
            raise ValueError("intermediate_graph_degree < graph_degree")
        if self.build_algo not in ("auto", "ivf_pq", "nn_descent", "brute"):
            raise ValueError(f"unknown build_algo {self.build_algo!r}")
        if self.compress not in ("auto", "on", "off"):
            raise ValueError(f"unknown compress mode {self.compress!r}")


@dataclass(frozen=True)
class CagraSearchParams:
    """cagra::search_params analog (cagra_types.hpp:77-118)."""

    itopk_size: int = 64
    max_iterations: int = 0  # 0 = auto-sized from itopk/search_width
    min_iterations: int = 0
    search_width: int = 1
    num_random_samplings: int = 1
    # "auto" rides the fused one-kernel hop (ops/cagra_hop.py) whenever the
    # index carries the inlined-int8-codes payload and the backend compiles
    # it (TPU), the unfused compressed loop otherwise; "fused"/"compressed"
    # force their loop (both error if the payload is absent); "exact"
    # forces full-precision traversal (the pre-round-5 loop)
    traversal: str = "auto"  # "auto" | "fused" | "compressed" | "exact"
    # exact re-rank depth for the compressed loop: the final answer ranks
    # the best refine_topk buffer entries against the raw dataset
    # (0 = the whole itopk buffer — safest; shrink to trade a little
    # recall for q·refine_topk fewer exit gathers)
    refine_topk: int = 0
    seed: int = 0

    def __post_init__(self):
        if self.itopk_size <= 0 or self.search_width <= 0:
            raise ValueError("itopk_size and search_width must be positive")
        if self.traversal not in ("auto", "fused", "compressed", "exact"):
            raise ValueError(f"unknown traversal mode {self.traversal!r}")


@jax.tree_util.register_pytree_node_class
@dataclass
class CagraIndex:
    """Graph index: dataset + fixed-degree kNN graph (cagra_types.hpp:55-134).

    The optional round-5 fields carry the compressed-traversal payload
    (None on indexes built with ``compress="off"`` or loaded from pre-r5
    files — those search via the exact loop):

    * ``proj``/``code_scale``: the (dim, p) PCA projection (orthonormal
      rotation when p == dim) and int8 quantization scale;
    * ``nbr_codes``: (n, graph_degree, p) int8 — node i's record inlines
      the projected codes of all its graph neighbors;
    * ``centroids``/``centroid_reps``: coarse centers from the IVF builder
      + each center's nearest dataset row, for guided seeding.
    """

    dataset: jax.Array  # (n, dim) fp32 (or uint8/int8 for integer inputs)
    graph: jax.Array  # (n, graph_degree) int32 neighbor ids
    norms: jax.Array  # (n,) squared L2 norms
    proj: Optional[jax.Array] = None  # (dim, p) fp32
    code_scale: Optional[jax.Array] = None  # () fp32
    nbr_codes: Optional[jax.Array] = None  # (n, graph_degree, p) int8
    centroids: Optional[jax.Array] = None  # (c, dim) fp32
    centroid_reps: Optional[jax.Array] = None  # (c,) int32
    # fraction of centered data variance the projection keeps — scales
    # full-space seed distances into projected space (PCA keeps more than
    # the random-subspace p/dim; None = legacy p/dim)
    proj_energy: Optional[jax.Array] = None  # () fp32

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    def tree_flatten(self):
        return (self.dataset, self.graph, self.norms, self.proj,
                self.code_scale, self.nbr_codes, self.centroids,
                self.centroid_reps, self.proj_energy), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- persistence (cagra_serialize.cuh analog) ---------------------------
    def save(self, path) -> None:
        arrays = {"dataset": self.dataset, "graph": self.graph,
                  "norms": self.norms}
        for name in ("proj", "code_scale", "nbr_codes", "centroids",
                     "centroid_reps", "proj_energy"):
            v = getattr(self, name)
            if v is not None:
                arrays[name] = v
        save_arrays(path, {"kind": "cagra", "metric": "sqeuclidean"}, arrays)

    @classmethod
    def load(cls, path) -> "CagraIndex":
        meta, arrays = load_arrays(path)
        if meta.get("kind") != "cagra":
            raise ValueError(f"not a cagra index: {meta.get('kind')}")
        opt = {
            name: jnp.asarray(arrays[name])
            for name in ("proj", "code_scale", "nbr_codes", "centroids",
                         "centroid_reps", "proj_energy")
            if name in arrays
        }
        return cls(
            jnp.asarray(arrays["dataset"]),
            jnp.asarray(arrays["graph"]),
            jnp.asarray(arrays["norms"]),
            **opt,
        )


# ---------------------------------------------------------------------------
# Build: kNN graph + optimize (prune)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("out_degree", "n_blocks"))
def optimize(graph: jax.Array, out_degree: int, n_blocks: int = 1) -> jax.Array:
    """Prune an intermediate kNN graph to ``out_degree`` (graph::optimize,
    detail/cagra/graph_core.cuh:320).

    Two stages, mirroring the reference:

    1. **Detour-count pruning**: edge (s→t) at rank j is detourable through
       u at rank i<j when t appears in u's list at rank m<j (a 2-hop path of
       strictly better-ranked edges). Keep the ``out_degree`` edges with the
       fewest detours (rank as tie-break). Computed as a ``lax.scan`` over
       rank position j with K² membership tests per node — static shapes,
       streamed memory.
    2. **Reverse-edge add** (rev-graph kernel analog, graph_core.cuh:191):
       the final list interleaves the best half of the pruned forward edges
       with up to degree/2 reverse edges (dedup'd, forward edges fill any
       remainder) so that every node stays reachable.
    """
    n, K = graph.shape
    block = ceil_div(n, n_blocks)
    pad = n_blocks * block - n
    g_pad = jnp.pad(graph, ((0, pad), (0, 0)), constant_values=-1)

    def count_block(_, gb):
        # gb: (B, K) neighbor ids of this node block
        two_hop = graph[jnp.maximum(gb, 0)]  # (B, K, K): neighbors of neighbors

        def step(j, counts):
            t = gb[:, j]  # (B,) target id at rank j
            # membership of t among each better-ranked neighbor's prefix:
            # hit[b, i, m] = (two_hop[b, i, m] == t[b]) & (i < j) & (m < j)
            hit = two_hop == t[:, None, None]
            ii = jnp.arange(K)[None, :, None] < j
            mm = jnp.arange(K)[None, None, :] < j
            c = jnp.sum(hit & ii & mm, axis=(1, 2)).astype(jnp.int32)
            return counts.at[:, j].set(c)

        counts = lax.fori_loop(0, K, step, jnp.zeros(gb.shape, jnp.int32))
        return None, counts

    _, counts = lax.scan(
        count_block, None, g_pad.reshape(n_blocks, block, K)
    )
    counts = counts.reshape(-1, K)[:n]

    # keep out_degree edges with fewest detours (rank breaks ties);
    # invalid (-1) entries sort last
    rank = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, :], graph.shape)
    key = jnp.where(graph >= 0, counts * K + rank, jnp.iinfo(jnp.int32).max)
    order = jnp.argsort(key, axis=1)[:, :out_degree]
    fwd = jnp.take_along_axis(graph, order, axis=1)  # (n, out_degree)

    # reverse candidates of the pruned graph, capped at out_degree per node,
    # better-ranked sources first
    half = max(1, out_degree // 2)
    src = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None], fwd.shape
    ).reshape(-1)
    tgt = fwd.reshape(-1)
    rnk = jnp.broadcast_to(
        jnp.arange(out_degree, dtype=jnp.int32)[None, :], fwd.shape
    ).reshape(-1)
    keys = jnp.where(tgt >= 0, tgt, n).astype(jnp.int32)
    order = jnp.lexsort((rnk, keys))
    valid, rev = segment_take(keys[order], n, half, src[order])
    rev = jnp.where(valid, rev, -1)

    # interleave: forward first-half at priority 0..half-1, reverse at
    # half..half+half-1, forward second-half last; dedup by id keeps the
    # best priority
    prio_fwd = jnp.where(
        jnp.arange(out_degree)[None, :] < half,
        jnp.arange(out_degree, dtype=jnp.int32)[None, :],
        (jnp.arange(out_degree, dtype=jnp.int32) + 2 * half)[None, :],
    ).astype(jnp.float32)
    prio_fwd = jnp.broadcast_to(prio_fwd, fwd.shape)
    prio_fwd = jnp.where(fwd >= 0, prio_fwd, jnp.inf)
    prio_rev = jnp.broadcast_to(
        (jnp.arange(half, dtype=jnp.int32) + half)[None, :].astype(jnp.float32),
        rev.shape,
    )
    prio_rev = jnp.where(rev >= 0, prio_rev, jnp.inf)
    out_ids, _, _ = merge_topk_dedup(
        fwd, prio_fwd, rev, prio_rev, out_degree,
        exclude_self=jnp.arange(n, dtype=jnp.int32),
    )
    return out_ids


def _drop_self(ids, row_start: int, ideg: int):
    """Remove each row's self-match and compact to ideg columns (stable)."""
    rows = row_start + jnp.arange(ids.shape[0], dtype=jnp.int32)
    ids = jnp.where(ids == rows[:, None], -1, ids)
    order = jnp.argsort(jnp.where(ids < 0, 2, 0), axis=1, stable=True)[:, :ideg]
    return jnp.take_along_axis(ids, order, axis=1)


def _flat_builder_fits(n: int, dim: int) -> bool:
    """IVF-Flat candidate scan (exact distances, no refine) while the raw
    fp32 dataset stays ≤ 2 GB of HBM; PQ+refine above. Shared by the build
    path selection and the auto graph-refine-sweep decision — one predicate
    so the two cannot desync (code-review r4)."""
    return n * dim * 4 <= (2 << 30)


def _build_knn_ivf_pq(X, ideg: int, params: "CagraParams", res):
    """Intermediate kNN graph via an IVF candidate search — the reference's
    scalable builder (cagra_build.cuh:87 build_knn_graph: ivf_pq::build,
    batched ivf_pq::search over the dataset itself, refine at
    ``refine_rate`` over-fetch). O(n·√n̄) instead of the O(n²) brute pass;
    the only TPU-viable route past ~1M rows (nn_descent's per-iteration
    host dispatch loop measured impractical on this runtime, round 3).

    TPU adaptation: while the raw fp32 dataset fits comfortably in HBM
    (≤ 2 GB), candidates come from an IVF-FLAT scan instead — exact
    in-list distances, so the refine pass disappears and the per-pair
    fetch width drops from refine_rate·(ideg+1) to ideg+2 (the in-kernel
    top-k cost is linear in that width). Above the threshold, the PQ +
    exact-refine pipeline, as in the reference."""
    n, dim = X.shape
    n_lists = params.ivf_pq_n_lists or int(
        max(16, min(65536, round((n / 976) ** 0.5) ** 2, n // 64)))
    n_probes = params.ivf_pq_n_probes or max(8, n_lists // 16)
    from raft_tpu.core.interruptible import check_interrupt

    out = []
    if _flat_builder_fits(n, dim):
        from raft_tpu.neighbors import ivf_flat as flm

        # ideg+1 covers the self-match slot: after _drop_self at least
        # ideg non-self neighbors remain whether or not self was fetched
        kf = ideg + 1
        idx = flm.build(X, flm.IvfFlatParams(
            n_lists=n_lists,
            kmeans_trainset_fraction=float(min(1.0, max(0.1, 200_000 / n))),
            group_size=512, seed=params.seed,
        ), res=res)
        B = int(max(4096, min(n, res.workspace_bytes
                              // max(kf * (dim + 8) * 4, 1))))
        for s in range(0, n, B):
            check_interrupt()
            qb = lax.slice_in_dim(X, s, min(s + B, n), axis=0)
            _, ids = flm.search(idx, qb, kf, n_probes=n_probes, res=res)
            out.append(_drop_self(ids, s, ideg))
    else:
        from raft_tpu.neighbors import ivf_pq as pqm
        from raft_tpu.neighbors import refine as refm

        kf = int(min(max(ideg + 2,
                         round(params.ivf_pq_refine_rate * (ideg + 1))), 512))
        idx = pqm.build(X, pqm.IvfPqParams(
            n_lists=n_lists, pq_dim=max(8, dim // 2), pq_bits=8,
            kmeans_trainset_fraction=float(min(1.0, max(0.1, 200_000 / n))),
            seed=params.seed,
        ), res=res)
        B = int(max(4096, min(n, res.workspace_bytes
                              // max(kf * (dim + 8) * 4, 1))))
        for s in range(0, n, B):
            check_interrupt()
            qb = lax.slice_in_dim(X, s, min(s + B, n), axis=0)
            _, cand = pqm.search(idx, qb, kf, n_probes=n_probes, res=res)
            _, ids = refm.refine(X, qb, cand, min(ideg + 1, kf), res=res)
            out.append(_drop_self(ids, s, ideg))
    graph = jnp.concatenate(out, axis=0) if len(out) > 1 else out[0]
    # the coarse centers double as the search's guided-seeding table
    return graph, idx.centers


@functools.partial(jax.jit, static_argnames=("sample", "block"))
def _refine_graph_block(X, graph, start, key, sample: int, block: int):
    """One node block of the neighbor-of-neighbor sweep: candidates = own
    current list + ``sample`` random 2-hop neighbors, exact distances, keep
    the best ideg (dedup'd)."""
    n, dim = X.shape
    ideg = graph.shape[1]
    rows = start + jnp.arange(block, dtype=jnp.int32)
    rows_c = jnp.minimum(rows, n - 1)
    own = graph[rows_c]                                    # (B, ideg)
    two_hop = graph[jnp.maximum(own, 0)]                   # (B, ideg, ideg)
    pick = jax.random.randint(key, (block, sample), 0, ideg * ideg)
    cand2 = jnp.take_along_axis(
        two_hop.reshape(block, ideg * ideg), pick, axis=1)
    cands = jnp.concatenate([own, cand2], axis=1)          # (B, ideg+sample)
    cands = jnp.where(cands == rows[:, None], -1, cands)   # drop self
    xv = X[jnp.maximum(cands, 0)].astype(jnp.float32)
    qv = X[rows_c].astype(jnp.float32)
    d = jnp.sum(xv * xv, axis=2) - 2.0 * jnp.einsum(
        "bcd,bd->bc", xv, qv, preferred_element_type=jnp.float32)
    d = jnp.where(cands >= 0, d, jnp.inf)
    # dedup-then-select, merge_topk_dedup style: a GOOD graph's 2-hop
    # candidates repeat heavily (shared neighbors), so any fixed top-m
    # window can fill with copies before ideg uniques appear — the round-4
    # bug that silently halved graph degree at 1M. The id-primary lexsort
    # makes every duplicate adjacent regardless of multiplicity; the second
    # sort restores distance order over the surviving first copies.
    order = jnp.lexsort((d, cands), axis=-1)
    si = jnp.take_along_axis(cands, order, axis=1)
    sd = jnp.take_along_axis(d, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((block, 1), jnp.bool_), si[:, 1:] == si[:, :-1]], axis=1)
    sd = jnp.where(dup | (si < 0), jnp.inf, sd)
    order2 = jnp.argsort(sd, axis=1)[:, :ideg]
    out = jnp.take_along_axis(si, order2, axis=1)
    keep = jnp.take_along_axis(sd, order2, axis=1) < jnp.inf
    return jnp.where(keep, out, -1)


def refine_knn_graph(X, graph, iters: int, sample: int, seed: int,
                     res) -> jax.Array:
    """Device-resident NN-descent-style refinement of an intermediate kNN
    graph (the local-join of detail/nn_descent.cuh:1215, recast as
    fixed-shape blocks: candidates = current neighbors + sampled 2-hop
    neighbors, exact distances on the MXU, sort-free dedup). Each sweep is
    a handful of dispatches over node blocks — unlike the host-driven
    nn_descent loop, viable on the tunneled TPU runtime."""
    from raft_tpu.core.interruptible import check_interrupt

    n, dim = X.shape
    ideg = graph.shape[1]
    width = ideg + sample
    block = int(max(1024, min(n,
                              res.workspace_bytes // max(width * (dim + 4) * 4, 1))))
    key = jax.random.key(seed ^ 0x5EED)
    for it in range(iters):
        parts = []
        for s in range(0, n, block):
            check_interrupt()
            key, sub = jax.random.split(key)
            g = _refine_graph_block(X, graph, s, sub, int(sample), block)
            b = min(block, n - s)
            parts.append(g[:b] if b < block else g)
        graph = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    return graph



def _sync(x) -> None:
    """Force completion on runtimes where block_until_ready does not
    synchronize (the tunneled axon runtime): a 1-element host fetch drains
    the stream up to x.

    Gated on telemetry: the per-phase syncs exist so ``_build_timings_s`` /
    ``cagra.build.*`` timers measure completion rather than dispatch. With
    telemetry off the build pipeline stays fully async — no host round-trip
    between phases — and ``_build_timings_s`` records dispatch times only.
    Callers that consume the phase timings (bench.py's cagra section) enable
    obs around the build so their recorded numbers stay completion-based.
    """
    if not obs.enabled():
        return
    import numpy as _np

    _np.asarray(jax.device_get(x.ravel()[:1] if hasattr(x, "ravel") else x))

@traced("cagra::build")
def build(
    dataset,
    params: CagraParams = CagraParams(),
    res: Optional[Resources] = None,
) -> CagraIndex:
    """Build a CAGRA index (cagra.cuh:274 → cagra_build.cuh:296): kNN graph
    via IVF-PQ+refine (or exact for small n, or NN-descent), then optimize
    to graph_degree.

    Phase wall-clock (knn_graph / refine_sweeps / optimize / compress) is
    recorded on the returned index as ``_build_timings_s`` — the bench
    surfaces it so build-time work has a profile to attack (VERDICT r4 #3).
    """
    import time as _time

    from raft_tpu.resilience import faultpoint

    faultpoint("cagra.build")
    res = res or current_resources()
    X = jnp.asarray(dataset, jnp.float32)
    n, dim = X.shape
    ideg = int(min(params.intermediate_graph_degree, n - 1))
    deg = int(min(params.graph_degree, ideg))

    algo = params.build_algo
    if algo == "auto":
        algo = "brute" if n <= params.brute_threshold else "ivf_pq"

    timings = {}
    t0 = _time.perf_counter()
    centroids = None
    if algo == "brute" or n <= 2048:
        # exact graph for small datasets: one tiled MXU pass beats training
        # an IVF index at this scale
        from raft_tpu.neighbors.brute_force import knn

        _, ids = knn(X, X, ideg + 1, metric="sqeuclidean", res=res)
        graph = _drop_self(ids, 0, ideg)
        _sync(graph)
        timings["knn_graph"] = _time.perf_counter() - t0
    elif algo == "ivf_pq":
        graph, centroids = _build_knn_ivf_pq(X, ideg, params, res)
        _sync(graph)
        timings["knn_graph"] = _time.perf_counter() - t0
        sweeps = params.graph_refine_iters
        if sweeps < 0:  # auto: the flat candidate scan is already ~exact
            sweeps = 0 if _flat_builder_fits(n, dim) else 2
        if sweeps > 0:
            t0 = _time.perf_counter()
            graph = refine_knn_graph(
                X, graph, int(sweeps),
                int(params.graph_refine_sample), params.seed, res)
            _sync(graph)
            timings["refine_sweeps"] = _time.perf_counter() - t0
    else:
        graph = nnd.build(
            X,
            nnd.NNDescentParams(
                graph_degree=ideg,
                intermediate_graph_degree=min(int(1.5 * ideg), n - 1),
                max_iterations=params.nn_descent_niter,
                seed=params.seed,
            ),
            res=res,
        )
        timings["knn_graph"] = _time.perf_counter() - t0

    # detour-prune in blocks bounded by workspace: scan materializes
    # (block, K, K) two-hop ids (int32)
    t0 = _time.perf_counter()
    per_node = ideg * ideg * 4 * 2
    block = max(128, int(res.workspace_bytes // max(per_node, 1) // 2))
    n_blocks = max(1, ceil_div(n, block))
    pruned = optimize(graph, deg, n_blocks=n_blocks)
    norms = jnp.sum(X * X, axis=1)
    _sync(pruned)
    timings["optimize"] = _time.perf_counter() - t0
    # integer datasets (uint8/int8, the big-ann formats) are stored in their
    # own dtype — 4× less HBM; the search upcasts gathered rows on the fly
    # (cagra_types.hpp supports int8/uint8 datasets the same way)
    store = jnp.asarray(dataset)
    if not jnp.issubdtype(store.dtype, jnp.integer):
        store = X

    compress = params.compress == "on" or (
        params.compress == "auto" and n >= params.compress_threshold)
    out = CagraIndex(store, pruned, norms)
    if compress:
        t0 = _time.perf_counter()
        out = _attach_compression(out, X, params, centroids, res)
        _sync(out.nbr_codes)
        timings["compress"] = _time.perf_counter() - t0
    out._build_timings_s = {k: round(v, 2) for k, v in timings.items()}
    if obs.enabled():
        obs.add("cagra.build.nodes", n)
        for phase, secs in timings.items():
            obs.record_timing(f"cagra.build.{phase}", secs)
    return out


def _attach_compression(index: CagraIndex, X, params: CagraParams,
                        centroids, res) -> CagraIndex:
    """Build the round-5 compressed-traversal payload: a PCA projection to
    ``compress_dim`` (orthonormal basis when compress_dim == dim), per-node
    inlined neighbor codes, and the centroid seeding table (computing
    centers with a quick balanced k-means when the builder didn't produce
    any)."""
    n, dim = X.shape
    p = int(params.compress_dim) or min(64, dim)
    p = min(p, dim)
    key = jax.random.key(params.seed ^ 0xC0DE)
    if p < dim:
        # PCA projection: descriptor data is strongly correlated, so the
        # top-p principal axes keep far more of the distance signal than a
        # random p-subspace (measured +10 recall points at p=dim/3 on
        # siftlike). Sample covariance on ≤256k rows via the in-repo
        # helpers (stats.cov fuses the centering; ops.linalg.eig_dc's
        # sign_flip keeps eigenvector signs — and hence saved index
        # bytes — deterministic across backends).
        from raft_tpu.ops.linalg import eig_dc
        from raft_tpu.stats import cov as stats_cov

        m = min(n, 262_144)
        rows = (jax.random.randint(key, (m,), 0, n)
                if m < n else jnp.arange(n))
        c = jax.jit(stats_cov, static_argnames="sample")(X[rows],
                                                         sample=False)
        vals, vecs = eig_dc(c)  # ascending eigenvalues
        proj = vecs[:, ::-1][:, :p]  # (dim, p) top components
        energy = jnp.sum(vals[-p:]) / jnp.maximum(jnp.sum(vals), 1e-30)
    else:
        # no reduction: any orthonormal basis is exact; skip the eigh
        g = jax.random.normal(key, (dim, p), jnp.float32)
        proj, _ = jnp.linalg.qr(g)
        energy = jnp.float32(1.0)
    # seeding table first: its brute kNN runs with a workspace-sized score
    # tile, and doing it BEFORE the n·deg·p code payload exists keeps the
    # two HBM spikes from stacking (1M×128/deg=64/p=64 peaked out a 16 GB
    # chip otherwise)
    reps = None
    if centroids is None and n > 4 * 1024:
        from raft_tpu.cluster import kmeans_balanced

        c = int(max(16, min(1024, n // 256)))
        frac = float(min(1.0, max(0.05, 100_000 / n)))
        # with-replacement draw: choice(replace=False) compiles an
        # O(n log n) permutation (the round-3 kmeans_balanced finding);
        # duplicate trainset rows are harmless to k-means
        rows = (jax.random.randint(jax.random.key(params.seed ^ 0x5EED5),
                                   (int(frac * n),), 0, n)
                if frac < 1.0 else slice(None))
        centroids = kmeans_balanced.fit(
            X[rows], c, kmeans_balanced.KMeansBalancedParams(), res=res)
    if centroids is not None:
        from raft_tpu.neighbors.brute_force import knn

        _, rep_ids = knn(centroids, X, 1, metric="sqeuclidean", res=res)
        reps = rep_ids[:, 0].astype(jnp.int32)

    xp = X @ proj  # (n, p)
    scale = jnp.maximum(jnp.max(jnp.abs(xp)) / 127.0, 1e-12)
    codes = jnp.clip(jnp.round(xp / scale), -127, 127).astype(jnp.int8)
    del xp
    # inline the neighbors' codes in row blocks (one big gather would hold
    # gather temporaries on top of the 4 GB output at 1M×64×64)
    blk = int(max(65536, res.workspace_bytes
                  // max(index.graph_degree * p * 2, 1)))
    parts = []
    for s in range(0, n, blk):
        gb = index.graph[s:s + blk]
        nc = codes[jnp.maximum(gb, 0)]
        parts.append(jnp.where(gb[..., None] >= 0, nc, jnp.int8(0)))
    nbr_codes = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    return CagraIndex(index.dataset, index.graph, index.norms,
                      proj=proj, code_scale=scale, nbr_codes=nbr_codes,
                      centroids=centroids, centroid_reps=reps,
                      proj_energy=energy)


@traced("cagra::build_from_graph")
def build_from_graph(dataset, graph) -> CagraIndex:
    """Wrap a prebuilt kNN graph (the from-serialized / interop path)."""
    X = jnp.asarray(dataset, jnp.float32)
    return CagraIndex(X, jnp.asarray(graph, jnp.int32), jnp.sum(X * X, axis=1))


# ---------------------------------------------------------------------------
# Search
# ---------------------------------------------------------------------------


# internal tuning knob for the compressed loop's merge (see merge() in
# _search_impl_compressed); 0 forces the slack+re-select path everywhere
_CAGRA_DEDUP_LIMIT = int(_os.environ.get("RAFT_TPU_CAGRA_DEDUP_LIMIT", "512"))


def _merge_candidates(bids, bd, bvis, cids, cd, itopk: int, packed: bool,
                      dedup_limit: int):
    """Buffer ∪ candidates → new (ids, d, vis): the ONE merge both
    traversal loops share (code-review r5 — the two hand-tuned copies had
    already diverged once). Candidate-side duplicates are masked exactly
    pre-select while the (q, b, b) compare tensor stays VPU-cheap
    (b ≤ dedup_limit); wider candidate sets select itopk + slack, mask
    later duplicate copies among the survivors, and compact with one
    narrow re-select — so duplicate copies never occupy itopk slots
    (ADVICE r4 cagra.py:536, now fixed for BOTH loops). ``packed`` picks
    the mantissa-packed iter select (2 VPU ops/pass) over ``lax.top_k``;
    top_k/packed are both stable, so the first copy — the buffer's,
    carrying its visited flag — is the one kept."""
    inf = jnp.float32(jnp.inf)
    dup_buf = jnp.any(cids[:, :, None] == bids[:, None, :], axis=2)
    bb = cids.shape[1]
    if bb <= dedup_limit:
        eq = cids[:, :, None] == cids[:, None, :]
        tri = jnp.tril(jnp.ones((bb, bb), jnp.bool_), k=-1)
        dup_self = jnp.any(eq & tri[None], axis=2)
        cd = jnp.where(dup_buf | dup_self | (cids < 0), inf, cd)
        slack = 0
    else:
        cd = jnp.where(dup_buf | (cids < 0), inf, cd)
        # capped at bb: the select reads itopk + slack of itopk + bb
        slack = min(bb, max(8, itopk // 4))
    allv = jnp.concatenate([bd, cd], axis=1)
    alli = jnp.concatenate([bids, cids], axis=1)
    allvis = jnp.concatenate(
        [bvis, jnp.zeros(cids.shape, jnp.bool_)], axis=1)

    def select(vals, kk):
        if packed:
            return iter_topk_min_packed(vals, kk)
        nv, sel = lax.top_k(-vals, kk)
        return -nv, sel

    nv, sel = select(allv, itopk + slack)
    ni = jnp.take_along_axis(alli, sel, axis=1)
    nvis = jnp.take_along_axis(allvis, sel, axis=1)
    if slack:
        w2 = itopk + slack
        dup = jnp.any(
            (ni[:, :, None] == ni[:, None, :])
            & (jnp.arange(w2)[None, None, :]
               < jnp.arange(w2)[None, :, None]), axis=2)
        nv = jnp.where(dup, inf, nv)
        nv, sel2 = select(nv, itopk)
        ni = jnp.take_along_axis(ni, sel2, axis=1)
        nvis = jnp.take_along_axis(nvis, sel2, axis=1)
    ni = jnp.where(jnp.isinf(nv), -1, ni)
    return ni, nv, nvis


@functools.partial(
    jax.jit,
    static_argnames=("k", "itopk", "width", "max_iter", "min_iter", "n_rand"),
)
def _search_impl(
    dataset, graph, queries, key, filter_bits, n_bits,
    k, itopk, width, max_iter, min_iter, n_rand,
):
    """Round-4 loop body, rebuilt from on-device microbenchmarks:

    * the round-3 sort-based merge (merge_topk_dedup: one 2-key variadic
      lexsort + argsort + 6 take_along_axis) measured ~12 ms/iteration at
      (q=2000, itopk=64) — 4× the gather it was merging. Narrow-row
      ``lax.top_k`` measured 0.44 ms at width 128, so the merge is now
      concat + top_k + two payload gathers, with dedup done by a
      (q, b, itopk) compare matrix instead of the sort.
    * per-entry norms come from the gathered rows (‖x‖² = Σx²) instead of a
      second (q, b) row gather of a norms table — the row gather is
      op-bound (~12 ns/row regardless of dtype/width), so dropping the
      second gather cut the distance stage ~40%.
    * visited marking is a compare against the picked positions, not a
      scatter.
    """
    n, dim = dataset.shape
    q = queries.shape[0]
    deg = graph.shape[1]
    b = width * deg
    qf = queries.astype(jnp.float32)
    inf = jnp.float32(jnp.inf)
    iota_itopk = jnp.arange(itopk, dtype=jnp.int32)

    def batch_dists(ids):
        """(q, m) ranking scores ‖x‖² − 2⟨q, x⟩ (query norm added at the
        end — it cannot change per-query ranking)."""
        xv = dataset[jnp.maximum(ids, 0)].astype(jnp.float32)  # (q, m, dim)
        ip = jnp.einsum("qmd,qd->qm", xv, qf,
                        preferred_element_type=jnp.float32)
        d = jnp.sum(xv * xv, axis=2) - 2.0 * ip
        return jnp.where(ids >= 0, d, inf)

    def merge(bids, bd, bvis, cids, cd):
        # shared buffer∪candidate merge; exact select (the hashmap +
        # bitonic-merge replacement)
        return _merge_candidates(bids, bd, bvis, cids, cd, itopk,
                                 packed=False, dedup_limit=320)

    # ---- init: random seeds (num_random_samplings analog) -----------------
    n_seed = min(itopk * n_rand, n)
    seed_ids = jax.random.randint(key, (q, n_seed), 0, n, dtype=jnp.int32)
    seed_d = batch_dists(seed_ids)
    buf_ids, buf_d, buf_vis = merge(
        jnp.full((q, itopk), -1, jnp.int32),
        jnp.full((q, itopk), inf, jnp.float32),
        jnp.ones((q, itopk), jnp.bool_),
        seed_ids, seed_d,
    )

    def cond(state):
        ids_b, _, vis, it = state
        frontier_open = jnp.any(~vis & (ids_b >= 0))
        return (it < max_iter) & (frontier_open | (it < min_iter))

    def body(state):
        ids_b, d_b, vis, it = state
        # pickup_next_parents (:51): best `width` unvisited buffer entries
        pkey = jnp.where(vis | (ids_b < 0), inf, d_b)
        _, ppos = lax.top_k(-pkey, width)  # positions of best unvisited
        parent_ids = jnp.take_along_axis(ids_b, ppos, axis=1)  # (q, w)
        parent_ok = jnp.take_along_axis(pkey, ppos, axis=1) < inf
        # mark them visited (compare, not scatter: TPU scatters serialize)
        vis = vis | jnp.any(
            iota_itopk[None, None, :] == ppos[:, :, None], axis=1)
        # expand: gather graph rows → (q, w*deg) candidates
        gr = graph[jnp.maximum(parent_ids, 0)]  # (q, w, deg)
        nbrs = jnp.where(parent_ok[:, :, None] & (gr >= 0), gr, -1)
        nbrs = nbrs.reshape(q, b)
        nd = batch_dists(nbrs)
        ids2, d2, vis2 = merge(ids_b, d_b, vis, nbrs, nd)
        return ids2, d2, vis2, it + 1

    buf_ids, buf_d, _, _ = lax.while_loop(
        cond, body, (buf_ids, buf_d, buf_vis, jnp.int32(0))
    )

    # ---- output: filter + top-k from the buffer; add back ‖q‖² ------------
    # (always re-select: wide-width merges can leave dedup holes mid-buffer)
    if filter_bits is not None:
        allowed = Bitset(filter_bits, n_bits).test(buf_ids)
        buf_d = jnp.where(allowed, buf_d, inf)
    _, sel = lax.top_k(-buf_d, k)
    buf_d = jnp.take_along_axis(buf_d, sel, axis=1)
    buf_ids = jnp.take_along_axis(buf_ids, sel, axis=1)
    qn = jnp.sum(qf * qf, axis=1)
    out_d = buf_d[:, :k]
    out_ids = jnp.where(jnp.isinf(out_d), -1, buf_ids[:, :k])
    out_d = jnp.where(jnp.isinf(out_d), inf,
                      jnp.maximum(out_d + qn[:, None], 0.0))
    return out_d, out_ids


def _seed_compressed(dataset, proj, code_scale, centroids, reps, proj_energy,
                     qf, qp, key, itopk: int, n_rand: int, merge):
    """Seed the compressed-traversal buffer (shared by the unfused loop and
    the fused driver — one implementation so seeds stay bit-identical):
    centroid-guided when the payload carries a seeding table, random rows
    projected on the fly otherwise. Returns the merged (ids, d, vis)."""
    n, dim = dataset.shape
    p = proj.shape[1]
    q = qf.shape[0]
    inf = jnp.float32(jnp.inf)
    if centroids is not None:
        # guided: one (q, c) MXU gemm, zero gathers. Centroid distances
        # live in the FULL space; scale by the projection's captured
        # variance fraction (proj_energy: PCA's kept-eigenvalue share, or
        # p/dim for a legacy random subspace) and shift into the buffer's
        # code-unit convention (‖·‖² − 2⟨qp,·⟩ == (proj dist − ‖qp·s‖²)/s²)
        # so seed scores merge monotonically with code scores.
        c = centroids.shape[0]
        cd_full = (jnp.sum(centroids * centroids, axis=1)[None, :]
                   - 2.0 * qf @ centroids.T)  # + ‖q‖², constant, dropped
        n_seed = min(itopk, c)
        s2 = code_scale * code_scale
        qp_n = jnp.sum(qp * qp, axis=1)
        frac = (proj_energy if proj_energy is not None
                else jnp.float32(p / dim))
        cd_code = (cd_full * frac) / s2 + (
            jnp.sum(qf * qf, axis=1) * frac / s2 - qp_n)[:, None]
        sv, spos = iter_topk_min_packed(cd_code, n_seed)
        seed_ids = reps[spos].astype(jnp.int32)
        seed_d = sv
    else:
        # random seeding (num_random_samplings analog): gather raw rows,
        # project on the fly
        n_seed = min(itopk * n_rand, n)
        seed_ids = jax.random.randint(key, (q, n_seed), 0, n,
                                      dtype=jnp.int32)
        xv = dataset[jnp.maximum(seed_ids, 0)].astype(jnp.float32)
        xp = jnp.einsum("qmd,dp->qmp", xv, proj,
                        preferred_element_type=jnp.float32) / code_scale
        seed_d = jnp.sum(xp * xp, axis=2) - 2.0 * jnp.einsum(
            "qmp,qp->qm", xp, qp, preferred_element_type=jnp.float32)

    return merge(
        jnp.full((q, itopk), -1, jnp.int32),
        jnp.full((q, itopk), inf, jnp.float32),
        jnp.ones((q, itopk), jnp.bool_),
        seed_ids, seed_d,
    )


def _exact_rerank(dataset, qf, buf_ids, filter_bits, n_bits, k: int, rt: int):
    """Exact re-rank of the buffer head against the raw dataset — the
    CAGRA-Q refinement exit both compressed traversals share. The buffer is
    ascending post-merge, so its head IS the best ``rt`` candidates."""
    inf = jnp.float32(jnp.inf)
    r_ids = buf_ids[:, :rt]
    xv = dataset[jnp.maximum(r_ids, 0)].astype(jnp.float32)  # (q, rt, dim)
    ip = jnp.einsum("qmd,qd->qm", xv, qf, preferred_element_type=jnp.float32)
    d_exact = jnp.sum(xv * xv, axis=2) - 2.0 * ip
    d_exact = jnp.where(r_ids >= 0, d_exact, inf)
    if filter_bits is not None:
        allowed = Bitset(filter_bits, n_bits).test(r_ids)
        d_exact = jnp.where(allowed, d_exact, inf)
    out_d, sel = iter_topk_min(d_exact, k)
    out_ids = jnp.take_along_axis(r_ids, sel, axis=1)
    qn = jnp.sum(qf * qf, axis=1)
    out_ids = jnp.where(jnp.isinf(out_d), -1, out_ids)
    out_d = jnp.where(jnp.isinf(out_d), inf,
                      jnp.maximum(out_d + qn[:, None], 0.0))
    return out_d, out_ids


@functools.partial(
    jax.jit,
    static_argnames=("k", "itopk", "width", "max_iter", "min_iter", "n_rand",
                     "refine_topk"),
)
def _search_impl_compressed(
    dataset, graph, nbr_codes, proj, code_scale, centroids, reps,
    proj_energy, queries, key, filter_bits, n_bits,
    k, itopk, width, max_iter, min_iter, n_rand, refine_topk,
):
    """Round-5 traversal over inlined neighbor codes (module docstring).

    Cost shape per iteration at (q, w, deg, p): q·w graph-row gathers +
    q·w code-record gathers (the ONLY per-row-op-bound work — the exact
    loop paid q·w·deg), one (q, w·deg, p) int8→bf16 MXU contraction, a
    compare-matrix dedup, and a mantissa-packed itopk select over
    itopk + w·deg entries. Distances are projected-space ranking scores;
    the exit re-ranks the best ``refine_topk`` buffer entries exactly.
    """
    n, dim = dataset.shape
    q = queries.shape[0]
    deg = graph.shape[1]
    p = proj.shape[1]
    b = width * deg
    qf = queries.astype(jnp.float32)
    qp = (qf @ proj) / code_scale  # query in code units
    inf = jnp.float32(jnp.inf)
    iota_itopk = jnp.arange(itopk, dtype=jnp.int32)

    def code_dists(codes, ids):
        """(q, m) projected ranking scores ‖c‖² − 2⟨qp, c⟩ from int8 codes
        (query-norm term dropped: constant per query)."""
        cf = codes.astype(jnp.bfloat16)
        ip = jnp.einsum("qmp,qp->qm", cf, qp.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
        nrm = jnp.einsum("qmp,qmp->qm", cf, cf,
                         preferred_element_type=jnp.float32)
        return jnp.where(ids >= 0, nrm - 2.0 * ip, inf)

    def merge(bids, bd, bvis, cids, cd):
        # shared buffer∪candidate merge; mantissa-packed select.
        # _CAGRA_DEDUP_LIMIT (internal tuning knob): whether candidate
        # dedup pays the (q, b, b) compare tensor pre-select or the
        # slack + re-select path — the crossover is hardware-dependent
        return _merge_candidates(bids, bd, bvis, cids, cd, itopk,
                                 packed=True,
                                 dedup_limit=_CAGRA_DEDUP_LIMIT)

    # ---- seeds (shared with the fused driver) -----------------------------
    buf_ids, buf_d, buf_vis = _seed_compressed(
        dataset, proj, code_scale, centroids, reps, proj_energy,
        qf, qp, key, itopk, n_rand, merge)

    def cond(state):
        ids_b, _, vis, it = state
        frontier_open = jnp.any(~vis & (ids_b >= 0))
        return (it < max_iter) & (frontier_open | (it < min_iter))

    def body(state):
        ids_b, d_b, vis, it = state
        pkey = jnp.where(vis | (ids_b < 0), inf, d_b)
        pv, ppos = iter_topk_min_packed(pkey, width)
        parent_ids = jnp.take_along_axis(ids_b, ppos, axis=1)  # (q, w)
        parent_ok = ~jnp.isinf(pv)
        vis = vis | jnp.any(
            iota_itopk[None, None, :] == ppos[:, :, None], axis=1)
        pid_c = jnp.maximum(parent_ids, 0)
        gr = graph[pid_c]  # (q, w, deg) — q·w row gathers
        codes = nbr_codes[pid_c]  # (q, w, deg, p) — q·w record gathers
        nbrs = jnp.where(parent_ok[:, :, None] & (gr >= 0), gr, -1)
        nbrs = nbrs.reshape(q, b)
        nd = code_dists(codes.reshape(q, b, p), nbrs)
        ids2, d2, vis2 = merge(ids_b, d_b, vis, nbrs, nd)
        return ids2, d2, vis2, it + 1

    buf_ids, buf_d, _, _ = lax.while_loop(
        cond, body, (buf_ids, buf_d, buf_vis, jnp.int32(0))
    )

    # ---- exit: exact re-rank of the buffer head (shared with fused) -------
    return _exact_rerank(dataset, qf, buf_ids, filter_bits, n_bits, k,
                         refine_topk)



# ---------------------------------------------------------------------------
# Round-6 fused traversal: the compressed loop with its five per-hop ops
# (graph gather, code gather, int8 einsum, dedup, merge) collapsed into one
# Pallas kernel (ops/cagra_hop.py). The host drives hops in chunks so every
# dispatch carries a `cagra::hop` span + faultpoint, while termination stays
# on-device (each chunk is a lax.while_loop that no-ops once the frontier
# closes — no host sync in the hop loop).
# ---------------------------------------------------------------------------

# hops per chunk dispatch: large enough that chunk overhead amortizes, small
# enough that spans/deadline checkpoints see the traversal progressing
_CAGRA_HOP_CHUNK = int(_os.environ.get("RAFT_TPU_CAGRA_HOP_CHUNK", "8"))
# queries per kernel grid step (VMEM-bound: the (b, b) dedup compare and the
# (q_block·w, deg, p) code scratch scale with it)
_CAGRA_QBLOCK = int(_os.environ.get("RAFT_TPU_CAGRA_QBLOCK", "32"))
# parents ride the kernel's scalar-prefetch channel (SMEM): cap the query
# tile so the (q_tile, w) int32 table stays small
_FUSED_MAX_TILE = 4096


@functools.partial(jax.jit, static_argnames=("itopk", "n_rand"))
def _fused_init(dataset, proj, code_scale, centroids, reps, proj_energy,
                queries, key, itopk, n_rand):
    """Project queries into code units and seed the buffer — identical ops
    to the unfused loop's preamble (seeds shared via _seed_compressed), with
    the visited flags widened to fp32 for the kernel."""
    qf = queries.astype(jnp.float32)
    qp = (qf @ proj) / code_scale

    def merge(bids, bd, bvis, cids, cd):
        return _merge_candidates(bids, bd, bvis, cids, cd, itopk,
                                 packed=True,
                                 dedup_limit=_CAGRA_DEDUP_LIMIT)

    buf_ids, buf_d, buf_vis = _seed_compressed(
        dataset, proj, code_scale, centroids, reps, proj_energy,
        qf, qp, key, itopk, n_rand, merge)
    return buf_ids, buf_d, buf_vis.astype(jnp.float32), qp


@functools.partial(
    jax.jit,
    static_argnames=("itopk", "width", "min_iter", "q_block", "interpret"),
)
def _fused_hop_chunk(graph, nbr_codes, qp, buf_ids, buf_d, buf_vis, it,
                     budget, itopk, width, min_iter, q_block, interpret):
    """Up to ``budget - it`` fused hops in one dispatch. Parent pickup is
    the same packed top-width as the unfused body; everything after it —
    gathers, distances, dedup, merge — happens inside the fused_hop kernel.
    Once the frontier closes the while_loop exits immediately, so chunks
    dispatched after termination cost one condition evaluation."""
    inf = jnp.float32(jnp.inf)
    iota_itopk = jnp.arange(itopk, dtype=jnp.int32)

    def cond(state):
        ids_b, _, vis, i = state
        frontier_open = jnp.any((vis == 0) & (ids_b >= 0))
        return (i < budget) & (frontier_open | (i < min_iter))

    def body(state):
        ids_b, d_b, vis, i = state
        # pickup_next_parents: best `width` unvisited buffer entries
        pkey = jnp.where((vis > 0) | (ids_b < 0), inf, d_b)
        pv, ppos = iter_topk_min_packed(pkey, width)
        parent_ids = jnp.take_along_axis(ids_b, ppos, axis=1)  # (q, w)
        parents = jnp.where(jnp.isinf(pv), -1, parent_ids)
        picked = jnp.any(
            iota_itopk[None, None, :] == ppos[:, :, None], axis=1)
        vis = jnp.where(picked, jnp.float32(1.0), vis)
        ids2, d2, vis2 = fused_hop(
            ids_b, d_b, vis, parents, qp, graph, nbr_codes,
            q_block=q_block, interpret=interpret)
        return ids2, d2, vis2, i + 1

    return lax.while_loop(cond, body, (buf_ids, buf_d, buf_vis, it))


@functools.partial(jax.jit, static_argnames=("k", "rt"))
def _fused_finish(dataset, queries, buf_ids, filter_bits, n_bits, k, rt):
    qf = queries.astype(jnp.float32)
    return _exact_rerank(dataset, qf, buf_ids, filter_bits, n_bits, k, rt)


def _run_fused_tile(index: "CagraIndex", qs, key, fb, k, itopk, width,
                    max_iter, min_iter, n_rand, rt, q_block, interpret):
    """One query tile through the fused traversal: init → chunked hop
    dispatches (each with a `cagra::hop` span and an armable faultpoint at
    the host dispatch site) → exact exit re-rank. Returns (d, ids, hops)."""
    from raft_tpu.resilience import faultpoint

    buf_ids, buf_d, buf_vis, qp = _fused_init(
        index.dataset, index.proj, index.code_scale, index.centroids,
        index.centroid_reps, index.proj_energy, qs, key, itopk, n_rand)
    it = jnp.int32(0)
    for start in range(0, max_iter, _CAGRA_HOP_CHUNK):
        budget = min(start + _CAGRA_HOP_CHUNK, max_iter)
        faultpoint("cagra.search.hop")
        with obs.record_span("cagra::hop",
                             attrs={"budget": budget, "width": width}):
            buf_ids, buf_d, buf_vis, it = _fused_hop_chunk(
                index.graph, index.nbr_codes, qp, buf_ids, buf_d, buf_vis,
                it, jnp.int32(budget), itopk=itopk, width=width,
                min_iter=min_iter, q_block=q_block, interpret=interpret)
    out_d, out_ids = _fused_finish(
        index.dataset, qs, buf_ids, fb, index.size, int(k), rt)
    return out_d, out_ids, it


def _resolve_traversal(params: CagraSearchParams, has_payload: bool,
                       k: int, itopk: int, size: int = 0,
                       allow_fused: bool = True, b: int = 0):
    """Resolve the traversal mode + exact-re-rank depth once for every
    search wrapper (single-device and distributed share this — the two
    copies had already drifted, code-review r5). Returns
    ``(mode, refine_topk)`` with refine_topk = 0 for the exact loop.

    "auto" picks the fused Pallas loop when the codes are inlined and the
    backend compiles it (TPU); the compiled-interpret route stays available
    by asking for ``traversal="fused"`` explicitly (tests). Fused falls
    back to the unfused compressed loop when the caller can't host the
    kernel (``allow_fused=False`` — distributed shard bodies), the index
    exceeds the kernel's exact-id bound (MAX_FUSED_ROWS), or the candidate
    set ``b`` (width·degree) is past _CAGRA_DEDUP_LIMIT — there the
    unfused merge switches to its slack+re-select dedup, and fused
    results could no longer be bit-identical to it (which is both the
    parity contract and what makes the mid-batch fallback seamless).

    Parity scope: with a centroid seeding table (every index past the
    small-n threshold) fused per-query results are bit-identical to the
    unfused loop regardless of batch shape. Small centroid-less indexes
    seed by ``jax.random.randint`` at the (possibly q-block-padded) tile
    shape, so there parity additionally needs q to be a tile/block
    multiple — a different draw yields different (equally valid) seeds,
    not wrong results."""
    mode = params.traversal
    fused_capable = (has_payload and allow_fused
                     and 0 < size < MAX_FUSED_ROWS
                     and 0 < b <= _CAGRA_DEDUP_LIMIT)
    if mode == "auto":
        if has_payload:
            mode = ("fused" if fused_capable
                    and jax.default_backend() == "tpu" else "compressed")
        else:
            mode = "exact"
    elif mode in ("compressed", "fused") and not has_payload:
        raise ValueError(
            f"traversal={mode!r} needs the compression payload "
            "(build with CagraParams.compress)")
    if mode == "fused" and not fused_capable:
        mode = "compressed"
    rt = 0
    if mode in ("compressed", "fused"):
        rt = int(params.refine_topk) or itopk
        if not k <= rt <= itopk:
            raise ValueError(
                f"refine_topk={rt} must be in [k={k}, itopk={itopk}]")
    return mode, rt


@traced("cagra::search")
def search(
    index: CagraIndex,
    queries,
    k: int,
    params: CagraSearchParams = CagraSearchParams(),
    filter: Optional[Bitset] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Best-first graph search (cagra.cuh:299); returns (distances, indices).

    Graph traversal visits filtered-out nodes (they route) but never returns
    them (the reference applies its sample filter the same way). Internal
    buffer = itopk_size candidates per query; k must not exceed it.
    """
    res = res or current_resources()
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim})")
    itopk = int(min(params.itopk_size, index.size))
    if not 0 < k <= itopk:
        raise ValueError(f"k={k} must be in (0, itopk_size={itopk}]")
    if filter is not None and filter.n_bits != index.size:
        raise ValueError(
            f"filter covers {filter.n_bits} bits but index has {index.size} rows"
        )
    width = int(params.search_width)
    max_iter = int(params.max_iterations) or max(16, itopk // width)
    min_iter = int(min(params.min_iterations, max_iter))
    key = jax.random.key(params.seed)
    b = width * index.graph_degree
    mode, rt = _resolve_traversal(params, index.nbr_codes is not None,
                                  int(k), itopk, size=index.size, b=b)

    # query tiling: one traversal's live set is ~per_q bytes/query (the
    # (b, b) dedup compares + gathered codes/vectors + merge passes);
    # un-tiled q=10k runs RESOURCE_EXHAUST a 16 GB chip. Tiles dispatch
    # back-to-back (no host sync between them), so the loop costs no
    # dispatch-amortization at large q.
    p = index.proj.shape[1] if index.proj is not None else index.dim
    if mode == "fused":
        # the kernel block-streams the traversal state, so only the exit
        # re-rank gather and the per-query buffer/qp rows count against the
        # workspace — tiles grow ~10× vs the unfused loop and the q-block
        # grid keeps the MXU fed across the whole batch
        per_q = 6 * rt * index.dim + 24 * itopk + 4 * p + 8 * width
    elif mode == "compressed":
        per_q = b * b + 4 * b * p + 8 * (itopk + b) + 4 * itopk * index.dim
    else:
        per_q = b * b + 6 * b * index.dim + 8 * (itopk + b)
    nq = queries.shape[0]
    if nq == 0:
        return (jnp.zeros((0, k), jnp.float32), jnp.zeros((0, k), jnp.int32))
    q_tile = int(max(256, min(nq, res.workspace_bytes // max(per_q, 1))))
    if mode == "fused":
        # parents ride the kernel's SMEM scalar-prefetch channel: bound the
        # tile, then align it to the kernel's query-block grid (pad rows
        # traverse as zero-queries and are sliced off below)
        q_tile = min(q_tile, _FUSED_MAX_TILE)
    n_tiles = ceil_div(nq, q_tile)
    q_tile = ceil_div(nq, n_tiles)  # equalize; pad the tail tile below so
    # every dispatch shares ONE compiled shape
    q_block = 0
    if mode == "fused":
        q_block = int(max(8, min(_CAGRA_QBLOCK, q_tile)))
        q_tile = ceil_div(q_tile, q_block) * q_block

    if obs.enabled():
        obs.add("cagra.search.queries", nq)
        obs.add("cagra.search.tiles", n_tiles)
        obs.add("cagra.search.iterations", nq * max_iter)
        obs.add(f"cagra.search.traversal.{mode}", 1)
        if mode == "fused":
            # roofline note (round 15): the fused hop's static FLOP/byte
            # model + the q-block occupancy stats — the "does the kernel
            # underfill the MXU" number the ROADMAP has been guessing at
            from raft_tpu.obs import roofline as obs_roofline
            from raft_tpu.ops.cagra_hop import occupancy_stats

            obs_roofline.note_dispatch(
                "cagra.fused_hop",
                {"q": q_tile, "width": width,
                 "degree": index.graph_degree, "proj_dim": p,
                 "itopk": itopk, "hops": _CAGRA_HOP_CHUNK},
                occupancy=occupancy_stats(
                    min(nq, q_tile), q_block, width, index.graph_degree,
                    p, itopk))

    from raft_tpu import resilience
    from raft_tpu.core.interruptible import check_interrupt
    from raft_tpu.resilience import faultpoint

    faultpoint("cagra.search")
    fb = filter.bits if filter is not None else None
    n_rand = int(max(1, params.num_random_samplings))
    interpret = jax.default_backend() != "tpu"
    outs = []
    for ti, s in enumerate(range(0, nq, q_tile)):
        check_interrupt()  # tiles dispatch back-to-back; this is the only
        # host checkpoint a multi-tile search passes through
        qs = queries[s:s + q_tile]
        if qs.shape[0] < q_tile:
            qs = jnp.pad(qs, ((0, q_tile - qs.shape[0]), (0, 0)))
        tkey = jax.random.fold_in(key, ti) if ti else key
        if mode == "fused":
            try:
                od, oi, hops = _run_fused_tile(
                    index, qs, tkey, fb, int(k), itopk, width, max_iter,
                    min_iter, n_rand, rt, q_block, interpret)
                # int(hops) blocks on the tile's last chunk, so the count
                # is opt-in on top of telemetry: back-to-back QPS loops
                # stay pipelined, and the bench samples hops only inside
                # its per-batch latency pass (which forces every call
                # anyway)
                if obs.enabled() and _os.environ.get(
                        "RAFT_TPU_CAGRA_COUNT_HOPS"):
                    obs.add("cagra.search.hops", int(hops))
                outs.append((od, oi))
                continue
            except Exception as e:
                # classified fallback to the unfused compressed loop (the
                # round-7 recovery contract: a failed kernel dispatch —
                # injected or real, e.g. a Mosaic lowering gap on an
                # unusual shape — degrades to the slower traversal instead
                # of sinking the search)
                kind = resilience.classify(e)
                if kind == resilience.DEADLINE:
                    # expired scopes / cooperative cancels are NEVER
                    # retried (resilience contract): re-running the tile
                    # on the slower loop only digs the hole deeper
                    raise
                resilience.record_event(
                    "fused_fallback", site="cagra.search.hop", kind=kind,
                    error=repr(e)[:200])
                if obs.enabled():
                    obs.add(f"cagra.search.fused_fallback.{kind}")
                mode = "compressed"
        if mode == "compressed":
            outs.append(_search_impl_compressed(
                index.dataset, index.graph, index.nbr_codes, index.proj,
                index.code_scale, index.centroids, index.centroid_reps,
                index.proj_energy, qs, tkey, fb, index.size,
                int(k), itopk, width, max_iter, min_iter, n_rand, rt,
            ))
        else:
            outs.append(_search_impl(
                index.dataset, index.graph, qs, tkey, fb, index.size,
                int(k), itopk, width, max_iter, min_iter, n_rand,
            ))
    if len(outs) == 1:
        # the fused q-block alignment can pad even a single tile
        return outs[0][0][:nq], outs[0][1][:nq]
    return (jnp.concatenate([o[0] for o in outs], axis=0)[:nq],
            jnp.concatenate([o[1] for o in outs], axis=0)[:nq])
