"""CAGRA-class graph ANN index: NN-descent build, detour pruning, best-first search.

Reference: raft::neighbors::cagra — build (cagra.cuh:274 →
detail/cagra/cagra_build.cuh:296: kNN graph via IVF-PQ+refine or NN-descent,
then graph::optimize = detour-count pruning + reverse-edge add,
detail/cagra/graph_core.cuh:320, rev-graph kernel :191); search
(cagra.cuh:299 → detail/cagra/cagra_search.cuh:104, single-CTA persistent
best-first kernel detail/cagra/search_single_cta_kernel-inl.cuh:466 with
pickup_next_parents :51, bitonic top-k merge :405, visited hashmap
detail/cagra/hashmap.hpp). Params mirror cagra_types.hpp:55-134
(intermediate_graph_degree=128, graph_degree=64, itopk_size=64,
search_width=1, max/min_iterations, num_random_samplings).

TPU redesign (SURVEY.md §7 hard-part 2 — data-dependent traversal vs XLA
static shapes):

* **Build**: NN-descent (nn_descent.py) gives the intermediate graph with
  distances; pruning streams the detour-count computation as a
  ``lax.scan`` over rank positions (K² comparisons per node per step)
  instead of the GPU's per-edge bitwise kernel — everything static-shape.
* **Search**: a fixed-capacity itopk candidate buffer per query, advanced by
  a ``lax.while_loop``; each step expands the best ``search_width``
  unvisited entries, gathers their graph rows, computes distances with one
  batched einsum across the whole query batch (MXU-friendly: the per-query
  matvec becomes a (Q, w·deg, dim) batched contraction), and merges via
  sort-based dedup (``merge_topk_dedup``) — the hashmap+bitonic-sort
  replacement. Termination: all itopk entries visited, or max_iterations.
* The visited set is the buffer's per-slot flag (the single-CTA parent bit);
  a node evicted and later re-inserted may be re-expanded — a bounded waste
  the GPU hashmap avoids, accepted here to keep shapes static.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.bitset import Bitset
from raft_tpu.core.trace import traced
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.serialize import load_arrays, save_arrays
from raft_tpu.neighbors import nn_descent as nnd
from raft_tpu.ops.segment import merge_topk_dedup, segment_take
from raft_tpu.utils.tiling import ceil_div


@dataclass(frozen=True)
class CagraParams:
    """cagra::index_params analog (cagra_types.hpp:55-63)."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: str = "nn_descent"  # "nn_descent" | "brute" (exact, small n)
    nn_descent_niter: int = 20
    seed: int = 0

    def __post_init__(self):
        if self.graph_degree <= 0:
            raise ValueError("graph_degree must be positive")
        if self.intermediate_graph_degree < self.graph_degree:
            raise ValueError("intermediate_graph_degree < graph_degree")
        if self.build_algo not in ("nn_descent", "brute"):
            raise ValueError(f"unknown build_algo {self.build_algo!r}")


@dataclass(frozen=True)
class CagraSearchParams:
    """cagra::search_params analog (cagra_types.hpp:77-118)."""

    itopk_size: int = 64
    max_iterations: int = 0  # 0 = auto-sized from itopk/search_width
    min_iterations: int = 0
    search_width: int = 1
    num_random_samplings: int = 1
    seed: int = 0

    def __post_init__(self):
        if self.itopk_size <= 0 or self.search_width <= 0:
            raise ValueError("itopk_size and search_width must be positive")


@jax.tree_util.register_pytree_node_class
@dataclass
class CagraIndex:
    """Graph index: dataset + fixed-degree kNN graph (cagra_types.hpp:55-134)."""

    dataset: jax.Array  # (n, dim) fp32
    graph: jax.Array  # (n, graph_degree) int32 neighbor ids
    norms: jax.Array  # (n,) squared L2 norms

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    def tree_flatten(self):
        return (self.dataset, self.graph, self.norms), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- persistence (cagra_serialize.cuh analog) ---------------------------
    def save(self, path) -> None:
        save_arrays(
            path,
            {"kind": "cagra", "metric": "sqeuclidean"},
            {"dataset": self.dataset, "graph": self.graph, "norms": self.norms},
        )

    @classmethod
    def load(cls, path) -> "CagraIndex":
        meta, arrays = load_arrays(path)
        if meta.get("kind") != "cagra":
            raise ValueError(f"not a cagra index: {meta.get('kind')}")
        return cls(
            jnp.asarray(arrays["dataset"]),
            jnp.asarray(arrays["graph"]),
            jnp.asarray(arrays["norms"]),
        )


# ---------------------------------------------------------------------------
# Build: kNN graph + optimize (prune)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("out_degree", "n_blocks"))
def optimize(graph: jax.Array, out_degree: int, n_blocks: int = 1) -> jax.Array:
    """Prune an intermediate kNN graph to ``out_degree`` (graph::optimize,
    detail/cagra/graph_core.cuh:320).

    Two stages, mirroring the reference:

    1. **Detour-count pruning**: edge (s→t) at rank j is detourable through
       u at rank i<j when t appears in u's list at rank m<j (a 2-hop path of
       strictly better-ranked edges). Keep the ``out_degree`` edges with the
       fewest detours (rank as tie-break). Computed as a ``lax.scan`` over
       rank position j with K² membership tests per node — static shapes,
       streamed memory.
    2. **Reverse-edge add** (rev-graph kernel analog, graph_core.cuh:191):
       the final list interleaves the best half of the pruned forward edges
       with up to degree/2 reverse edges (dedup'd, forward edges fill any
       remainder) so that every node stays reachable.
    """
    n, K = graph.shape
    block = ceil_div(n, n_blocks)
    pad = n_blocks * block - n
    g_pad = jnp.pad(graph, ((0, pad), (0, 0)), constant_values=-1)

    def count_block(_, gb):
        # gb: (B, K) neighbor ids of this node block
        two_hop = graph[jnp.maximum(gb, 0)]  # (B, K, K): neighbors of neighbors

        def step(j, counts):
            t = gb[:, j]  # (B,) target id at rank j
            # membership of t among each better-ranked neighbor's prefix:
            # hit[b, i, m] = (two_hop[b, i, m] == t[b]) & (i < j) & (m < j)
            hit = two_hop == t[:, None, None]
            ii = jnp.arange(K)[None, :, None] < j
            mm = jnp.arange(K)[None, None, :] < j
            c = jnp.sum(hit & ii & mm, axis=(1, 2)).astype(jnp.int32)
            return counts.at[:, j].set(c)

        counts = lax.fori_loop(0, K, step, jnp.zeros(gb.shape, jnp.int32))
        return None, counts

    _, counts = lax.scan(
        count_block, None, g_pad.reshape(n_blocks, block, K)
    )
    counts = counts.reshape(-1, K)[:n]

    # keep out_degree edges with fewest detours (rank breaks ties);
    # invalid (-1) entries sort last
    rank = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, :], graph.shape)
    key = jnp.where(graph >= 0, counts * K + rank, jnp.iinfo(jnp.int32).max)
    order = jnp.argsort(key, axis=1)[:, :out_degree]
    fwd = jnp.take_along_axis(graph, order, axis=1)  # (n, out_degree)

    # reverse candidates of the pruned graph, capped at out_degree per node,
    # better-ranked sources first
    half = max(1, out_degree // 2)
    src = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None], fwd.shape
    ).reshape(-1)
    tgt = fwd.reshape(-1)
    rnk = jnp.broadcast_to(
        jnp.arange(out_degree, dtype=jnp.int32)[None, :], fwd.shape
    ).reshape(-1)
    keys = jnp.where(tgt >= 0, tgt, n).astype(jnp.int32)
    order = jnp.lexsort((rnk, keys))
    valid, rev = segment_take(keys[order], n, half, src[order])
    rev = jnp.where(valid, rev, -1)

    # interleave: forward first-half at priority 0..half-1, reverse at
    # half..half+half-1, forward second-half last; dedup by id keeps the
    # best priority
    prio_fwd = jnp.where(
        jnp.arange(out_degree)[None, :] < half,
        jnp.arange(out_degree, dtype=jnp.int32)[None, :],
        (jnp.arange(out_degree, dtype=jnp.int32) + 2 * half)[None, :],
    ).astype(jnp.float32)
    prio_fwd = jnp.broadcast_to(prio_fwd, fwd.shape)
    prio_fwd = jnp.where(fwd >= 0, prio_fwd, jnp.inf)
    prio_rev = jnp.broadcast_to(
        (jnp.arange(half, dtype=jnp.int32) + half)[None, :].astype(jnp.float32),
        rev.shape,
    )
    prio_rev = jnp.where(rev >= 0, prio_rev, jnp.inf)
    out_ids, _, _ = merge_topk_dedup(
        fwd, prio_fwd, rev, prio_rev, out_degree,
        exclude_self=jnp.arange(n, dtype=jnp.int32),
    )
    return out_ids


@traced("cagra::build")
def build(
    dataset,
    params: CagraParams = CagraParams(),
    res: Optional[Resources] = None,
) -> CagraIndex:
    """Build a CAGRA index (cagra.cuh:274 → cagra_build.cuh:296): kNN graph
    via NN-descent (or exact for small n), then optimize to graph_degree."""
    res = res or current_resources()
    X = jnp.asarray(dataset, jnp.float32)
    n, dim = X.shape
    ideg = int(min(params.intermediate_graph_degree, n - 1))
    deg = int(min(params.graph_degree, ideg))

    if params.build_algo == "brute" or n <= 2048:
        # exact graph for small datasets (the reference uses ivf_pq+refine;
        # at this scale one tiled exact pass is cheaper than training IVF)
        from raft_tpu.neighbors.brute_force import knn

        _, ids = knn(X, X, ideg + 1, metric="sqeuclidean", res=res)
        # drop self-matches (first column after exact sort)
        self_col = ids == jnp.arange(n, dtype=jnp.int32)[:, None]
        ids = jnp.where(self_col, -1, ids)
        order = jnp.argsort(jnp.where(ids < 0, 2, 0), axis=1, stable=True)[:, :ideg]
        graph = jnp.take_along_axis(ids, order, axis=1)
    else:
        graph = nnd.build(
            X,
            nnd.NNDescentParams(
                graph_degree=ideg,
                intermediate_graph_degree=min(int(1.5 * ideg), n - 1),
                max_iterations=params.nn_descent_niter,
                seed=params.seed,
            ),
            res=res,
        )

    # detour-prune in blocks bounded by workspace: scan materializes
    # (block, K, K) two-hop ids (int32)
    per_node = ideg * ideg * 4 * 2
    block = max(128, int(res.workspace_bytes // max(per_node, 1) // 2))
    n_blocks = max(1, ceil_div(n, block))
    pruned = optimize(graph, deg, n_blocks=n_blocks)
    norms = jnp.sum(X * X, axis=1)
    return CagraIndex(X, pruned, norms)


def build_from_graph(dataset, graph) -> CagraIndex:
    """Wrap a prebuilt kNN graph (the from-serialized / interop path)."""
    X = jnp.asarray(dataset, jnp.float32)
    return CagraIndex(X, jnp.asarray(graph, jnp.int32), jnp.sum(X * X, axis=1))


# ---------------------------------------------------------------------------
# Search
# ---------------------------------------------------------------------------

@functools.partial(
    jax.jit,
    static_argnames=("k", "itopk", "width", "max_iter", "min_iter", "n_rand"),
)
def _search_impl(
    dataset, norms, graph, queries, key, filter_bits, n_bits,
    k, itopk, width, max_iter, min_iter, n_rand,
):
    n, dim = dataset.shape
    q = queries.shape[0]
    deg = graph.shape[1]
    qn = jnp.sum(queries * queries, axis=1)  # (q,)
    inf = jnp.float32(jnp.inf)

    def batch_dists(ids):
        """(q, m) distances of each query to dataset[ids] (q, m)."""
        xv = dataset[jnp.maximum(ids, 0)]  # (q, m, dim)
        ip = jnp.einsum("qmd,qd->qm", xv, queries)
        d = qn[:, None] + norms[jnp.maximum(ids, 0)] - 2.0 * ip
        return jnp.where(ids >= 0, jnp.maximum(d, 0.0), inf)

    # ---- init: random seeds (num_random_samplings analog) -----------------
    n_seed = min(itopk * n_rand, n)
    seed_ids = jax.random.randint(key, (q, n_seed), 0, n, dtype=jnp.int32)
    seed_d = batch_dists(seed_ids)
    buf_ids, buf_d, _, buf_vis = merge_topk_dedup(
        jnp.full((q, itopk), -1, jnp.int32),
        jnp.full((q, itopk), inf, jnp.float32),
        seed_ids,
        seed_d,
        itopk,
        payload=jnp.ones((q, itopk), jnp.bool_),
        cand_payload=jnp.zeros(seed_ids.shape, jnp.bool_),
    )

    def cond(state):
        ids_b, _, vis, it = state
        frontier_open = jnp.any(~vis & (ids_b >= 0))
        return (it < max_iter) & (frontier_open | (it < min_iter))

    def body(state):
        ids_b, d_b, vis, it = state
        # pickup_next_parents (:51): best `width` unvisited buffer entries
        pkey = jnp.where(vis | (ids_b < 0), inf, d_b)
        _, ppos = lax.top_k(-pkey, width)  # positions of best unvisited
        parent_ids = jnp.take_along_axis(ids_b, ppos, axis=1)  # (q, w)
        parent_ok = jnp.take_along_axis(pkey, ppos, axis=1) < inf
        # mark them visited
        vis = vis | jnp.zeros_like(vis).at[
            jnp.arange(q)[:, None], ppos
        ].set(True)
        # expand: gather graph rows → (q, w*deg) candidates
        nbrs = graph[jnp.maximum(parent_ids, 0)].reshape(q, width * deg)
        nbrs = jnp.where(
            (parent_ok[:, :, None] & (graph[jnp.maximum(parent_ids, 0)] >= 0)).reshape(
                q, width * deg
            ),
            nbrs,
            -1,
        )
        nd = batch_dists(nbrs)
        ids2, d2, _, vis2 = merge_topk_dedup(
            ids_b, d_b, nbrs, nd, itopk,
            payload=vis, cand_payload=jnp.zeros(nbrs.shape, jnp.bool_),
        )
        return ids2, d2, vis2, it + 1

    buf_ids, buf_d, _, _ = lax.while_loop(
        cond, body, (buf_ids, buf_d, buf_vis, jnp.int32(0))
    )

    # ---- output: filter + top-k from the buffer ---------------------------
    if filter_bits is not None:
        allowed = Bitset(filter_bits, n_bits).test(buf_ids)
        buf_d = jnp.where(allowed, buf_d, inf)
        order = jnp.argsort(buf_d, axis=1)
        buf_d = jnp.take_along_axis(buf_d, order, axis=1)
        buf_ids = jnp.take_along_axis(buf_ids, order, axis=1)
    out_d = buf_d[:, :k]
    out_ids = jnp.where(jnp.isinf(out_d), -1, buf_ids[:, :k])
    return out_d, out_ids


@traced("cagra::search")
def search(
    index: CagraIndex,
    queries,
    k: int,
    params: CagraSearchParams = CagraSearchParams(),
    filter: Optional[Bitset] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Best-first graph search (cagra.cuh:299); returns (distances, indices).

    Graph traversal visits filtered-out nodes (they route) but never returns
    them (the reference applies its sample filter the same way). Internal
    buffer = itopk_size candidates per query; k must not exceed it.
    """
    res = res or current_resources()
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim})")
    itopk = int(min(params.itopk_size, index.size))
    if not 0 < k <= itopk:
        raise ValueError(f"k={k} must be in (0, itopk_size={itopk}]")
    if filter is not None and filter.n_bits != index.size:
        raise ValueError(
            f"filter covers {filter.n_bits} bits but index has {index.size} rows"
        )
    width = int(params.search_width)
    max_iter = int(params.max_iterations) or max(16, itopk // width)
    min_iter = int(min(params.min_iterations, max_iter))
    key = jax.random.key(params.seed)
    return _search_impl(
        index.dataset,
        index.norms,
        index.graph,
        queries,
        key,
        filter.bits if filter is not None else None,
        index.size,
        int(k),
        itopk,
        width,
        max_iter,
        min_iter,
        int(max(1, params.num_random_samplings)),
    )
