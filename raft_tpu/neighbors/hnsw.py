"""CAGRA → HNSW export + CPU-side search (reference neighbors/hnsw.hpp,
hnsw_types.hpp:41, writer detail/cagra/cagra_serialize.cuh
serialize_to_hnswlib).

``save_to_hnswlib`` writes the base-layer-only hnswlib
``HierarchicalNSW<float>`` binary layout the reference emits — with one
deliberate deviation: ``max_level`` is 0, not 1, so the file loads in STOCK
hnswlib (the reference's 1 requires its patched ``base_layer_only`` loader;
0 works in both). The interop story: build on TPU, serve anywhere. The writer is native C++ (raft_tpu/native/hnsw_writer.cpp,
like the reference's) with a pure-Python fallback.

``HnswIndex`` is a self-contained reader + greedy base-layer search — the
in-repo stand-in for hnswlib's search (hnswlib is not a dependency), and
the round-trip oracle for the writer.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

_HEADER = struct.Struct("<QQQQQQiiQQQdQ")


def save_to_hnswlib(index, path) -> None:
    """Write a CagraIndex as a base-layer-only hnswlib index file
    (cagra_serialize.cuh serialize_to_hnswlib byte layout: header, then per
    element [links_count u32 | graph row u32s | vector f32s | label u64],
    then a zero u32 per element for the absent upper levels)."""
    graph = np.ascontiguousarray(np.asarray(index.graph), dtype=np.uint32)
    data = np.ascontiguousarray(np.asarray(index.dataset), dtype=np.float32)
    n, degree = graph.shape
    dim = data.shape[1]
    if data.shape[0] != n:
        raise ValueError(f"graph rows {n} != dataset rows {data.shape[0]}")
    entry = n // 2  # the reference picks size/2 as the entrypoint

    from raft_tpu.core.fsio import atomic_replace, atomic_write
    from raft_tpu.native import get_native_lib

    lib = get_native_lib()
    path = str(path)
    if lib is not None:
        import ctypes

        def produce(tmp_path):
            # native writer owns the file; atomic_replace renames the
            # completed tmp onto the target so a crash never leaves a
            # torn export
            rc = lib.raft_tpu_write_hnsw(
                tmp_path.encode(), n, dim, degree,
                graph.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                entry,
            )
            if rc != 0:
                raise OSError(
                    f"native hnsw writer failed with code {rc} for {path}")

        atomic_replace(path, produce)
        return

    # pure-Python fallback: identical bytes (atomic, same contract)
    size_per_el = degree * 4 + 4 + dim * 4 + 8
    with atomic_write(path) as f:
        f.write(_HEADER.pack(0, n, n, size_per_el, size_per_el - 8,
                             degree * 4 + 4, 0, entry, degree // 2, degree,
                             degree // 2, 0.42424242, 500))
        lab = np.empty(1, np.uint64)
        deg = np.full(1, degree, np.int32)
        for i in range(n):
            deg.tofile(f)
            graph[i].tofile(f)
            data[i].tofile(f)
            lab[0] = i
            lab.tofile(f)
        np.zeros(n, np.int32).tofile(f)


@dataclass
class HnswIndex:
    """Parsed base-layer-only hnswlib index (hnsw_types.hpp index analog)."""

    graph: np.ndarray    # (n, degree) uint32
    dataset: np.ndarray  # (n, dim) float32
    labels: np.ndarray   # (n,) uint64
    entrypoint: int

    @classmethod
    def load(cls, path, dim: int) -> "HnswIndex":
        """Parse an hnswlib file of known ``dim`` (hnswlib's loader also
        needs the space dim up front).

        The hnswlib layout carries no magic, so the header is validated
        structurally BEFORE any parse (ISSUE 7 satellite): a wrong-kind or
        corrupt file fails with a classified ``ValueError`` naming what is
        wrong, like the other index loaders — not a downstream reshape or
        view error."""
        with open(path, "rb") as f:
            head = f.read(_HEADER.size)
            if head[:8] == b"RAFTTPU\x00":
                raise ValueError(
                    f"{path} is a raft_tpu container, not an hnswlib "
                    f"index — load it with the matching Index.load()")
            if len(head) < _HEADER.size:
                raise ValueError(
                    f"not an hnswlib index: {path} holds {len(head)} bytes, "
                    f"shorter than the {_HEADER.size}-byte header")
            hdr = _HEADER.unpack(head)
            (_, max_el, n, size_per_el, label_off, offset_data, max_level,
             entry, _, max_m0, _, _, _) = hdr
            degree = (offset_data - 4) // 4
            if not (0 < n <= max_el) or degree <= 0 or \
                    offset_data != degree * 4 + 4 or \
                    label_off != size_per_el - 8 or not 0 <= entry < n:
                raise ValueError(
                    f"not a CAGRA-exported hnswlib index: header invariants "
                    f"violated (n={n}, max_el={max_el}, degree={degree}, "
                    f"offset_data={offset_data}, label_off={label_off}, "
                    f"size_per_el={size_per_el}, entry={entry}) in {path}")
            if size_per_el != degree * 4 + 4 + dim * 4 + 8:
                raise ValueError(
                    f"dim {dim} inconsistent with element size {size_per_el}")
            raw = np.fromfile(f, np.uint8, n * size_per_el)
            if raw.size < n * size_per_el:
                raise ValueError(
                    f"truncated hnswlib index: {path} holds {raw.size} of "
                    f"{n * size_per_el} element bytes — partial write")
        el = raw.reshape(n, size_per_el)
        counts = el[:, :4].view(np.int32)[:, 0]
        graph = np.ascontiguousarray(el[:, 4:offset_data]).view(np.uint32).reshape(n, degree)
        dat = np.ascontiguousarray(el[:, offset_data:label_off]).view(np.float32).reshape(n, dim)
        labels = np.ascontiguousarray(el[:, label_off:]).view(np.uint64)[:, 0]
        if not (counts == degree).all():
            raise ValueError("variable link counts: not a CAGRA-exported index")
        return cls(graph, dat, labels, int(entry))

    def knn(self, queries, k: int, ef: int = 64, n_iters: int | None = None):
        """Greedy best-first base-layer search (hnswlib searchBaseLayerST
        equivalent, numpy host implementation). Terminates like hnswlib —
        candidate heap empty or its best exceeds the ef-th result;
        ``n_iters`` optionally caps expansions (None = uncapped).
        Returns (distances (q, k), labels (q, k))."""
        q = np.asarray(queries, np.float32)
        n, degree = self.graph.shape
        ef = max(ef, k)
        if n_iters is None:
            n_iters = n  # hard safety bound only; termination is heap-driven
        out_d = np.empty((q.shape[0], k), np.float32)
        out_i = np.empty((q.shape[0], k), np.int64)
        for r in range(q.shape[0]):
            qv = q[r]
            visited = {self.entrypoint}
            cand = [(float(((self.dataset[self.entrypoint] - qv) ** 2).sum()),
                     self.entrypoint)]
            best = list(cand)
            for _ in range(n_iters):
                cand.sort()
                if not cand:
                    break
                d0, u = cand.pop(0)
                worst = max(best)[0] if len(best) >= ef else np.inf
                if d0 > worst:
                    break
                nbrs = [v for v in self.graph[u] if v not in visited]
                visited.update(int(v) for v in nbrs)
                if nbrs:
                    dv = ((self.dataset[nbrs] - qv) ** 2).sum(axis=1)
                    for dd, v in zip(dv, nbrs):
                        if len(best) < ef or dd < max(best)[0]:
                            best.append((float(dd), int(v)))
                            cand.append((float(dd), int(v)))
                            if len(best) > ef:
                                best.remove(max(best))
            best.sort()
            top = best[:k]
            while len(top) < k:
                top.append((np.inf, -1))
            out_d[r] = [t[0] for t in top]
            out_i[r] = [int(self.labels[t[1]]) if t[1] >= 0 else -1 for t in top]
        return out_d, out_i
