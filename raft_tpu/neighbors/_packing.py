"""Shared padded-list packing for IVF indexes.

The TPU replacement for the reference's variable-length interleaved list
containers (ivf_list.hpp, kIndexGroupSize grouping ivf_flat_types.hpp:47):
rows are scattered into one dense (n_lists, max_list_size, ...) block, with
``list_ids == -1`` marking padding. Used by ivf_flat (raw vectors) and
ivf_pq (codes); both build and extend flows.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

# nearest-alternative rounds the spill runs before its pressure valve
# (round 3: one alternative was not enough — see _spill_core)
_N_ALT = 4

#: ledger entries backing the paged-scan (re)trace count — each
#: `_paged_impl` (ivf_flat, ivf_pq, future paged backends) records a
#: ledger trace_event at TRACE time only, so a delta across a serving
#: window counts recompiles (the zero-recompile upsert contract asserted
#: in tier-1/bench/smoke) AND names the operand whose shape caused each
#: one (obs/compile.py — the round-11 replacement for the ad-hoc
#: PAGED_TRACES counter dict)
PAGED_ENTRIES = ("ivf_flat.paged_scan", "ivf_pq.paged_scan",
                 "ivf_flat.paged_pallas", "ivf_pq.paged_pallas",
                 "ivf_bq.paged_pallas")


def paged_trace_count() -> int:
    """Total (re)traces of the paged scan programs in this process — a
    thin shim over the compile ledger (public name and delta semantics
    unchanged from the PAGED_TRACES era)."""
    from raft_tpu.obs import compile as obs_compile

    return sum(obs_compile.trace_count(e) for e in PAGED_ENTRIES)


def round_list_size(max_count: int, group_size: int,
                    pow2_chunks: bool = False) -> int:
    """THE padded-list-size formula: max cluster size rounded up to
    ``group_size``, and — under ``pow2_chunks`` (the strip backend's
    block-divisibility requirement) — to a power-of-two number of
    group_size chunks. One copy: :func:`pack_lists`, the streamed builds'
    pre-sized donated blocks, the distributed common-mls computation
    (_sharding.round_mls) and the bench's share restatement must all
    agree EXACTLY or scattered rows overwrite/drop and byte predictions
    drift."""
    mls = max(group_size, -(-int(max_count) // group_size) * group_size)
    if pow2_chunks:
        chunks = mls // group_size
        mls = group_size * (1 << (chunks - 1).bit_length())
    return mls


def pack_lists(payload, row_ids, labels, n_lists: int, group_size: int,
               pow2_chunks: bool = False) -> Tuple:
    """Scatter rows into padded per-list blocks.

    payload: (n, ...) per-row data; row_ids: (n,) source ids; labels: (n,)
    list assignment. max_list_size = max cluster size rounded up to
    ``group_size``. With ``pow2_chunks``, it is further rounded to a
    power-of-two number of group_size chunks — the strip-scan TPU backend's
    block divisibility requirement (ops/strip_scan.py; ≤ 2× padding, in
    practice ~1.1× because the auto list cap is itself 4×mean ≈ pow2).
    Returns (list_payload, list_ids).
    """
    n = payload.shape[0]
    sizes = jnp.bincount(labels, length=n_lists)
    max_size = round_list_size(int(jnp.max(sizes)), group_size,
                               pow2_chunks)

    order = jnp.argsort(labels)
    sorted_labels = labels[order]
    offsets = jnp.cumsum(sizes) - sizes
    pos = jnp.arange(n, dtype=jnp.int32) - offsets[sorted_labels].astype(jnp.int32)

    list_payload = jnp.zeros((n_lists, max_size) + payload.shape[1:], payload.dtype)
    list_ids = jnp.full((n_lists, max_size), -1, jnp.int32)
    list_payload = list_payload.at[sorted_labels, pos].set(payload[order])
    list_ids = list_ids.at[sorted_labels, pos].set(row_ids[order].astype(jnp.int32))
    return list_payload, list_ids


def spill_to_cap(work, centers, labels, metric: str, cap: int,
                 base_counts=None, chunk: int = 65536):
    """Cap per-list occupancy by spilling overflow rows to their
    second-nearest center.

    The reference bounds list growth through its list containers and the
    balancing passes (cluster/detail/kmeans_balanced.cuh adjust_centers);
    with padded dense blocks a single runaway cluster would inflate the
    whole (n_lists, max_list_size, ·) allocation AND every scan's chunk
    count, so a hard cap matters more here. Rows ranked >= cap within their
    cluster first bid for their nearest alternative centers with room; any
    residue is then packed into free slots across all lists (emptiest
    first), so the cap is HARD whenever total capacity covers the rows
    (n_lists·cap >= n — true for every auto cap). With insufficient total
    capacity the unplaceable overflow keeps its original label. Recall
    impact of the nearest-alternative rounds is bounded (a spilled row is
    found whenever its second-best list is probed, n_probes >> 1 in
    practice); the final packing trades that locality for the memory bound
    on the residue only.

    Shapes are data-independent (second-nearest is computed for every row
    in static tiles): one extra assignment-scale pass, but the compiled
    programs are reused across builds — round-3 finding: data-dependent
    shapes here caused fresh ~10 s XLA compiles on every build.
    """
    n_lists = centers.shape[0]
    # base_counts: occupancy already committed to each list (extend() spills
    # only the new rows on top of the existing fill)
    base = (jnp.zeros(n_lists, jnp.int32) if base_counts is None
            else jnp.asarray(base_counts, jnp.int32))
    counts = jnp.bincount(labels, length=n_lists)
    if int(jnp.max(counts + base)) <= cap:
        return labels
    out, n_residue = _spill_core(work, centers, labels, metric, cap, base,
                                 counts, chunk)
    n_res = int(n_residue)
    if n_res > 0:
        # ADVICE r4: the pressure valve places these rows irrespective of
        # distance — essentially never probed for nearby queries. Surface
        # the recall tradeoff at build time instead of hiding it in a
        # comment.
        from raft_tpu.core.logger import get_logger

        get_logger().warning(
            "spill_to_cap: %d row(s) exhausted all %d nearest alternative "
            "lists and were packed into distant free slots (emptiest "
            "first); these rows are unlikely to be probed by nearby "
            "queries. Consider raising list_cap_factor or n_lists for "
            "this data distribution.", n_res, min(_N_ALT, n_lists - 1))
    return out


def _spill_core(work, centers, labels, metric, cap, base, counts, chunk):
    """Jittable spill body (no host syncs) — usable inside shard_map
    (distributed builds spill each shard in-SPMD). Returns
    ``(labels_out, n_residue)`` where n_residue counts rows the pressure
    valve placed non-locally (distance-blind) — callers on the host path
    surface it as a warning."""
    n = labels.shape[0]
    n_lists = centers.shape[0]
    # rank of each row within its cluster (arrival order, after the base)
    order = jnp.argsort(labels)
    offsets = jnp.cumsum(counts) - counts
    rank_sorted = jnp.arange(n, dtype=jnp.int32) - offsets[labels[order]].astype(jnp.int32)
    rank = jnp.zeros(n, jnp.int32).at[order].set(rank_sorted)
    over = base[labels] + rank >= cap

    # 4 nearest alternative centers for every row, in static-shape tiles
    # (round-3: one alternative was not enough — a mega-cluster's neighbors
    # fill up and the remainder stayed put, inflating max_list_size 2×)
    from raft_tpu.ops import distance as dist_mod
    from raft_tpu.ops.select_k import select_k

    n_alt = min(_N_ALT, n_lists - 1)
    if n_alt <= 0:
        # a single list has nowhere to spill (tuple contract as below)
        return labels, jnp.int32(0)
    alts = []
    for s in range(0, n, chunk):
        w = work[s:s + chunk]
        lb = labels[s:s + chunk]
        if metric == "inner_product":
            d = -dist_mod.matmul_t(w, centers, jnp.bfloat16)
        else:
            d = dist_mod._expanded_distance(w, centers, "sqeuclidean",
                                            jnp.bfloat16, None)
        d = d.at[jnp.arange(w.shape[0]), lb].set(jnp.inf)
        _, a = select_k(d, n_alt, select_min=True)
        alts.append(a)
    alt = jnp.concatenate(alts) if len(alts) > 1 else alts[0]  # (n, n_alt)

    # sequential admission over alternative ranks: each round, rows still
    # overflowing bid for their next-nearest list; a target only accepts up
    # to its remaining capacity (conservative: capacity freed by rows that
    # spill OUT of a list in the same round is not reused)
    free = jnp.maximum(cap - (base + counts), 0)
    labels_out = labels
    remaining = over

    def admit(labels_out, remaining, free, targets):
        target = jnp.where(remaining, targets, n_lists)
        s_order = jnp.argsort(target)
        t_sorted = target[s_order]
        t_counts = jnp.bincount(t_sorted, length=n_lists + 1)
        t_off = jnp.cumsum(t_counts) - t_counts
        rank_sorted = (jnp.arange(n, dtype=jnp.int32)
                       - t_off[t_sorted].astype(jnp.int32))
        t_rank = jnp.zeros(n, jnp.int32).at[s_order].set(rank_sorted)
        admitted = remaining \
            & (t_rank < free[jnp.clip(target, 0, n_lists - 1)]) \
            & (target < n_lists)
        labels_out = jnp.where(admitted, targets, labels_out)
        free = free - jnp.bincount(jnp.where(admitted, targets, n_lists),
                                   length=n_lists + 1)[:n_lists]
        return labels_out, remaining & ~admitted, free

    for r in range(n_alt):
        labels_out, remaining, free = admit(labels_out, remaining, free,
                                            alt[:, r])
    # pressure valve (round-4): a Zipf mega-cluster can exhaust all n_alt
    # NEAREST alternatives and leave the cap soft — at 10M rows a handful
    # of stragglers pow2-inflated every padded array 4×. Remaining rows
    # are packed into free slots across ALL lists, emptiest first: row
    # rank t among the remainder goes to the list owning the t-th free
    # slot (searchsorted over the cumulative free-capacity profile). This
    # makes the cap HARD whenever total capacity covers the rows
    # (n_lists·cap ≥ n + base — true for every auto cap, which is ≥ 1.5×
    # mean occupancy). NOTE the weaker placement property: unlike the
    # nearest-alternative rounds, the receiving list may be far from the
    # row, making those few rows unlikely to be probed — the price of the
    # memory bound, paid only by the residue the local rounds could not
    # place (ranking candidate lists by distance per row would restore
    # locality if it ever matters).
    order_lists = jnp.argsort(-free)                    # emptiest first
    cumfree = jnp.cumsum(free[order_lists])
    t_rank = jnp.cumsum(remaining.astype(jnp.int32)) - 1
    slot = jnp.searchsorted(cumfree, t_rank, side="right")
    ok = remaining & (t_rank < cumfree[-1]) & (slot < n_lists)
    labels_out = jnp.where(
        ok, order_lists[jnp.clip(slot, 0, n_lists - 1)], labels_out)
    return labels_out, jnp.sum(ok.astype(jnp.int32))


def auto_group_size(n: int, n_lists: int, floor: int = 64) -> int:
    """512 (== strip_scan.MC, enables the strip TPU backend) when the mean
    list is big enough that the padding is noise; else ``floor`` so small
    indexes stay small. ivf_pq passes floor=128: its Pallas LUT backend
    requires 128-aligned max_list_size (ops/pq_scan.py), and a 64 granule can
    produce odd multiples of 64 (ADVICE.md round-2 high finding)."""
    return 512 if n // max(n_lists, 1) >= 192 else floor


def auto_list_cap(n: int, n_lists: int, group_size: int, factor: int = 4) -> int:
    """Default cap: ``factor`` × mean occupancy, group-aligned."""
    mean = -(-n // n_lists)
    return max(group_size, -(-(factor * mean) // group_size) * group_size)


def unpack_lists(list_payload, list_ids) -> Tuple:
    """Inverse of pack_lists: recover the valid (payload, ids, labels) rows
    (used by extend to repack with additions)."""
    n_lists, max_size = list_ids.shape
    valid = list_ids.reshape(-1) >= 0
    payload = list_payload.reshape((-1,) + list_payload.shape[2:])[valid]
    ids = list_ids.reshape(-1)[valid]
    labels = jnp.repeat(jnp.arange(n_lists, dtype=jnp.int32), max_size)[valid]
    return payload, ids, labels


# ---------------------------------------------------------------------------
# Streamed-build helpers (promoted from ivf_pq round 17 so the ivf_bq
# streamed build shares ONE copy of the offset/rank/diversion math — the
# scatter position arithmetic and the capacity check must agree exactly or
# rows overwrite/drop)
# ---------------------------------------------------------------------------


def chunk_ranks(labels, n_lists: int):
    """Chunk-local arrival rank of each row within its label, in
    label-sorted order: returns ``(order, sorted_labels, rank_sorted)``.
    The ONE definition shared by the streamed-build scatter position math
    and the capacity diversion's fill check. Sentinel labels (== n_lists)
    sort last and rank within the sentinel bucket."""
    m = labels.shape[0]
    order = jnp.argsort(labels)
    sorted_labels = labels[order]
    counts = jnp.bincount(labels, length=n_lists + 1)[:n_lists]
    offsets = jnp.cumsum(counts) - counts
    safe_sl = jnp.minimum(sorted_labels, n_lists - 1)
    rank_sorted = (jnp.arange(m, dtype=jnp.int32)
                   - offsets[safe_sl].astype(jnp.int32))
    return order, sorted_labels, rank_sorted


@functools.partial(jax.jit, static_argnames=("block", "metric"))
def assign_top2(rows, centers, block: int = 4096,
                metric: str = "sqeuclidean"):
    """Best and second-best center per row, tiled over center blocks
    (fused_l2_nn_argmin gives only the argmin; the streamed builds'
    capacity diversion needs the runner-up as the spill target — the
    one-pass analog of :func:`spill_to_cap`'s first alternative round).
    ``metric`` matches kmeans_balanced._assign: "sqeuclidean" ranks by
    expanded L2, "inner_product" by −⟨row, center⟩."""
    m, dim = rows.shape
    n_c = centers.shape[0]
    nb = -(-n_c // block)
    cpad = jnp.pad(centers, ((0, nb * block - n_c), (0, 0)))
    cn = jnp.sum(cpad * cpad, axis=1)
    cn = jnp.where(jnp.arange(nb * block) < n_c, cn, jnp.inf)

    def step(carry, bi):
        v1, i1, v2, i2 = carry
        cb = lax.dynamic_slice_in_dim(cpad, bi * block, block, axis=0)
        bn = lax.dynamic_slice_in_dim(cn, bi * block, block, axis=0)
        ip = jnp.einsum("md,cd->mc", rows, cb,
                        preferred_element_type=jnp.float32)
        d = -ip if metric == "inner_product" else bn[None, :] - 2.0 * ip
        d = jnp.where(jnp.isinf(bn)[None, :], jnp.inf, d)
        bv1 = jnp.min(d, axis=1)
        ba1 = jnp.argmin(d, axis=1).astype(jnp.int32) + bi * block
        d2 = jnp.where(jnp.arange(block)[None, :]
                       == (ba1 - bi * block)[:, None], jnp.inf, d)
        bv2 = jnp.min(d2, axis=1)
        ba2 = jnp.argmin(d2, axis=1).astype(jnp.int32) + bi * block
        # merge two sorted pairs -> global best two
        cand_v = jnp.stack([v1, v2, bv1, bv2], axis=1)
        cand_i = jnp.stack([i1, i2, ba1, ba2], axis=1)
        nv1 = jnp.min(cand_v, axis=1)
        na1 = jnp.argmin(cand_v, axis=1)
        ni1 = jnp.take_along_axis(cand_i, na1[:, None], axis=1)[:, 0]
        cv2 = jnp.where(jnp.arange(4)[None, :] == na1[:, None],
                        jnp.inf, cand_v)
        na2 = jnp.argmin(cv2, axis=1)
        nv2 = jnp.take_along_axis(cv2, na2[:, None], axis=1)[:, 0]
        ni2 = jnp.take_along_axis(cand_i, na2[:, None], axis=1)[:, 0]
        return (nv1, ni1, nv2, ni2), None

    init = (jnp.full((m,), jnp.inf), jnp.zeros((m,), jnp.int32),
            jnp.full((m,), jnp.inf), jnp.zeros((m,), jnp.int32))
    (v1, i1, v2, i2), _ = lax.scan(step, init,
                                   jnp.arange(nb, dtype=jnp.int32))
    return i1, i2


@functools.partial(jax.jit, static_argnames=("n_lists",))
def divert_to_cap(l1, l2, run_counts, cap, n_lists):
    """Capacity diversion for one streamed chunk: rows whose nearest list
    is full (given the running fill) take their second-nearest; rows whose
    second choice is also full get the drop sentinel ``n_lists``. Ranks are
    chunk-local arrival order, matching the scatter's position math."""
    m = l1.shape[0]

    def rank_of(lab):
        order, _, rank_sorted = chunk_ranks(lab, n_lists)
        return jnp.zeros(m, jnp.int32).at[order].set(rank_sorted)

    full1 = run_counts[l1] + rank_of(l1) >= cap
    lab = jnp.where(full1, l2, l1)
    # re-rank under the diverted labels; overflow past cap drops
    full2 = run_counts[jnp.minimum(lab, n_lists - 1)] + rank_of(lab) >= cap
    return jnp.where(full2, n_lists, lab).astype(jnp.int32)
