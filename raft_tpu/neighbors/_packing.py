"""Shared padded-list packing for IVF indexes.

The TPU replacement for the reference's variable-length interleaved list
containers (ivf_list.hpp, kIndexGroupSize grouping ivf_flat_types.hpp:47):
rows are scattered into one dense (n_lists, max_list_size, ...) block, with
``list_ids == -1`` marking padding. Used by ivf_flat (raw vectors) and
ivf_pq (codes); both build and extend flows.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def pack_lists(payload, row_ids, labels, n_lists: int, group_size: int) -> Tuple:
    """Scatter rows into padded per-list blocks.

    payload: (n, ...) per-row data; row_ids: (n,) source ids; labels: (n,)
    list assignment. max_list_size = max cluster size rounded up to
    ``group_size``. Returns (list_payload, list_ids).
    """
    n = payload.shape[0]
    sizes = jnp.bincount(labels, length=n_lists)
    max_size = int(jnp.max(sizes))
    max_size = max(group_size, -(-max_size // group_size) * group_size)

    order = jnp.argsort(labels)
    sorted_labels = labels[order]
    offsets = jnp.cumsum(sizes) - sizes
    pos = jnp.arange(n, dtype=jnp.int32) - offsets[sorted_labels].astype(jnp.int32)

    list_payload = jnp.zeros((n_lists, max_size) + payload.shape[1:], payload.dtype)
    list_ids = jnp.full((n_lists, max_size), -1, jnp.int32)
    list_payload = list_payload.at[sorted_labels, pos].set(payload[order])
    list_ids = list_ids.at[sorted_labels, pos].set(row_ids[order].astype(jnp.int32))
    return list_payload, list_ids


def spill_to_cap(work, centers, labels, metric: str, cap: int,
                 base_counts=None, chunk: int = 65536):
    """Cap per-list occupancy by spilling overflow rows to their
    second-nearest center.

    The reference bounds list growth through its list containers and the
    balancing passes (cluster/detail/kmeans_balanced.cuh adjust_centers);
    with padded dense blocks a single runaway cluster would inflate the
    whole (n_lists, max_list_size, ·) allocation AND every scan's chunk
    count, so a hard cap matters more here. Rows ranked >= cap within their
    cluster move to their second-nearest center when that list has room
    (pre-spill occupancy — a one-level, best-effort spill: a second list
    that also overflows keeps the row, so the cap is soft). Recall impact is
    bounded: a spilled row is found whenever its second-best list is probed,
    and n_probes >> 1 in practice.
    """
    n = labels.shape[0]
    n_lists = centers.shape[0]
    # base_counts: occupancy already committed to each list (extend() spills
    # only the new rows on top of the existing fill)
    base = (jnp.zeros(n_lists, jnp.int32) if base_counts is None
            else jnp.asarray(base_counts, jnp.int32))
    counts = jnp.bincount(labels, length=n_lists)
    if int(jnp.max(counts + base)) <= cap:
        return labels

    # rank of each row within its cluster (arrival order, after the base)
    order = jnp.argsort(labels)
    offsets = jnp.cumsum(counts) - counts
    rank_sorted = jnp.arange(n, dtype=jnp.int32) - offsets[labels[order]].astype(jnp.int32)
    rank = jnp.zeros(n, jnp.int32).at[order].set(rank_sorted)
    over = base[labels] + rank >= cap

    # second-nearest center — computed only for overflow rows (build is
    # eager, so the data-dependent row subset is a host-side gather), in
    # chunks so the (n_over, n_lists) block stays bounded
    from raft_tpu.ops import distance as dist_mod
    import numpy as np

    over_rows = np.where(np.asarray(over))[0]
    work_o = work[jnp.asarray(over_rows)]
    labels_o = labels[jnp.asarray(over_rows)]
    second = []
    for s in range(0, over_rows.shape[0], chunk):
        w = work_o[s:s + chunk]
        if metric == "inner_product":
            d = -dist_mod.matmul_t(w, centers, None, "highest")
        else:
            d = dist_mod._expanded_distance(w, centers, "sqeuclidean", None, "highest")
        d = d.at[jnp.arange(w.shape[0]), labels_o[s:s + chunk]].set(jnp.inf)
        second.append(jnp.argmin(d, axis=1).astype(jnp.int32))
    second_o = jnp.concatenate(second) if second else jnp.zeros(0, jnp.int32)
    labels2 = jnp.array(labels).at[jnp.asarray(over_rows)].set(second_o)

    # admission control per target: spills ranked within each target list
    # only fill its *remaining* capacity, so concurrent spills from several
    # overflowing lists cannot pile one target above the cap
    spill_target = jnp.where(over, labels2, n_lists)  # n_lists = not spilling
    s_order = jnp.argsort(spill_target)
    t_sorted = spill_target[s_order]
    t_counts = jnp.bincount(t_sorted, length=n_lists + 1)
    t_off = jnp.cumsum(t_counts) - t_counts
    spill_rank_sorted = jnp.arange(n, dtype=jnp.int32) - t_off[t_sorted].astype(jnp.int32)
    spill_rank = jnp.zeros(n, jnp.int32).at[s_order].set(spill_rank_sorted)
    admitted = over & (base[labels2] + counts[labels2] + spill_rank < cap)
    return jnp.where(admitted, labels2, labels)


def auto_group_size(n: int, n_lists: int) -> int:
    """512 (== ragged_scan.MC, enables the ragged TPU backend) when the mean
    list is big enough that the padding is noise; else 64 so small indexes
    stay small (the dense scan path doesn't care about 512-alignment)."""
    return 512 if n // max(n_lists, 1) >= 192 else 64


def auto_list_cap(n: int, n_lists: int, group_size: int, factor: int = 4) -> int:
    """Default cap: ``factor`` × mean occupancy, group-aligned."""
    mean = -(-n // n_lists)
    return max(group_size, -(-(factor * mean) // group_size) * group_size)


def unpack_lists(list_payload, list_ids) -> Tuple:
    """Inverse of pack_lists: recover the valid (payload, ids, labels) rows
    (used by extend to repack with additions)."""
    n_lists, max_size = list_ids.shape
    valid = list_ids.reshape(-1) >= 0
    payload = list_payload.reshape((-1,) + list_payload.shape[2:])[valid]
    ids = list_ids.reshape(-1)[valid]
    labels = jnp.repeat(jnp.arange(n_lists, dtype=jnp.int32), max_size)[valid]
    return payload, ids, labels
