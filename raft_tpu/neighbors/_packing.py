"""Shared padded-list packing for IVF indexes.

The TPU replacement for the reference's variable-length interleaved list
containers (ivf_list.hpp, kIndexGroupSize grouping ivf_flat_types.hpp:47):
rows are scattered into one dense (n_lists, max_list_size, ...) block, with
``list_ids == -1`` marking padding. Used by ivf_flat (raw vectors) and
ivf_pq (codes); both build and extend flows.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def pack_lists(payload, row_ids, labels, n_lists: int, group_size: int) -> Tuple:
    """Scatter rows into padded per-list blocks.

    payload: (n, ...) per-row data; row_ids: (n,) source ids; labels: (n,)
    list assignment. max_list_size = max cluster size rounded up to
    ``group_size``. Returns (list_payload, list_ids).
    """
    n = payload.shape[0]
    sizes = jnp.bincount(labels, length=n_lists)
    max_size = int(jnp.max(sizes))
    max_size = max(group_size, -(-max_size // group_size) * group_size)

    order = jnp.argsort(labels)
    sorted_labels = labels[order]
    offsets = jnp.cumsum(sizes) - sizes
    pos = jnp.arange(n, dtype=jnp.int32) - offsets[sorted_labels].astype(jnp.int32)

    list_payload = jnp.zeros((n_lists, max_size) + payload.shape[1:], payload.dtype)
    list_ids = jnp.full((n_lists, max_size), -1, jnp.int32)
    list_payload = list_payload.at[sorted_labels, pos].set(payload[order])
    list_ids = list_ids.at[sorted_labels, pos].set(row_ids[order].astype(jnp.int32))
    return list_payload, list_ids


def unpack_lists(list_payload, list_ids) -> Tuple:
    """Inverse of pack_lists: recover the valid (payload, ids, labels) rows
    (used by extend to repack with additions)."""
    n_lists, max_size = list_ids.shape
    valid = list_ids.reshape(-1) >= 0
    payload = list_payload.reshape((-1,) + list_payload.shape[2:])[valid]
    ids = list_ids.reshape(-1)[valid]
    labels = jnp.repeat(jnp.arange(n_lists, dtype=jnp.int32), max_size)[valid]
    return payload, ids, labels
