"""Shared filter plumbing for the IVF families.

One module owns the two facts every family used to restate locally:

- **The filter→bias rule** (:func:`apply_filter_bias`): a filtered-out row
  is a ``+inf`` bias lane — the tombstone mechanism generalized. The bias
  operand already rides every scan engine (packed strip, BQ, paged), so a
  predicate needs no new kernel path: it is masked in VMEM before ranking,
  and the kernels skip fully-dead sub-blocks (see
  ``ops/strip_scan.py``'s ``sub_live`` operand). Out-of-range ids fail the
  test (``Bitset.test``), so rows minted after the mask was built are
  excluded rather than served unfiltered.

- **The selectivity→widening rule** (:func:`widen_plan`): a scan at 1%
  selectivity probes the same lists as the unfiltered scan but 99% of
  their rows are masked, so k survivors only come back if the plan
  over-probes. The widening factor is ``min(1/pass_rate,
  RAFT_TPU_FILTER_MAX_WIDEN)``, applied to ``n_probes`` (every family) and
  to refine-style over-fetch ``k_fetch`` (ivf_bq/ivf_pq re-rank rungs).
  ``Bitset.pass_rate()`` is a host float cached per bitset instance, so
  the plan costs one device sync per distinct filter object, not per
  query batch.

Families must not re-implement either rule (the three pre-round-19 copies
in ivf_flat/ivf_pq/ivf_bq had already drifted in id-clamp handling).
"""

from __future__ import annotations

import math
import os
from typing import Optional, Tuple

import jax.numpy as jnp

FILTER_MAX_WIDEN_ENV = "RAFT_TPU_FILTER_MAX_WIDEN"


def default_filter_max_widen() -> float:
    """Cap on the selectivity widening factor (``RAFT_TPU_FILTER_MAX_WIDEN``,
    default 8): a 1/256 pass rate still only widens ``n_probes``/``k_fetch``
    by this much — past it, recall is bought with a larger mask-aware
    over-fetch at the caller, not an unbounded probe sweep."""
    return float(os.environ.get(FILTER_MAX_WIDEN_ENV, "8"))


def apply_filter_bias(bias, ids, filter):
    """Fold ``filter`` into a scan bias: ``+inf`` where the row id fails.

    ``bias`` is the engine's per-entry additive fp32 bias (already ``+inf``
    at padding/tombstones); ``ids`` the matching source-row ids (``-1`` at
    padding). Ids are clamped to 0 for the gather — a clamped padding slot
    may *pass* the test, but its bias is already ``+inf`` and ``where``
    keeps it, so padding stays dead either way. No-op when ``filter`` is
    None.
    """
    if filter is None:
        return bias
    return jnp.where(filter.test(jnp.maximum(ids, 0)), bias, jnp.inf)


def widen_plan(
    filter,
    n_probes: int,
    n_lists: int,
    k_fetch: Optional[int] = None,
    k_cap: Optional[int] = None,
    max_widen: Optional[float] = None,
) -> Tuple[int, Optional[int], float, float]:
    """Selectivity-aware plan widening.

    Returns ``(n_probes_eff, k_fetch_eff, pass_rate, widen)``. With no
    filter this is the identity (``pass_rate=1, widen=1``). Otherwise the
    widening factor is ``min(1/pass_rate, max_widen)`` (knob default:
    :func:`default_filter_max_widen`); ``n_probes`` is scaled and clamped
    to ``n_lists``, and ``k_fetch`` (when given — the refine rungs'
    over-fetch) is scaled and clamped to ``k_cap``. Callers stamp
    ``pass_rate``/``widen`` on their search span and pass the *effective*
    values to ``obs_roofline.note_dispatch`` so predicted-vs-measured
    stays exact.
    """
    if filter is None:
        return int(n_probes), k_fetch, 1.0, 1.0
    rate = float(filter.pass_rate())
    cap = default_filter_max_widen() if max_widen is None else float(max_widen)
    widen = min(max(cap, 1.0), 1.0 / max(rate, 1e-9))
    widen = max(widen, 1.0)
    n_probes_eff = int(min(n_lists, math.ceil(n_probes * widen)))
    k_fetch_eff = k_fetch
    if k_fetch is not None:
        k_fetch_eff = int(math.ceil(k_fetch * widen))
        if k_cap is not None:
            k_fetch_eff = min(int(k_cap), k_fetch_eff)
        k_fetch_eff = max(int(k_fetch), k_fetch_eff)
    return n_probes_eff, k_fetch_eff, rate, widen
