"""NN-descent ("GNND") — the all-neighbors kNN-graph builder CAGRA uses.

Reference: raft::neighbors::experimental::nn_descent
(nn_descent.cuh:59 build; detail/nn_descent.cuh:342 GNND class, :1191
local_join, :1215 host-buffered sample/update loop), itself the GPU
formulation of Wang et al., "Fast k-NN Graph Construction by GPU based
NN-Descent" (CIKM'21). Parameters mirror nn_descent_types.hpp:49-54
(graph_degree / intermediate_graph_degree / max_iterations /
termination_threshold).

TPU design — no atomics, no per-thread queues; everything is batched sort /
gather / matmul:

* The graph state is three dense (n, K) arrays (ids / dists / is_new) —
  K = intermediate_graph_degree, rows sorted by distance.
* Per iteration, each node samples up to S "new" and S "old" neighbors from
  its forward list and up to S from the reverse adjacency of those samples
  (the reference's in/out sampling, detail/nn_descent.cuh:1215).
* The local join materializes each node's sampled union U (4S ids), gathers
  their vectors and computes the (4S, 4S) pair distances with ONE batched
  einsum per node block — the MXU replacement for the warp-tiled join
  (detail/nn_descent.cuh:1191).
* Candidate edges (new x new, new x old, both directions) are distributed to
  their target nodes by sort + ``segment_take`` (the scatter-free analog of
  atomic list appends) and merged with ``merge_topk_dedup`` (sort-based
  bitonic-merge/dedup replacement).
* The whole iteration is one jitted program; the host loop only reads the
  scalar update counter for the termination test (termination_threshold) and
  the interruptible cancellation point.

**Status on the TPU runtime (round-4 decision, VERDICT r3 #8):** this
host-driven loop is CPU-capable but NOT the production TPU graph builder —
its per-iteration dispatch pattern measured impractical on the tunneled
runtime and its large sort/gather working set can fault the TPU worker at
bench scale (round 3). The production CAGRA builder on TPU is the IVF
candidate search + device-resident neighbor-of-neighbor sweeps
(cagra._build_knn_ivf_pq + cagra.refine_knn_graph — the latter IS the
NN-descent local join recast as fixed-shape device blocks). This module
remains for CPU builds and API parity with
raft::neighbors::experimental::nn_descent.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.interruptible import check_interrupt
from raft_tpu.core.logger import get_logger
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.ops.segment import merge_topk_dedup, segment_take
from raft_tpu.utils.tiling import ceil_div

_log = get_logger()


@dataclass(frozen=True)
class NNDescentParams:
    """Mirror of nn_descent::index_params (nn_descent_types.hpp:49-54)."""

    graph_degree: int = 64
    intermediate_graph_degree: int = 128
    max_iterations: int = 20
    termination_threshold: float = 1e-4
    # GNND's per-node sample size (the segment-size analog); join cost per
    # node scales with ~6*sample_size^2 edges.
    sample_size: int = 16
    seed: int = 0

    def __post_init__(self):
        if self.graph_degree <= 0 or self.intermediate_graph_degree < self.graph_degree:
            raise ValueError(
                "need 0 < graph_degree <= intermediate_graph_degree "
                f"(got {self.graph_degree}, {self.intermediate_graph_degree})"
            )
        if self.sample_size <= 0:
            raise ValueError("sample_size must be positive")


def _pair_indices(S2: int, S4: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Static (a, b) index pairs into the per-node union U of size S4
    (first S2 entries are NEW, rest OLD): new x new unordered pairs plus the
    full new x old grid — the GNND join rule (new entries must meet
    everything, old x old pairs were already joined)."""
    import numpy as np

    pa, pb = [], []
    for i in range(S2):
        for j in range(i + 1, S2):  # new x new
            pa.append(i)
            pb.append(j)
        for j in range(S2, S4):  # new x old
            pa.append(i)
            pb.append(j)
    return jnp.asarray(np.array(pa, np.int32)), jnp.asarray(np.array(pb, np.int32))


def _sample(key, ids, flags, S, want_new):
    """Sample up to S per-row ids where flag==want_new; returns (n,S) ids
    (-1 padded) and the source positions (n,S) (for demotion)."""
    n, K = ids.shape
    eligible = (flags == want_new) & (ids >= 0)
    r = jax.random.uniform(key, (n, K))
    # eligible entries first (key 0), random order among them
    order = jnp.argsort(jnp.where(eligible, r, 2.0 + r), axis=1)[:, :S]
    picked = jnp.take_along_axis(eligible, order, axis=1)
    out = jnp.where(picked, jnp.take_along_axis(ids, order, axis=1), -1)
    return out, jnp.where(picked, order, -1)


def _reverse_sample(key, sample_ids, n, S):
    """Up to S reverse-adjacency sources per node from a forward sample:
    edge (i -> sample_ids[i, j]) contributes source i to node
    sample_ids[i, j]'s reverse list (random subset per node, like the
    reference's reverse-graph sampling)."""
    ns, w = sample_ids.shape
    src = jnp.broadcast_to(jnp.arange(ns, dtype=jnp.int32)[:, None], (ns, w)).reshape(-1)
    tgt = sample_ids.reshape(-1)
    keys = jnp.where(tgt >= 0, tgt, n).astype(jnp.int32)
    # randomize within each target's span so the cap keeps a random subset
    r = jax.random.uniform(key, keys.shape)
    order = jnp.lexsort((r, keys))
    valid, rsrc = segment_take(keys[order], n, S, src[order])
    return jnp.where(valid, rsrc, -1)


def _init_state(key, X, norms, K, block_rows):
    """Random initial graph: K distinct-ish random neighbors per node."""
    n = X.shape[0]
    ids = jax.random.randint(key, (n, K), 0, n, dtype=jnp.int32)
    # self-edges shifted off; duplicate ids resolved by the first merge pass
    ids = jnp.where(ids == jnp.arange(n, dtype=jnp.int32)[:, None], (ids + 1) % n, ids)
    dists = _block_pair_dists(X, norms, ids, block_rows)
    # dedup via a merge against an empty candidate set
    empty_ids = jnp.full((n, 1), -1, jnp.int32)
    empty_d = jnp.full((n, 1), jnp.inf, jnp.float32)
    ids, dists, _, flags = merge_topk_dedup(
        ids,
        dists,
        empty_ids,
        empty_d,
        K,
        exclude_self=jnp.arange(n, dtype=jnp.int32),
        payload=jnp.ones((n, K), jnp.bool_),
        cand_payload=jnp.zeros((n, 1), jnp.bool_),
    )
    return ids, dists, flags


def _block_pair_dists(X, norms, ids, block_rows):
    """d2(i, ids[i, :]) computed in row blocks (memory-bounded gather)."""
    n, K = ids.shape
    nb = ceil_div(n, block_rows)
    pad = nb * block_rows - n
    ids_p = jnp.pad(ids, ((0, pad), (0, 0))).reshape(nb, block_rows, K)
    rows_p = jnp.pad(jnp.arange(n, dtype=jnp.int32), (0, pad)).reshape(nb, block_rows)

    def step(_, inp):
        bids, brows = inp
        xb = X[brows]  # (B, dim)
        xn = X[jnp.maximum(bids, 0)]  # (B, K, dim)
        ip = jnp.einsum("bd,bkd->bk", xb, xn)
        d = norms[brows][:, None] + norms[jnp.maximum(bids, 0)] - 2.0 * ip
        return None, jnp.maximum(d, 0.0)

    _, d = lax.scan(step, None, (ids_p, rows_p))
    d = d.reshape(nb * block_rows, K)[:n]
    return jnp.where(ids >= 0, d, jnp.inf)


@functools.partial(
    jax.jit, static_argnames=("K", "S", "n_blocks", "cand_cap")
)
def _iteration(X, norms, ids, dists, is_new, key, K, S, n_blocks, cand_cap):
    """One NN-descent round; returns (ids, dists, is_new, n_updates)."""
    n = X.shape[0]
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)

    fwd_new, new_pos = _sample(k1, ids, is_new, S, want_new=True)
    fwd_old, _ = _sample(k2, ids, is_new, S, want_new=False)
    rev_new = _reverse_sample(k3, fwd_new, n, S)
    rev_old = _reverse_sample(k4, fwd_old, n, S)
    # demote sampled new entries (they join this round; GNND flag flip);
    # mode="drop" discards the -1 (not sampled) positions
    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], new_pos.shape)
    is_new = is_new.at[rows, new_pos].set(False, mode="drop")

    NEW = jnp.concatenate([fwd_new, rev_new], axis=1)  # (n, 2S)
    OLD = jnp.concatenate([fwd_old, rev_old], axis=1)
    U = jnp.concatenate([NEW, OLD], axis=1)  # (n, 4S)
    S2, S4 = 2 * S, 4 * S
    pa, pb = _pair_indices(S2, S4)

    block = ceil_div(n, n_blocks)
    pad = n_blocks * block - n
    U_p = jnp.pad(U, ((0, pad), (0, 0)), constant_values=-1).reshape(
        n_blocks, block, S4
    )

    def join_block(carry, Ub):
        ids_c, dists_c, flags_c, updates = carry
        Us = jnp.maximum(Ub, 0)
        xu = X[Us]  # (B, 4S, dim)
        nu = norms[Us]  # (B, 4S)
        ip = jnp.einsum("bsd,btd->bst", xu, xu)
        D = jnp.maximum(nu[:, :, None] + nu[:, None, :] - 2.0 * ip, 0.0)
        a = Ub[:, pa]  # (B, P)
        b = Ub[:, pb]
        d = D[:, pa, pb]
        ok = (a >= 0) & (b >= 0) & (a != b)
        # both directions, flattened
        src = jnp.concatenate([a, b], axis=1).reshape(-1)
        tgt = jnp.concatenate([b, a], axis=1).reshape(-1)
        dd = jnp.concatenate([d, d], axis=1).reshape(-1)
        okk = jnp.concatenate([ok, ok], axis=1).reshape(-1)
        keys = jnp.where(okk, tgt, n).astype(jnp.int32)
        order = jnp.lexsort((dd, keys))
        valid, csrc, cd = segment_take(keys[order], n, cand_cap, src[order], dd[order])
        cand_ids = jnp.where(valid, csrc, -1)
        cand_d = jnp.where(valid, cd, jnp.inf)
        ids2, dists2, from_cand, flags2 = merge_topk_dedup(
            ids_c,
            dists_c,
            cand_ids,
            cand_d,
            K,
            exclude_self=jnp.arange(n, dtype=jnp.int32),
            payload=flags_c,
            cand_payload=jnp.ones(cand_ids.shape, jnp.bool_),
        )
        return (ids2, dists2, flags2, updates + jnp.sum(from_cand)), None

    (ids, dists, is_new, updates), _ = lax.scan(
        join_block, (ids, dists, is_new, jnp.int32(0)), U_p
    )
    return ids, dists, is_new, updates


@traced("nn_descent::build")
def build(
    dataset,
    params: NNDescentParams = NNDescentParams(),
    res: Optional[Resources] = None,
    return_distances: bool = False,
):
    """Build the (n, graph_degree) approximate kNN graph (nn_descent.cuh:59).

    L2 (sqeuclidean) metric, matching the reference builder. Returns int32
    neighbor ids sorted by distance (and the distances when requested).
    """
    res = res or current_resources()
    X = jnp.asarray(dataset, jnp.float32)
    n, dim = X.shape
    if n < 2:
        raise ValueError(f"need at least 2 rows, got {n}")
    K = int(min(params.intermediate_graph_degree, n - 1))
    deg = int(min(params.graph_degree, K))
    S = int(min(params.sample_size, K))
    norms = jnp.sum(X * X, axis=1)

    # memory budget: the join materializes ~(block, 4S, dim) gathers and
    # ~12*S^2*block edge triples; bound both by workspace_bytes
    per_node = 4 * S * dim * 4 + 12 * S * S * 12
    block = max(256, int(res.workspace_bytes // max(per_node, 1) // 4))
    n_blocks = max(1, ceil_div(n, block))
    cand_cap = 2 * S

    key = jax.random.key(params.seed)
    kinit, key = jax.random.split(key)
    ids, dists, is_new = _init_state(kinit, X, norms, K, block_rows=4096)

    threshold = params.termination_threshold * n * K
    from raft_tpu.resilience import active_deadline

    for it in range(params.max_iterations):
        # deadline checkpoint (ISSUE 3): descent is anytime — every round
        # only improves the graph — so an expiring budget returns the
        # current graph marked degraded instead of dying to the watchdog
        dl = active_deadline()
        if dl is not None and it > 0 and dl.reached():
            dl.mark_degraded("nn_descent.build")
            break
        check_interrupt()
        kit, key = jax.random.split(key)
        ids, dists, is_new, updates = _iteration(
            X, norms, ids, dists, is_new, kit, K, S, n_blocks, cand_cap
        )
        n_updates = int(updates)
        _log.debug("nn_descent iter %d: %d updates", it, n_updates)
        if n_updates <= threshold:
            break

    if return_distances:
        return ids[:, :deg], dists[:, :deg]
    return ids[:, :deg]
