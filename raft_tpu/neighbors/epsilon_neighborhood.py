"""Dense epsilon-neighborhood (reference neighbors/epsilon_neighborhood.cuh:
eps_neighbors_l2sq — boolean adjacency + per-row degree within radius).

One tiled pairwise-distance pass with a fused comparison; the reference's
custom kernel exists to avoid materializing distances, which XLA's fusion
handles for free here (the bool matrix is the output either way).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.ops import distance as dist_mod


def eps_neighbors(
    x,
    y,
    eps: float,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(adjacency (m, n) bool, degree (m,) int32) of pairs with
    ‖x_i − y_j‖² ≤ eps² (eps_neighbors_l2sq analog — eps is the L2 radius,
    squared internally like the reference)."""
    res = res or current_resources()
    if eps <= 0:
        raise ValueError(f"eps must be positive, got {eps}")
    d2 = dist_mod.pairwise_distance(x, y, "sqeuclidean", res=res)
    adj = d2 <= jnp.float32(eps) ** 2
    return adj, jnp.sum(adj.astype(jnp.int32), axis=1)
