"""Brute-force (exact) k-nearest-neighbor search, tiled for out-of-core scale.

Reference: raft::neighbors::brute_force (brute_force-inl.cuh:157 knn, :337
build, :417 search) and the tiled engine tiled_brute_force_knn
(neighbors/detail/knn_brute_force.cuh:61): pick a memory-bounded tile, compute
pairwise distances per tile, select_k per tile, then merge partial results
(knn_merge_parts.cuh:140).

TPU design: the dataset is reshaped into static tiles and the whole
tile-scan-merge loop is a single `lax.scan` under jit — XLA pipelines the gemm
of tile i+1 against the top-k merge of tile i (the stream-overlap analog).
Distances ride the MXU via the expanded forms; dataset norms are precomputed at
build time (brute_force_types.hpp:50 stores norms for the same reason).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.obs import compile as obs_compile
from raft_tpu.obs import roofline as obs_roofline
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.serialize import load_arrays, save_arrays
from raft_tpu.core.trace import traced
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops.select_k import select_k
from raft_tpu.utils.tiling import ceil_div, pad_and_tile

# Metrics where larger is better (search selects max instead of min).
_MAX_METRICS = frozenset({"inner_product"})


@jax.tree_util.register_pytree_node_class
@dataclass
class BruteForceIndex:
    """Exact-search index: the dataset plus precomputed row norms
    (brute_force_types.hpp:50 analog)."""

    dataset: jax.Array  # (n, dim)
    norms: Optional[jax.Array]  # (n,) L2^2 norms, only for expanded metrics
    metric: str
    metric_arg: float = 2.0

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    def tree_flatten(self):
        return (self.dataset, self.norms), (self.metric, self.metric_arg)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    # -- persistence (brute_force_serialize.cuh analog) --------------------
    def save(self, path) -> None:
        arrays = {"dataset": self.dataset}
        if self.norms is not None:
            arrays["norms"] = self.norms
        save_arrays(
            path,
            {"kind": "brute_force", "metric": self.metric, "metric_arg": self.metric_arg},
            arrays,
        )

    @classmethod
    def load(cls, path) -> "BruteForceIndex":
        meta, arrays = load_arrays(path)
        if meta.get("kind") != "brute_force":
            raise ValueError(f"not a brute_force index: {meta.get('kind')}")
        norms = jnp.asarray(arrays["norms"]) if "norms" in arrays else None
        return cls(jnp.asarray(arrays["dataset"]), norms, meta["metric"], meta.get("metric_arg", 2.0))


@traced("brute_force::build")
def build(dataset, metric: str = "sqeuclidean", metric_arg: float = 2.0,
          res: Optional[Resources] = None) -> BruteForceIndex:
    """Build = store dataset + precompute norms (brute_force-inl.cuh:337)."""
    del res
    metric = dist_mod.canonical_metric(metric)
    dataset = jnp.asarray(dataset)
    norms = None
    if metric in ("sqeuclidean", "euclidean", "cosine"):
        norms = dist_mod.sqnorm(dataset)
    return BruteForceIndex(dataset, norms, metric, metric_arg)


def _tile_distances(queries, qn, tile, tile_norms, metric, metric_arg, compute_dtype, precision=None):
    """Distances of all queries against one dataset tile, reusing precomputed
    query norms ``qn`` (hoisted out of the tile scan) and tile norms."""
    if metric in ("sqeuclidean", "euclidean"):
        ip = dist_mod.matmul_t(queries, tile, compute_dtype, precision)
        d = jnp.maximum(qn[:, None] + tile_norms[None, :] - 2.0 * ip, 0.0)
        return jnp.sqrt(d) if metric == "euclidean" else d
    if metric == "cosine":
        ip = dist_mod.matmul_t(queries, tile, compute_dtype, precision)
        tn = jnp.sqrt(tile_norms)
        return 1.0 - ip / jnp.maximum(jnp.sqrt(qn)[:, None] * tn[None, :], 1e-30)
    if metric == "inner_product":
        return dist_mod.matmul_t(queries, tile, compute_dtype, precision)
    if metric in dist_mod.EXPANDED_METRICS:
        return dist_mod._expanded_distance(queries, tile, metric, compute_dtype, precision)
    if metric == "haversine":
        return dist_mod.haversine(queries, tile)
    return dist_mod._elementwise_tile(queries, tile, metric, metric_arg)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "metric_arg", "tile_rows", "select_algo", "compute_dtype"),
)
def _search_impl(queries, dataset, norms, filter, k, metric, metric_arg,
                 tile_rows, select_algo, compute_dtype):
    # compile-ledger registration: runs at trace time only (obs/compile.py)
    obs_compile.trace_event(
        "brute_force.search", queries=queries, dataset=dataset, norms=norms,
        filter=filter,
        static={"k": k, "metric": metric, "metric_arg": metric_arg,
                "tile_rows": tile_rows, "select_algo": select_algo,
                "compute_dtype": compute_dtype})
    n, dim = dataset.shape
    q = queries.shape[0]
    select_min = metric not in _MAX_METRICS
    bad = jnp.float32(jnp.inf if select_min else -jnp.inf)
    needs_norms = metric in ("sqeuclidean", "euclidean", "cosine")
    if needs_norms and norms is None:
        # index built via the raw dataclass constructor rather than build()
        norms = dist_mod.sqnorm(dataset)
    qn = dist_mod.sqnorm(queries) if needs_norms else None

    tiles, n_tiles = pad_and_tile(dataset, tile_rows)
    tnorms = (
        pad_and_tile(norms, tile_rows)[0]
        if norms is not None
        else jnp.zeros((n_tiles, tile_rows), jnp.float32)
    )

    def step(_, inp):
        tile, tn, start = inp
        d = _tile_distances(queries, qn, tile, tn, metric, metric_arg, compute_dtype)
        ids = start + jnp.arange(tile_rows, dtype=jnp.int32)
        valid = ids < n
        if filter is not None:
            valid = valid & filter.test(ids)
        d = jnp.where(valid[None, :], d, bad)
        # per-tile top-k, fused with the distance gemm (never materializes the
        # full tile distance matrix to HBM)
        vals, sel = select_k(d, k, select_min=select_min, algo=select_algo)
        sel_ids = jnp.where(vals == bad, -1, jnp.take(ids, sel))
        return None, (vals, sel_ids)

    starts = jnp.arange(n_tiles, dtype=jnp.int32) * tile_rows
    if n_tiles == 1:
        _, (vals, idx) = step(None, (tiles[0], tnorms[0], starts[0]))
        return vals, idx
    # scan over dataset tiles, then one exact merge over n_tiles*k candidates
    # per query (knn_merge_parts analog, knn_merge_parts.cuh:140)
    _, (tile_vals, tile_idx) = lax.scan(step, None, (tiles, tnorms, starts))
    cat_vals = jnp.moveaxis(tile_vals, 0, 1).reshape(q, n_tiles * k)
    cat_idx = jnp.moveaxis(tile_idx, 0, 1).reshape(q, n_tiles * k)
    return select_k(cat_vals, k, select_min=select_min, indices=cat_idx, algo="exact")


@traced("brute_force::search")
def search(
    index: BruteForceIndex,
    queries,
    k: int,
    filter: Optional[Bitset] = None,
    tile_rows: Optional[int] = None,
    select_algo: str = "exact",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN of each query row: returns (distances (q,k), indices (q,k)).

    Mirrors brute_force::search (brute_force-inl.cuh:417) with the tiled merge
    engine of detail/knn_brute_force.cuh:61. ``filter`` excludes dataset rows
    (bitset_filter analog, sample_filter.cuh:31).
    """
    res = res or current_resources()
    queries = jnp.asarray(queries)
    n = index.size
    if filter is not None and filter.n_bits != n:
        raise ValueError(
            f"filter covers {filter.n_bits} bits but index has {n} rows"
        )
    if tile_rows is None:
        # Budget: mirrors chooseTileSize (knn_brute_force.cuh:84). Expanded
        # metrics materialize a (q, tile) fp32 distance block; elementwise
        # metrics additionally broadcast a (q, tile, dim) intermediate.
        q = queries.shape[0]
        if index.metric in dist_mod.EXPANDED_METRICS:
            per_col = max(1, q * 4 + index.dim * 4)
        else:
            per_col = max(1, q * index.dim * 4)
        tile_rows = int(min(n, max(k, res.workspace_bytes // per_col)))
    tile_rows = max(min(tile_rows, n), min(n, k))
    if obs.enabled():
        q_obs = int(queries.shape[0])
        obs.add("brute_force.search.queries", q_obs)
        obs.add("brute_force.search.rows_scanned", q_obs * n)
        obs.add("brute_force.search.tiles", ceil_div(n, int(tile_rows)))
        # roofline note (round 15): the exact scan is the plane's
        # calibration anchor — one dense gemm, no padding waste
        obs_roofline.note_dispatch(
            "brute_force.search",
            {"q": q_obs, "n": n, "dim": index.dim, "k": int(k),
             "dtype": str(index.dataset.dtype)})
    from raft_tpu.resilience import degrade_on_oom, faultpoint

    def attempt(tr):
        faultpoint("brute_force.search")
        return _search_impl(
            queries,
            index.dataset,
            index.norms,
            filter,
            int(k),
            index.metric,
            float(index.metric_arg),
            int(tr),
            select_algo,
            res.compute_dtype if index.metric in dist_mod.EXPANDED_METRICS else None,
        )

    # OOM-adaptive (ISSUE 3): the tile only partitions the scan — any size
    # >= min(n, k) is exact — so a RESOURCE_EXHAUSTED retries at half the
    # tile down to the floor instead of failing the query
    floor = min(int(tile_rows), max(min(n, int(k)), 128))
    return degrade_on_oom(attempt, int(tile_rows), floor=floor,
                          site="brute_force.search")


@traced("brute_force::knn")
def knn(
    queries,
    dataset,
    k: int,
    metric: str = "sqeuclidean",
    metric_arg: float = 2.0,
    **kwargs,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot exact kNN (brute_force-inl.cuh:157 analog)."""
    return search(build(dataset, metric, metric_arg), queries, k, **kwargs)
