"""Random ball cover: exact kNN via landmark triangle-inequality pruning
(reference neighbors/ball_cover-inl.cuh: build_index, all_knn_query :111,
knn_query :258, eps_nn :313; kernels in
spatial/knn/detail/ball_cover/registers.cuh).

TPU design. The reference's one-CTA-per-query kernel walks landmarks in
distance order and early-exits per query. Early exit is per-query control
flow XLA can't express, so the scan is batched: landmarks are visited in
order of each query's *lower bound* ``max(0, d(q, l) − radius_l)`` — which
makes the bound sequence monotone per query, so one shared
``lax.while_loop`` over landmark batches stops exactly when every query's
next bound exceeds its current kth distance. Each step is a dense
gather + matmul over B lists for all queries (finished queries ride along
masked — the cost of lockstep, bounded by the slowest query).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.trace import traced
from raft_tpu.neighbors._packing import pack_lists
from raft_tpu.ops import distance as dist_mod

SUPPORTED_METRICS = ("sqeuclidean", "euclidean", "haversine")
_GROUP = 32


@jax.tree_util.register_pytree_node_class
@dataclass
class BallCoverIndex:
    """Landmarks + padded member lists + per-landmark radii
    (ball_cover_types.hpp BallCoverIndex analog)."""

    landmarks: jax.Array   # (L, dim) fp32
    list_data: jax.Array   # (L, m, dim)
    list_ids: jax.Array    # (L, m) int32, -1 padding
    radii: jax.Array       # (L,) euclidean radius of each ball
    metric: str

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]

    @property
    def dim(self) -> int:
        return self.landmarks.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_ids >= 0))

    def tree_flatten(self):
        return (self.landmarks, self.list_data, self.list_ids, self.radii), (self.metric,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])


@traced("ball_cover::build")
def build(
    dataset,
    n_landmarks: int = 0,
    metric: str = "euclidean",
    seed: int = 0,
    res: Optional[Resources] = None,
) -> BallCoverIndex:
    """Sample √n landmarks, assign every point to its nearest landmark,
    record ball radii (ball_cover-inl.cuh build_index)."""
    res = res or current_resources()
    metric = dist_mod.canonical_metric(metric)
    if metric not in SUPPORTED_METRICS:
        raise ValueError(f"ball_cover supports {SUPPORTED_METRICS}, got {metric!r}")
    dataset = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = dataset.shape
    L = int(n_landmarks) or max(1, int(n ** 0.5))
    if L > n:
        raise ValueError(f"n_landmarks={L} > n_rows={n}")

    key = jax.random.key(seed)
    rows = jax.random.choice(key, n, (L,), replace=False)
    landmarks = dataset[rows]
    if metric == "haversine":
        d = dist_mod.haversine(dataset, landmarks)
        labels = jnp.argmin(d, axis=1).astype(jnp.int32)
        dist_to_lm = jnp.take_along_axis(d, labels[:, None], axis=1)[:, 0]
    else:
        d2 = dist_mod.pairwise_distance(dataset, landmarks, "sqeuclidean", res=res)
        labels = jnp.argmin(d2, axis=1).astype(jnp.int32)
        dist_to_lm = jnp.sqrt(jnp.maximum(
            jnp.take_along_axis(d2, labels[:, None], axis=1)[:, 0], 0.0))

    row_ids = jnp.arange(n, dtype=jnp.int32)
    list_data, list_ids = pack_lists(dataset, row_ids, labels, L, _GROUP)
    radii = jax.ops.segment_max(dist_to_lm, labels, num_segments=L)
    radii = jnp.where(jnp.isfinite(radii), radii, 0.0)
    return BallCoverIndex(landmarks, list_data, list_ids, radii, metric)


@functools.partial(jax.jit, static_argnames=("k", "batch", "haversine"))
def _query_impl(queries, landmarks, list_data, list_ids, radii, k: int,
                batch: int, haversine: bool = False):
    """Ranking distances are squared-L2 internally (kth compared as sqrt)
    for the Euclidean family, and true great-circle radians for haversine —
    both satisfy the triangle inequality the landmark bound needs."""
    q, dim = queries.shape
    L, m, _ = list_data.shape
    nb = -(-L // batch)

    if haversine:
        d_ql = dist_mod.haversine(queries, landmarks)
    else:
        d_ql = jnp.sqrt(jnp.maximum(
            dist_mod._expanded_distance(queries, landmarks, "sqeuclidean", None, "highest"),
            0.0))
    lb = jnp.maximum(d_ql - radii[None, :], 0.0)        # (q, L)
    order = jnp.argsort(lb, axis=1).astype(jnp.int32)   # per-query visit order
    lb_sorted = jnp.take_along_axis(lb, order, axis=1)
    # pad the visit order to a batch multiple (repeat the last landmark —
    # rescanning a list is harmless for a top-k merge)
    pad = nb * batch - L
    order = jnp.pad(order, ((0, 0), (0, pad)), mode="edge")
    lb_sorted = jnp.pad(lb_sorted, ((0, 0), (0, pad)), mode="edge")

    qn = dist_mod.sqnorm(queries)
    norms = dist_mod.sqnorm(list_data, axis=2)          # (L, m)
    norms = jnp.where(list_ids >= 0, norms, jnp.inf)

    def cond(state):
        best_v, _, b = state
        if haversine:
            kth = best_v[:, k - 1]
        else:
            kth = jnp.sqrt(jnp.maximum(best_v[:, k - 1], 0.0))
        nxt = lb_sorted[:, jnp.minimum(b * batch, nb * batch - 1)]
        return (b < nb) & jnp.any((nxt <= kth) | ~jnp.isfinite(kth))

    def body(state):
        best_v, best_i, b = state
        lists = lax.dynamic_slice_in_dim(order, b * batch, batch, axis=1)  # (q, B)
        cand = list_data[lists]                       # (q, B, m, dim)
        ids = list_ids[lists].reshape(q, batch * m)
        if haversine:
            flat = cand.reshape(q, batch * m, dim)
            sin_dlat = jnp.sin(0.5 * (flat[:, :, 0] - queries[:, None, 0]))
            sin_dlon = jnp.sin(0.5 * (flat[:, :, 1] - queries[:, None, 1]))
            a = (sin_dlat ** 2
                 + jnp.cos(queries[:, None, 0]) * jnp.cos(flat[:, :, 0]) * sin_dlon ** 2)
            d2 = 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
        else:
            nrm = norms[lists].reshape(q, batch * m)
            ip = jnp.einsum("qd,qbmd->qbm", queries, cand,
                            preferred_element_type=jnp.float32).reshape(q, batch * m)
            d2 = jnp.maximum(qn[:, None] + nrm - 2.0 * ip, 0.0)
        d2 = jnp.where(ids >= 0, d2, jnp.inf)
        allv = jnp.concatenate([best_v, d2], axis=1)
        alli = jnp.concatenate([best_i, ids], axis=1)
        best_v, sel = lax.top_k(-allv, k)
        best_v = -best_v
        best_i = jnp.take_along_axis(alli, sel, axis=1)
        return best_v, best_i, b + 1

    best_v = jnp.full((q, k), jnp.inf, jnp.float32)
    best_i = jnp.full((q, k), -1, jnp.int32)
    best_v, best_i, _ = lax.while_loop(cond, body, (best_v, best_i, jnp.zeros((), jnp.int32)))
    return best_v, best_i


def knn_query(
    index: BallCoverIndex,
    queries,
    k: int,
    batch: int = 8,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN against the indexed points (ball_cover-inl.cuh:258).
    Returns (distances, indices) in the index's metric."""
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim}), got {queries.shape}")
    if not 0 < k <= index.size:
        raise ValueError(f"k={k} out of range for {index.size} points")
    v, i = _query_impl(queries, index.landmarks, index.list_data,
                       index.list_ids, index.radii, int(k), int(batch),
                       index.metric == "haversine")
    if index.metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return jnp.where(i >= 0, v, jnp.inf), i


def all_knn_query(
    index: BallCoverIndex,
    k: int,
    batch: int = 8,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """kNN of every indexed point against the index itself, self included
    (ball_cover-inl.cuh:111). Rows are ordered by source row id."""
    # reconstruct the dataset in row order from the packed lists
    flat_ids = index.list_ids.reshape(-1)
    flat = index.list_data.reshape(-1, index.dim)
    n = index.size
    pos = jnp.where(flat_ids >= 0, flat_ids, n)  # padding → OOB → dropped
    dataset = jnp.zeros((n, index.dim), jnp.float32).at[pos].set(
        flat, mode="drop")
    return knn_query(index, dataset, k, batch=batch, res=res)


def eps_nn(
    index: BallCoverIndex,
    queries,
    eps: float,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """All index points within L2 radius ``eps`` of each query
    (ball_cover-inl.cuh:313): (adjacency (q, n) bool over source row ids,
    degree (q,)). Balls with lower bound > eps contribute nothing and are
    masked before the compare."""
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if eps <= 0:
        raise ValueError(f"eps must be positive, got {eps}")
    q = queries.shape[0]
    n = index.size
    L, m, dim = index.list_data.shape

    d_ql = jnp.sqrt(jnp.maximum(dist_mod._expanded_distance(
        queries, index.landmarks, "sqeuclidean", None, "highest"), 0.0))
    ball_ok = (d_ql - index.radii[None, :]) <= eps       # (q, L)

    qn = dist_mod.sqnorm(queries)
    norms = jnp.where(index.list_ids >= 0,
                      dist_mod.sqnorm(index.list_data, axis=2), jnp.inf)
    ip = jnp.einsum("qd,lmd->qlm", queries, index.list_data,
                    preferred_element_type=jnp.float32)
    d2 = jnp.maximum(qn[:, None, None] + norms[None] - 2.0 * ip, 0.0)
    within = (d2 <= eps * eps) & ball_ok[:, :, None] & (index.list_ids >= 0)[None]

    # scatter per-entry flags into row-id order
    adj = jnp.zeros((q, n), bool)
    flat_ids = jnp.clip(index.list_ids.reshape(-1), 0, n - 1)
    pos = jnp.where(index.list_ids.reshape(-1) >= 0, flat_ids, n)
    adj = adj.at[:, pos].max(within.reshape(q, -1), mode="drop")
    return adj, jnp.sum(adj.astype(jnp.int32), axis=1)
