"""IVF-BQ: inverted-file index with RaBitQ-style 1-bit quantized vectors.

Reference surface: IVF-RaBitQ (PAPERS.md, "GPU-Native Approximate Nearest
Neighbor Search with IVF-RaBitQ") — a random rotation, per-residual sign
codes (1 bit/dim), and an UNBIASED distance estimator built from two
per-row correction scalars, beating PQ on both build time and search
throughput. The build has no codebook training at all: encode is one
rotation matmul + a sign, which is why BQ builds are a kmeans-only cost.

Estimator. Let c_l be the coarse center of list l, r = x − c_l the
residual, u = R·r̃ its random rotation (R orthogonal ⇒ ‖u‖ = ‖r‖), and
b = sign(u) ∈ {−1, +1}^D the stored code. RaBitQ's quotient estimator for
an inner product against any query-side vector v is

    ⟨u, v⟩  ≈  ⟨b, v⟩ · f,      f = ‖u‖² / ‖u‖₁        (unbiased over R;
                                                         property-tested in
                                                         tests/test_ivf_bq.py)

(f·b is u's least-squares projection onto b; the random rotation makes the
orthogonal error mean-free against any fixed v). From it, expanded L2:

    d̂²(q, x) = ‖q‖²                                   (finalize, shared)
             − 2⟨q, c_l⟩                               (pair_const, exact)
             + ‖c_l‖² + ‖u‖² + 2·f·⟨b, R·c̃_l⟩         (bias: baked per row)
             − 2·f·⟨b, R·q̃⟩                           (the scan matmul)

so search-time work is the coarse gemm (which also yields the exact
−2⟨q, c_l⟩ term) plus ONE ±1 contraction per probed strip — the
ops/bq_scan.py engine. Per-row storage: rot_dim/8 code bytes + 8 bytes of
correction scalars (f and the bias) — 32× compression on the code bytes
against fp32, 4× against the r04 IVF-PQ configuration (pq_dim = dim/2 at
8 bits). The estimate ranks candidates; callers hold the recall gate by
over-fetching and exact re-ranking through neighbors/refine
(:func:`search_refined`), exactly like the IVF-PQ headline path.

Storage is the shared padded-list layout (_packing.pack_lists) at a FIXED
512 granule / pow2 chunks: code rows are rot_dim/8 bytes, so strip-aligned
padding costs almost nothing and every IVF-BQ index is strip-eligible —
the packed kernel and the pure-jnp reference are the only two scan paths.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.obs import compile as obs_compile
from raft_tpu.obs import roofline as obs_roofline
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import _filtering
from raft_tpu.core.trace import traced
from raft_tpu.core.resources import Resources, current_resources
from raft_tpu.core.serialize import load_arrays, save_arrays
from raft_tpu.neighbors import _packing
from raft_tpu.ops import distance as dist_mod
from raft_tpu.ops import linalg
from raft_tpu.ops.bq_scan import (extend_query_planes, multibit_width,
                                  pack_code_planes, pack_sign_bits)

# legacy alias (pre-round-17 this module imported ivf_pq's private helper;
# the shared copy now lives in ops/linalg — satellite 1)
_pad_rot = linalg.pad_rot

SUPPORTED_METRICS = ("sqeuclidean", "euclidean", "inner_product", "cosine")

#: compile-ledger entry for the fused scan — a trace-count delta of zero
#: across repeated searches is the steady-state zero-recompile contract
#: asserted by the bench section and the check.sh smoke; every retrace
#: additionally lands in the ledger with the operand shape-diff that
#: caused it (obs/compile.py, the round-11 replacement for the ad-hoc
#: _BQ_TRACES counter)
_LEDGER_ENTRY = "ivf_bq.search"


def scan_trace_count() -> int:
    """(Re)traces of the fused BQ search program — a thin shim over the
    compile ledger (public name and delta semantics unchanged)."""
    from raft_tpu.obs import compile as obs_compile

    return obs_compile.trace_count(_LEDGER_ENTRY)


@dataclass(frozen=True)
class IvfBqParams:
    """Build params (IvfFlatParams shape — BQ has no codebook knobs; the
    degrees of freedom are the rotation representation and the code width).

    ``rotation_kind``: "dense" (explicit QR rotation matrix — the legacy
    representation) or "hadamard" (SRHT: sign diagonal + fast Walsh–
    Hadamard butterfly, O(d·log d) apply — the billion-scale build
    default; see ops/linalg). ``bits`` (1–4): bits per rotated dimension —
    1 is the classic sign code, 2–4 stack extra bit-planes for the
    high-recall/no-refine regime (module docstring)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    # per-list occupancy cap: -1 = auto (4× mean, group-aligned), 0 = off
    list_size_cap: int = -1
    bits: int = 1
    rotation_kind: str = "dense"
    seed: int = 0

    def __post_init__(self):
        m = dist_mod.canonical_metric(self.metric)
        if m not in SUPPORTED_METRICS:
            raise ValueError(f"ivf_bq supports {SUPPORTED_METRICS}, got {self.metric!r}")
        object.__setattr__(self, "metric", m)
        if not 1 <= self.bits <= 4:
            raise ValueError(f"bits must be in [1, 4], got {self.bits}")
        if self.rotation_kind not in linalg.ROTATION_KINDS:
            raise ValueError(
                f"rotation_kind must be one of {linalg.ROTATION_KINDS}, "
                f"got {self.rotation_kind!r}")


#: fixed list granule: code rows are tiny (rot_dim/8 bytes), so the strip
#: backend's 512/pow2 alignment is near-free and every index stays
#: strip-eligible (no gather fallback path to carry)
_GROUP = 512


@jax.tree_util.register_pytree_node_class
@dataclass
class IvfBqIndex:
    """Coarse centers + rotation + packed sign codes + correction scalars.

    ``list_codes[l, j]`` holds row j's ``bits`` packed code planes over
    rot_dim dimensions (bit-plane-major per plane, ops/bq_scan
    pack_code_planes; bits=1 is the classic pack_sign_bits layout).
    ``list_scale`` is the per-row unbiasing factor f = ‖u‖²/⟨L, u⟩ (for
    bits=1, ⟨b, u⟩ = ‖u‖₁; 0 at padding); ``list_bias`` the per-row
    additive term of the estimator (module docstring; +inf at padding so
    the scan self-masks). ``list_ids[l, j] == -1`` marks padding.
    ``rotation`` is the dense orthogonal matrix for
    ``rotation_kind="dense"`` or the SRHT (rot_dim,) sign diagonal for
    ``rotation_kind="hadamard"`` (ops/linalg.rotate_rows applies either)."""

    centers: jax.Array     # (n_lists, dim) fp32 — for stage 1, unrotated
    rotation: jax.Array    # (rot_dim, rot_dim) dense | (rot_dim,) signs
    list_codes: jax.Array  # (n_lists, max_list_size, bits·rot_dim/8) uint8
    list_ids: jax.Array    # (n_lists, max_list_size) int32, -1 = padding
    list_scale: jax.Array  # (n_lists, max_list_size) fp32
    list_bias: jax.Array   # (n_lists, max_list_size) fp32, +inf at padding
    metric: str
    bits: int = 1
    rotation_kind: str = "dense"

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        # dense (rot_dim, rot_dim) and hadamard (rot_dim,) agree on axis 0
        return self.rotation.shape[0]

    @property
    def max_list_size(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_ids >= 0))

    @property
    def code_bytes_per_row(self) -> int:
        return int(self.list_codes.shape[-1])

    def list_sizes(self) -> jax.Array:
        return jnp.sum(self.list_ids >= 0, axis=1).astype(jnp.int32)

    def tree_flatten(self):
        return (self.centers, self.rotation, self.list_codes, self.list_ids,
                self.list_scale, self.list_bias), (self.metric, self.bits,
                                                   self.rotation_kind)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    # -- persistence (v2 crash-safe container, core/serialize) -------------
    def save(self, path) -> None:
        save_arrays(
            path,
            {"kind": "ivf_bq", "metric": self.metric, "bits": self.bits,
             "rotation_kind": self.rotation_kind},
            {
                "centers": self.centers,
                "rotation": self.rotation,
                "list_codes": self.list_codes,
                "list_ids": self.list_ids,
                "list_scale": self.list_scale,
                "list_bias": self.list_bias,
            },
        )

    @classmethod
    def load(cls, path) -> "IvfBqIndex":
        meta, arrays = load_arrays(path)
        if meta.get("kind") != "ivf_bq":
            raise ValueError(f"not an ivf_bq index: {meta.get('kind')}")
        # legacy (pre-rotation_kind) files carry neither field: they were
        # written by the dense-QR 1-bit build, so the defaults ARE their
        # true description — old indexes load unchanged
        rkind = meta.get("rotation_kind", "dense")
        if rkind not in linalg.ROTATION_KINDS:
            # classified (resilience.classify → FATAL ValueError): a file
            # from a NEWER format revision must fail loudly by name, never
            # decode garbage through the wrong apply
            raise ValueError(
                f"unknown ivf_bq rotation_kind {rkind!r} (supported: "
                f"{linalg.ROTATION_KINDS}); the file may come from a newer "
                "format revision")
        return cls(
            jnp.asarray(arrays["centers"]),
            jnp.asarray(arrays["rotation"]),
            jnp.asarray(arrays["list_codes"]),
            jnp.asarray(arrays["list_ids"]),
            jnp.asarray(arrays["list_scale"]),
            jnp.asarray(arrays["list_bias"]),
            meta["metric"],
            int(meta.get("bits", 1)),
            rkind,
        )


# ---------------------------------------------------------------------------
# Build
# ---------------------------------------------------------------------------


def auto_rot_dim(dim: int, rotation_kind: str = "dense") -> int:
    """Rotation width: dim rounded up to whole code bytes (dense), or to
    the next power of two (hadamard — the Walsh–Hadamard butterfly's
    width, which is also whole bytes at ≥ 8)."""
    if rotation_kind == "hadamard":
        return linalg.hadamard_rot_dim(dim)
    return -(-dim // 8) * 8


def _make_rotation(key, rot_dim: int, rotation_kind: str) -> jax.Array:
    """The rotation operand for either representation (the dense QR matrix
    or the SRHT sign diagonal), from one key — the single derivation
    build/build_streaming/distributed-build all share."""
    if rotation_kind == "hadamard":
        return linalg.make_srht_signs(key, rot_dim)
    return linalg.make_rotation_matrix(key, rot_dim)


def _encode_math(rows, labels, centers, rotation, rc, c2, l2: bool,
                 bits: int = 1, rotation_kind: str = "dense"):
    """Encode one row chunk (plain traceable body — :func:`_encode_chunk`
    is its jitted wrapper; the streamed-build scatter calls this inline):
    rotate the residual, quantize to ``bits``-bit levels, bake the two
    correction scalars. Returns (packed codes (m, bits·nb) uint8,
    scale (m,) fp32, bias (m,) fp32). The one definition of the
    estimator's build side — extend(), build_streaming() and the
    distributed build reuse it so the scalars cannot drift between
    flows."""
    u = linalg.rotate_rows(rows - centers[labels], rotation, rotation_kind)
    norm2 = jnp.einsum("md,md->m", u, u, preferred_element_type=jnp.float32)
    if bits == 1:
        signs = jnp.where(u >= 0, jnp.int8(1), jnp.int8(-1))
        packed = pack_sign_bits(signs)
        # ⟨b, u⟩ = ‖u‖₁ for the sign code — kept as the abs-sum so 1-bit
        # scalars stay bit-identical with every pre-multi-bit index
        proj = jnp.sum(jnp.abs(u), axis=1)
        levels_f = signs.astype(jnp.float32)
    else:
        # symmetric uniform quantizer over [−t, t], t = max|u| per row:
        # code c ∈ [0, 2^bits), dequantized LEVEL L = 2c − (2^bits−1)
        # (odd integers; bits=1 would reduce to sign). The estimator stays
        # the RaBitQ quotient f = ‖u‖²/⟨L, u⟩, which makes f·L the exact
        # projection of u onto its own code direction — unbiased over the
        # rotation by the same argument as the sign code.
        t = jnp.maximum(jnp.max(jnp.abs(u), axis=1, keepdims=True), 1e-30)
        c = jnp.clip(jnp.floor((u / t + 1.0) * (0.5 * (1 << bits))),
                     0, (1 << bits) - 1).astype(jnp.uint8)
        packed = pack_code_planes(c, bits)
        levels_f = 2.0 * c.astype(jnp.float32) - jnp.float32((1 << bits) - 1)
        proj = jnp.einsum("md,md->m", levels_f, u,
                          preferred_element_type=jnp.float32)
    # f = ‖u‖²/⟨L, u⟩ — the RaBitQ unbiasing quotient; a zero residual
    # (row == its center) gets f = 0, which makes the estimate exact
    # (⟨L, u⟩ ≥ 0 always: levels are monotone in u per dimension)
    scale = norm2 / jnp.maximum(proj, 1e-30)
    if l2:
        # 2·f·⟨L, R·c̃_l⟩ completes the −2⟨q−c, r⟩ cross term exactly at
        # the per-row level; ‖c‖² + ‖u‖² are the expanded-L2 constants
        g = jnp.einsum("md,md->m", levels_f, rc[labels],
                       preferred_element_type=jnp.float32)
        bias = c2[labels] + norm2 + 2.0 * scale * g
    else:
        bias = jnp.zeros_like(scale)
    return packed, scale, bias


_encode_chunk = functools.partial(jax.jit, static_argnames=(
    "l2", "bits", "rotation_kind"))(_encode_math)


def _encode_rows(work, labels, centers, rotation, metric, bits: int = 1,
                 rotation_kind: str = "dense", chunk: int = 262_144):
    """Chunked encode over all rows (the 15M-row resident build must never
    hold an (n, rot_dim) fp32 residual array — the ivf_pq enc_chunk
    lesson)."""
    n = work.shape[0]
    l2 = metric in ("sqeuclidean", "euclidean")
    rc = linalg.rotate_rows(centers, rotation, rotation_kind)
    c2 = dist_mod.sqnorm(centers)
    parts = []
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        parts.append(_encode_chunk(
            lax.slice_in_dim(work, s, e, axis=0),
            lax.slice_in_dim(labels, s, e, axis=0),
            centers, rotation, rc, c2, l2, bits, rotation_kind))
    if len(parts) == 1:
        return parts[0]
    return tuple(jnp.concatenate([p[i] for p in parts]) for i in range(3))


@traced("ivf_bq::build")
def build(
    dataset,
    params: IvfBqParams = IvfBqParams(),
    res: Optional[Resources] = None,
) -> IvfBqIndex:
    """Train the coarse quantizer, rotate, sign-encode and pack the lists.

    The whole build beyond kmeans is one rotation matmul + sign + two
    reductions per row — no codebook training (the IVF-RaBitQ build-time
    headline)."""
    res = res or current_resources()
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > n_rows={n}")
    rot_dim = auto_rot_dim(dim, params.rotation_kind)

    work = dataset.astype(jnp.float32)
    if params.metric == "cosine":
        work = work / jnp.maximum(jnp.linalg.norm(work, axis=1, keepdims=True), 1e-30)

    km_metric = ("inner_product" if params.metric in ("cosine", "inner_product")
                 else "sqeuclidean")
    km = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=km_metric, seed=params.seed)
    key = jax.random.key(params.seed)
    k_train, k_rot = jax.random.split(key)
    n_train = max(params.n_lists, int(n * params.kmeans_trainset_fraction))
    with obs.record_span("ivf_bq::coarse_train"):
        if n_train < n:
            # with-replacement sampling (see ivf_flat.build: duplicates are
            # noise for k-means, and choice(replace=False) compiles an
            # O(n log n) permutation program)
            train_rows = jax.random.randint(k_train, (n_train,), 0, n)
            centers = kmeans_balanced.fit(work[train_rows], params.n_lists, km, res=res)
            labels = kmeans_balanced.predict(work, centers, km, res=res)
        else:
            centers, labels = kmeans_balanced.fit_predict(work, params.n_lists, km, res=res)

    if obs.enabled():
        obs.add("ivf_bq.build.rows", n)
        obs.add("ivf_bq.build.lists", params.n_lists)

    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(n, params.n_lists, _GROUP)
    if cap:
        labels = _packing.spill_to_cap(work, centers, labels, km_metric, cap)

    rotation = _make_rotation(k_rot, rot_dim, params.rotation_kind)
    enc_attrs = ({"rows": int(n), "bits": int(params.bits),
                  "rotation_kind": params.rotation_kind}
                 if obs.enabled() else None)
    with obs.record_span("ivf_bq::encode", attrs=enc_attrs):
        codes, scale, bias = _encode_rows(work, labels, centers, rotation,
                                          params.metric, params.bits,
                                          params.rotation_kind)
    with obs.record_span("ivf_bq::pack"):
        row_ids = jnp.arange(n, dtype=jnp.int32)
        list_codes, list_ids = _packing.pack_lists(
            codes, row_ids, labels, params.n_lists, _GROUP, pow2_chunks=True)
        aux, _ = _packing.pack_lists(
            jnp.stack([scale, bias], axis=1), row_ids, labels,
            params.n_lists, _GROUP, pow2_chunks=True)
        list_scale = aux[:, :, 0]
        list_bias = jnp.where(list_ids >= 0, aux[:, :, 1], jnp.inf)
    return IvfBqIndex(centers, rotation, list_codes, list_ids, list_scale,
                      list_bias, params.metric, params.bits,
                      params.rotation_kind)


@traced("ivf_bq::extend")
def extend(index: IvfBqIndex, new_vectors, new_ids=None,
           res: Optional[Resources] = None) -> IvfBqIndex:
    """Encode new vectors with the existing quantizers and repack. The old
    rows' codes and correction scalars are carried as-is (codes cannot
    reconstruct vectors, so extension is a repack of payloads, never a
    re-encode)."""
    res = res or current_resources()
    new_vectors = jnp.asarray(new_vectors).astype(jnp.float32)
    if new_vectors.shape[1] != index.dim:
        raise ValueError(f"dim mismatch: {new_vectors.shape[1]} != {index.dim}")
    if index.metric == "cosine":
        new_vectors = new_vectors / jnp.maximum(
            jnp.linalg.norm(new_vectors, axis=1, keepdims=True), 1e-30)
    km_metric = ("inner_product" if index.metric in ("cosine", "inner_product")
                 else "sqeuclidean")
    labels = kmeans_balanced.predict(
        new_vectors, index.centers,
        kmeans_balanced.KMeansBalancedParams(metric=km_metric), res=res)
    total = index.size + int(new_vectors.shape[0])
    cap = _packing.auto_list_cap(total, index.n_lists, _GROUP)
    labels = _packing.spill_to_cap(
        new_vectors, index.centers, labels, km_metric, cap,
        base_counts=index.list_sizes(),
    )
    new_codes, new_scale, new_bias = _encode_rows(
        new_vectors, labels, index.centers, index.rotation, index.metric,
        index.bits, index.rotation_kind)

    old_codes, old_ids, old_labels = _packing.unpack_lists(
        index.list_codes, index.list_ids)
    old_aux, _, _ = _packing.unpack_lists(
        jnp.stack([index.list_scale,
                   jnp.where(index.list_ids >= 0, index.list_bias, 0.0)],
                  axis=2),
        index.list_ids)
    if new_ids is None:
        start = int(jnp.max(old_ids) + 1) if old_ids.size else 0
        new_ids = jnp.arange(start, start + new_vectors.shape[0], dtype=jnp.int32)
    else:
        new_ids = jnp.asarray(new_ids, jnp.int32)

    all_codes = jnp.concatenate([old_codes, new_codes])
    all_aux = jnp.concatenate(
        [old_aux, jnp.stack([new_scale, new_bias], axis=1)])
    all_ids = jnp.concatenate([old_ids, new_ids])
    all_labels = jnp.concatenate([old_labels, labels])
    list_codes, list_ids = _packing.pack_lists(
        all_codes, all_ids, all_labels, index.n_lists, _GROUP,
        pow2_chunks=True)
    aux, _ = _packing.pack_lists(all_aux, all_ids, all_labels,
                                 index.n_lists, _GROUP, pow2_chunks=True)
    return IvfBqIndex(
        index.centers, index.rotation, list_codes, list_ids, aux[:, :, 0],
        jnp.where(list_ids >= 0, aux[:, :, 1], jnp.inf), index.metric,
        index.bits, index.rotation_kind)


# ---------------------------------------------------------------------------
# Streamed build (the billion-scale fast path)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _scatter_chunk_bq(list_codes, list_ids, list_scale, list_bias,
                      codes, scale, bias, labels, base, row_start):
    """One streamed-build chunk's offset-scatter into the DONATED packed
    blocks (build_streaming pass 2). ``base`` is the per-list write offset
    accumulated over previous chunks; the in-chunk rank comes from one
    chunk-local sort (_packing.chunk_ranks — the ONE copy of the position
    math), so no global position array ever exists. Encode runs OUTSIDE
    (non-donating :func:`_encode_chunk`), so its OOM-degraded retry can
    never invalidate a donated block."""
    m = labels.shape[0]
    n_lists, mls = list_ids.shape
    order, sorted_labels, rank_sorted = _packing.chunk_ranks(labels, n_lists)
    safe_sl = jnp.minimum(sorted_labels, n_lists - 1)
    pos = base[safe_sl].astype(jnp.int32) + rank_sorted
    # sentinel labels (== n_lists, the diversion drop marker) and overflow
    # past mls route to row mls, which mode="drop" discards
    pos = jnp.where((sorted_labels < n_lists) & (pos < mls), pos, mls)
    list_codes = list_codes.at[safe_sl, pos].set(codes[order], mode="drop")
    ids = row_start + jnp.arange(m, dtype=jnp.int32)
    list_ids = list_ids.at[safe_sl, pos].set(ids[order], mode="drop")
    list_scale = list_scale.at[safe_sl, pos].set(scale[order], mode="drop")
    list_bias = list_bias.at[safe_sl, pos].set(bias[order], mode="drop")
    return list_codes, list_ids, list_scale, list_bias


def _encode_chunk_degradable(rows, labels, centers, rotation, rc, c2, l2,
                             bits, rotation_kind, floor: int = 4096):
    """One chunk through :func:`_encode_chunk` behind the
    ``ivf_bq.build.encode_chunk`` faultpoint, with the round-7 OOM
    recovery: an OOM-classified failure halves the encode sub-chunk (down
    to ``floor``) and re-encodes in parts — per-row math is row-independent
    so the degraded result is bit-identical, only the dispatch count
    grows. DEADLINE/FATAL classes propagate classified."""
    from raft_tpu import resilience

    m = rows.shape[0]
    # small chunks still get at least one halving before the floor bites
    # (the floor exists to stop meaningless 64-row dispatch storms, not to
    # veto recovery outright) — max with 64 AFTER the m//2 clamp, so even
    # a 256-row chunk halves once instead of dying on its first OOM
    floor = max(64, min(floor, m // 2))
    sub = m
    while True:
        try:
            resilience.faultpoint("ivf_bq.build.encode_chunk")
            if sub >= m:
                return _encode_chunk(rows, labels, centers, rotation, rc,
                                     c2, l2, bits, rotation_kind)
            parts = []
            for s in range(0, m, sub):
                e = min(s + sub, m)
                parts.append(_encode_chunk(
                    lax.slice_in_dim(rows, s, e, axis=0),
                    lax.slice_in_dim(labels, s, e, axis=0),
                    centers, rotation, rc, c2, l2, bits, rotation_kind))
            return tuple(jnp.concatenate([p[i] for p in parts])
                         for i in range(3))
        except Exception as e:
            kind = resilience.classify(e)
            if kind == resilience.OOM and sub > floor:
                sub = max(floor, sub // 2)
                obs.add("ivf_bq.build.degraded_chunk")
                resilience.record_event(
                    "degraded_chunk", site="ivf_bq.build.encode_chunk",
                    chunk_rows=sub)
                continue
            raise


@traced("ivf_bq::build_streaming")
def build_streaming(
    chunk_fn,
    n: int,
    dim: int,
    params: IvfBqParams = IvfBqParams(),
    res: Optional[Resources] = None,
    chunk_rows: int = 0,
    train_rows: int = 0,
) -> IvfBqIndex:
    """Out-of-HBM IVF-BQ build: the dataset visits the device one chunk at
    a time (the SIFT-1B per-chip-share configuration — peak residency is
    the packed index + ONE chunk's encode transient, never the raw
    (n, dim) matrix; obs.costmodel.predict_build_streaming_bytes is the
    closed-form bound, asserted in tier-1).

    ``chunk_fn(start, end) -> (end-start, dim) array`` supplies rows — a
    file reader (bench/io.py), a generator, or a host array slice. It is
    called once per chunk per pass (twice total), so it must be
    deterministic. ``chunk_rows`` defaults to the workspace-budget
    formula, overridable via ``RAFT_TPU_BQ_BUILD_CHUNK``.

    Rides the ``ivf_pq.build_streaming`` cache-only pattern: quantizers
    train on ``train_rows`` sampled rows (default ≤ 2M; ``>= n`` streams
    the WHOLE dataset through training, in which case the output is
    BIT-IDENTICAL — codes, scales, ids — to one-shot :func:`build` at
    ``kmeans_trainset_fraction=1`` and ``list_size_cap=0``, the
    check.sh/tier-1 parity contract); pass 1 streams label assignment
    (capacity diversion under a cap: nearest-full rows take their
    second-nearest, doubly-full rows are DROPPED and counted on
    ``index._streaming_dropped``); pass 2 encodes each chunk through the
    shared :func:`_encode_chunk` (the ``ivf_bq.build.encode_chunk``
    faultpoint with OOM→halve-chunk degraded retry, round-7 gate) and
    offset-scatters into DONATED blocks."""
    import os

    import numpy as np

    res = res or current_resources()
    if params.metric == "cosine":
        raise ValueError("build_streaming: cosine needs normalized chunks; "
                         "normalize inside chunk_fn and use inner_product")
    rot_dim = auto_rot_dim(dim, params.rotation_kind)
    nb_total = multibit_width(rot_dim, params.bits)
    km_metric = ("inner_product" if params.metric == "inner_product"
                 else "sqeuclidean")
    km = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=km_metric, seed=params.seed)
    env_chunk = int(os.environ.get("RAFT_TPU_BQ_BUILD_CHUNK", "0") or 0)
    chunk = int(chunk_rows) or env_chunk or int(
        max(262_144, min(n, res.workspace_bytes // max(dim * 12, 1))))
    chunk = min(chunk, n)
    starts = list(range(0, n, chunk))
    cap = params.list_size_cap
    if cap < 0:
        cap = _packing.auto_list_cap(n, params.n_lists, _GROUP)

    from raft_tpu.core.interruptible import check_interrupt

    # --- quantizers (same key derivation as build(): bit-identity) ---------
    key = jax.random.key(params.seed)
    _k_train, k_rot = jax.random.split(key)
    rotation = _make_rotation(k_rot, rot_dim, params.rotation_kind)
    t_rows = int(train_rows) or int(min(2_000_000, max(
        params.n_lists * 32, n * params.kmeans_trainset_fraction)))
    t_rows = min(t_rows, n)
    with obs.record_span("ivf_bq::coarse_train"):
        if t_rows >= n:
            # full-data training: read whole chunks so the trainset IS the
            # dataset in order (the bit-identity-with-build() contract)
            train_parts = [jnp.asarray(chunk_fn(s, min(s + chunk, n)),
                                       jnp.float32) for s in starts]
        else:
            per = max(1, t_rows // len(starts))
            train_parts = [jnp.asarray(chunk_fn(s, min(s + per, n)),
                                       jnp.float32) for s in starts]
        trainset = (jnp.concatenate(train_parts) if len(train_parts) > 1
                    else train_parts[0])
        del train_parts
        centers = kmeans_balanced.fit(trainset, params.n_lists, km, res=res)
        del trainset
    if obs.enabled():
        obs.add("ivf_bq.build.rows", n)
        obs.add("ivf_bq.build.lists", params.n_lists)
        obs.add("ivf_bq.build.streamed_chunks", len(starts))

    # --- pass 1: streamed assignment (+ capacity diversion under a cap) ----
    n_lists = params.n_lists
    run = np.zeros(n_lists, np.int64)
    counts_np = np.zeros((len(starts), n_lists), np.int64)
    labels_chunks = []
    dropped = 0
    for ci, s in enumerate(starts):
        check_interrupt()
        e = min(s + chunk, n)
        rows = jnp.asarray(chunk_fn(s, e), jnp.float32)
        if cap:
            l1, l2_ = _packing.assign_top2(rows, centers, metric=km_metric)
            labels = _packing.divert_to_cap(
                l1, l2_, jnp.asarray(run, jnp.int32), jnp.int32(cap),
                n_lists)
        else:
            labels = kmeans_balanced.predict(rows, centers, km, res=res)
        labels_chunks.append(labels)
        # deliberate per-chunk host fetch (ivf_pq.build_streaming precedent):
        # the streamed build is host-driven by design — the (n_lists,) count
        # steers cap diversion and the pass-2 offsets, amortized by the
        # chunk's assign gemm
        c = np.asarray(jnp.bincount(jnp.minimum(labels, n_lists),  # graftlint: ignore[loop-host-transfer]
                                    length=n_lists + 1))
        counts_np[ci] = c[:n_lists]
        dropped += int(c[n_lists])
        run += c[:n_lists]
        del rows
    totals = counts_np.sum(axis=0)
    # strip-eligible padded size: 512 granule, pow2 chunks — THE shared
    # pack_lists formula, so one-shot and streamed builds agree on mls
    mls = _packing.round_list_size(int(totals.max()), _GROUP,
                                   pow2_chunks=True)
    base_np = np.cumsum(counts_np, axis=0) - counts_np  # per-chunk offsets
    if dropped:
        from raft_tpu.core.logger import get_logger

        get_logger().warning(
            "ivf_bq.build_streaming: %d row(s) overflowed both their "
            "nearest and second-nearest capped lists and were dropped "
            "(cap=%d); raise list_size_cap or n_lists.", dropped, cap)

    # --- pass 2: encode + offset-scatter into donated blocks ---------------
    l2 = params.metric in ("sqeuclidean", "euclidean")
    rc = linalg.rotate_rows(centers, rotation, params.rotation_kind)
    c2 = dist_mod.sqnorm(centers)
    list_codes = jnp.zeros((n_lists, mls, nb_total), jnp.uint8)
    list_ids = jnp.full((n_lists, mls), -1, jnp.int32)
    list_scale = jnp.zeros((n_lists, mls), jnp.float32)
    list_bias = jnp.full((n_lists, mls), jnp.inf, jnp.float32)
    for ci, s in enumerate(starts):
        check_interrupt()
        e = min(s + chunk, n)
        rows = jnp.asarray(chunk_fn(s, e), jnp.float32)
        labels = labels_chunks[ci]
        safe = jnp.minimum(labels, n_lists - 1)
        with obs.record_span("ivf_bq::encode_chunk",
                             attrs=({"rows": int(e - s), "chunk": ci}
                                    if obs.enabled() else None)):
            codes, scale, bias = _encode_chunk_degradable(
                rows, safe, centers, rotation, rc, c2, l2, params.bits,
                params.rotation_kind)
            list_codes, list_ids, list_scale, list_bias = _scatter_chunk_bq(
                list_codes, list_ids, list_scale, list_bias, codes, scale,
                bias, labels, jnp.asarray(base_np[ci], jnp.int32),
                jnp.int32(s))
        del rows
    out = IvfBqIndex(centers, rotation, list_codes, list_ids, list_scale,
                     list_bias, params.metric, params.bits,
                     params.rotation_kind)
    out._streaming_dropped = dropped
    return out


# ---------------------------------------------------------------------------
# Search
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "metric", "select_algo", "compute_dtype",
                     "l2", "bits", "rotation_kind"),
)
def _bq_search_prep(queries, centers, rotation, list_bias, list_ids, filter,
                    n_probes, metric, select_algo, compute_dtype, l2,
                    bits: int = 1, rotation_kind: str = "dense"):
    """Stage 1 + operand prep: ONE coarse gemm feeds both the probe ranking
    and the exact per-pair center term (ivf_pq's shared ``_pq_probe_prep``
    — one copy of the math, so the packed and paged engines cannot
    drift); the rotated query — plane-extended for multi-bit codes
    (ops/bq_scan.extend_query_planes) — is the scan's A operand.
    ``list_bias`` / ``list_ids`` may equally be a paged store's
    (capacity, page_rows) pools — the masking is shape-agnostic."""
    from raft_tpu.neighbors.ivf_pq import _pq_probe_prep

    probes, qr, pair_const = _pq_probe_prep(
        queries, centers, rotation, n_probes, select_algo, l2,
        rotation_kind)
    qr = extend_query_planes(qr, bits)
    bias = _filtering.apply_filter_bias(list_bias, list_ids, filter)
    return probes, qr, bias, pair_const


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "select_algo",
                     "compute_dtype", "classes", "class_counts", "q_tile",
                     "interpret", "impl", "bits", "rotation_kind"),
)
def _bq_fused(queries, centers, rotation, list_codes, list_scale, list_bias,
              list_ids, filter, cls_ord, k, n_probes, metric, select_algo,
              compute_dtype, classes, class_counts, q_tile, interpret, impl,
              bits: int = 1, rotation_kind: str = "dense"):
    """The ENTIRE BQ search — coarse gemm, device strip planning, packed
    scan, merge, finalize — as one jit: one runtime dispatch, zero host
    syncs (the round-4 _ragged_fused shape). The in-kernel tournament
    top-k is allowed (approx_ok=True): this path over-fetches and
    exact-re-ranks via neighbors/refine, which absorbs its ~1e-4/row
    bin-collision loss (the IVF-PQ precedent)."""
    from raft_tpu.ops.bq_scan import bq_strip_search_traced

    # ledger registration: runs at trace time only (obs/compile.py)
    obs_compile.trace_event(
        _LEDGER_ENTRY, queries=queries, centers=centers, rotation=rotation,
        list_codes=list_codes, list_scale=list_scale, list_bias=list_bias,
        list_ids=list_ids, filter=filter, cls_ord=cls_ord,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "select_algo": select_algo, "compute_dtype": compute_dtype,
                "classes": classes, "class_counts": class_counts,
                "q_tile": q_tile, "interpret": interpret, "impl": impl,
                "bits": bits, "rotation_kind": rotation_kind})
    l2 = metric in ("sqeuclidean", "euclidean")
    # packed coarse select only while its perturbation bound stays tight
    # (2^-(23-ceil(log2 n_lists)) ≤ 5e-4 at 4096 lists — see
    # ivf_flat._ragged_fused)
    sa = ("packed" if select_algo == "exact" and not interpret
          and centers.shape[0] <= 4096 else select_algo)
    probes, qr, bias, pair_const = _bq_search_prep(
        queries, centers, rotation, list_bias, list_ids, filter,
        n_probes, metric, sa, compute_dtype, l2, bits, rotation_kind,
    )
    vals, ids = bq_strip_search_traced(
        qr, probes, list_codes, list_scale, bias, list_ids, cls_ord,
        classes, class_counts, int(k), int(k), -2.0 if l2 else -1.0,
        q_tile, interpret, pair_const=pair_const, approx_ok=True, impl=impl,
    )
    from raft_tpu.neighbors.ivf_flat import _finalize_ragged

    # shared fused finalizer: ‖Rq̃‖² == ‖q‖² (orthogonal rotation,
    # zero-padding adds nothing), same alpha conventions as the fp scans
    return _finalize_ragged(vals, ids, queries, metric)


@traced("ivf_bq::search")
def search(
    index: IvfBqIndex,
    queries,
    k: int,
    n_probes: int = 20,
    filter: Optional[Bitset] = None,
    select_algo: str = "exact",
    backend: str = "auto",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate k-NN over the 1-bit compressed lists. Returns
    (distances, indices); distances are UNBIASED estimates, not exact —
    pipe through :mod:`raft_tpu.neighbors.refine` (or call
    :func:`search_refined`) for the recall-gated configuration.

    ``backend``: "packed" (the bq_scan Pallas kernel — the TPU path,
    interpret-mode elsewhere), "reference" (the pure-jnp scan, bit-identical
    to "packed" — the CPU default and parity oracle), or "auto".

    Recovery contract (round-7 invariant): the scan dispatch carries the
    ``ivf_bq.search.scan`` faultpoint; an OOM-classified failure retries at
    half the query tile (down to a floor) with ``ivf_bq.search.degraded_tile``
    counting the degradation, DEADLINE/FATAL classes propagate classified.
    """
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be (q, {index.dim}), got {queries.shape}")
    n_probes = int(min(n_probes, index.n_lists))
    filter_attrs = None
    if filter is not None:
        from raft_tpu.resilience import faultpoint

        faultpoint("ivf_bq.search.filter")
        n_probes, _, f_rate, f_widen = _filtering.widen_plan(
            filter, n_probes, index.n_lists)
        filter_attrs = {"filter_pass_rate": round(f_rate, 6),
                        "filter_widen_x": round(f_widen, 4),
                        "filter_n_probes": n_probes}
    if not 0 < k <= min(n_probes * index.max_list_size, 512):
        raise ValueError(
            f"k={k} out of range (1..min(n_probes·max_list_size, 512)) for "
            f"n_probes={n_probes} x max_list_size={index.max_list_size}")
    if index.metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)

    if backend == "auto":
        backend = "packed" if jax.default_backend() == "tpu" else "reference"
    if backend not in ("packed", "reference"):
        raise ValueError(f"unknown backend {backend!r}")
    impl = "pallas" if backend == "packed" else "jnp"
    scan_attrs = None
    if obs.enabled():
        q_obs = int(queries.shape[0])
        obs.add("ivf_bq.search.queries", q_obs)
        obs.add("ivf_bq.search.probes", q_obs * n_probes)
        obs.add(f"ivf_bq.search.backend.{backend}", 1)
        scan_attrs = {"backend": backend, "queries": q_obs,
                      "probes": int(n_probes), "k": int(k)}
        if filter_attrs:
            scan_attrs.update(filter_attrs)
        # roofline note (round 15): packed-scan FLOP/byte model + strip
        # occupancy at the scan's real planning width (rot_dim) when the
        # host already caches per-list lengths (no forced device sync)
        occ = None
        lens_cached = getattr(index, "_lens_np_cache", None)
        if lens_cached is not None \
                and lens_cached.shape[0] == index.n_lists:
            from raft_tpu.ops.bq_scan import occupancy_stats
            kf_occ = min(int(k), 512)
            occ = obs_roofline.memo_occupancy(
                index,
                (id(lens_cached), q_obs, int(n_probes), kf_occ,
                 res.workspace_bytes),
                lambda: occupancy_stats(
                    lens_cached, index.max_list_size, q_obs, n_probes,
                    rot_dim=index.rot_dim,
                    workspace_bytes=res.workspace_bytes, kf=kf_occ,
                    bits=index.bits))
        obs_roofline.note_dispatch(
            "ivf_bq.search",
            {"q": q_obs, "dim": index.dim, "n_lists": index.n_lists,
             "max_list_size": index.max_list_size,
             "n_probes": int(n_probes), "k": int(k),
             "rot_dim": index.rot_dim, "bits": index.bits,
             "rotation_kind": index.rotation_kind},
            occupancy=occ)
    from raft_tpu import resilience
    from raft_tpu.neighbors.ivf_flat import _ragged_plan_static

    # plan with the scan's REAL row width (the bf16 unpacked block the
    # kernel holds in VMEM is bits·rot_dim wide — every extra bit-plane
    # widens the MXU contraction)
    classes, class_counts, cls_ord, q_tile = _ragged_plan_static(
        index, n_probes, k, res, index.rot_dim * index.bits)
    q_tile = min(q_tile, queries.shape[0])
    interpret = jax.default_backend() != "tpu"
    while True:
        try:
            resilience.faultpoint("ivf_bq.search.scan")
            # ledger watch: a (re)tracing dispatch gets its wall-clock
            # stamped on the ledger record (steady state stamps nothing)
            with obs.record_span("ivf_bq::scan", attrs=scan_attrs), \
                    obs_compile.watch():
                return _bq_fused(
                    queries, index.centers, index.rotation, index.list_codes,
                    index.list_scale, index.list_bias, index.list_ids,
                    filter, cls_ord, int(k), n_probes, index.metric,
                    select_algo, res.compute_dtype, classes, class_counts,
                    q_tile, interpret, impl, index.bits,
                    index.rotation_kind,
                )
        except Exception as e:
            kind = resilience.classify(e)
            if kind == resilience.OOM and q_tile > 64:
                # degraded-tile retry: half the query tile halves the
                # per-dispatch working set; the result is identical, only
                # the dispatch count grows
                q_tile = max(64, q_tile // 2)
                obs.add("ivf_bq.search.degraded_tile")
                resilience.record_event("degraded_tile",
                                        site="ivf_bq.search.scan",
                                        q_tile=q_tile)
                continue
            raise


# ---------------------------------------------------------------------------
# Paged search (serving layer): scan a PagedListStore's packed sign pages
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "metric", "select_algo",
                     "compute_dtype", "q_tile", "interpret", "impl",
                     "bits", "rotation_kind"),
)
def _paged_fused_bq(queries, centers, rotation, codes_pool, scale_pool,
                    bias_pool, page_ids, table, chain_pages, filter,
                    k, n_probes, metric, select_algo, compute_dtype,
                    q_tile, interpret, impl, bits: int = 1,
                    rotation_kind: str = "dense"):
    """The ENTIRE paged BQ search as one jit: coarse gemm + rotation,
    device strip planning, the page-table DMA ±1 kernel, merge, finalize —
    the ``_bq_fused`` shape over page chains. Capacity-shaped operands
    (zero-recompile serving contract); the exact −2⟨q, c_l⟩ term rides
    pair_const exactly like the packed path."""
    from raft_tpu.ops.bq_scan import paged_bq_search_traced

    obs_compile.trace_event(
        "ivf_bq.paged_pallas", queries=queries, centers=centers,
        rotation=rotation, codes_pool=codes_pool, scale_pool=scale_pool,
        bias_pool=bias_pool, page_ids=page_ids, table=table,
        chain_pages=chain_pages, filter=filter,
        static={"k": k, "n_probes": n_probes, "metric": metric,
                "select_algo": select_algo, "compute_dtype": compute_dtype,
                "q_tile": q_tile, "interpret": interpret, "impl": impl,
                "bits": bits, "rotation_kind": rotation_kind})
    l2 = metric in ("sqeuclidean", "euclidean")
    sa = ("packed" if select_algo == "exact" and not interpret
          and centers.shape[0] <= 4096 else select_algo)
    # THE packed path's prep (one copy — probes/rotation/pair_const are
    # bitwise parity by construction); the bias/ids operands are simply
    # the store's pools instead of the packed arrays
    probes, qr, bias, pair_const = _bq_search_prep(
        queries, centers, rotation, bias_pool, page_ids, filter,
        n_probes, metric, sa, compute_dtype, l2, bits, rotation_kind,
    )
    alpha = -2.0 if l2 else -1.0
    vals, ids = paged_bq_search_traced(
        qr, probes, codes_pool, scale_pool, bias, page_ids, table,
        chain_pages, int(k), int(k), alpha, q_tile, interpret,
        pair_const=pair_const, impl=impl)
    from raft_tpu.neighbors.ivf_flat import _finalize_ragged

    return _finalize_ragged(vals, ids, queries, metric)


@traced("ivf_bq::search_paged")
def search_paged(
    store,
    queries,
    k: int,
    n_probes: int = 20,
    filter: Optional[Bitset] = None,
    select_algo: str = "exact",
    backend: str = "auto",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate k-NN over a mutable paged 1-bit code store
    (:class:`raft_tpu.serving.PagedListStore`, kind ``"ivf_bq"``): same
    estimator contract as :func:`search`, over a store that keeps serving
    while rows stream in/out — no repack, zero recompiles on steady-state
    mutations.

    ``backend``: "paged_pallas" (page-table DMA ±1 kernel — the TPU
    engine, interpret-mode elsewhere), "paged_jnp" (its bit-parity jnp
    reference — the CPU default), or "auto"."""
    if store.kind != "ivf_bq":
        raise ValueError(f"expected an ivf_bq store, got {store.kind!r}")
    res = res or current_resources()
    queries = jnp.asarray(queries).astype(jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != store.dim:
        raise ValueError(f"queries must be (q, {store.dim}), got {queries.shape}")
    n_probes = int(min(n_probes, store.n_lists))
    if filter is None:
        filter = getattr(store, "filter", None)
    filter_attrs = None
    if filter is not None:
        from raft_tpu.resilience import faultpoint
        faultpoint("ivf_bq.search.filter")
        n_probes, _, f_rate, f_widen = _filtering.widen_plan(
            filter, n_probes, store.n_lists)
        filter_attrs = {"filter_pass_rate": round(f_rate, 6),
                        "filter_widen_x": round(f_widen, 4),
                        "filter_n_probes": n_probes}
    from raft_tpu.neighbors.ivf_flat import (_paged_plan_static,
                                             paged_backend_auto)

    if backend == "auto":
        backend = paged_backend_auto(store, k)
    if backend not in ("paged_pallas", "paged_jnp"):
        raise ValueError(f"unknown backend {backend!r}")
    # one ATOMIC store snapshot (the scan_state contract)
    codes_pool, bias_pool, scale_pool, page_ids, table, chain_pages = \
        store.paged_scan_state()
    width = int(table.shape[1])
    if not 0 < k <= min(n_probes * width * store.page_rows, 512):
        raise ValueError(f"k={k} out of range")
    if store.metric == "cosine":
        queries = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-30)
    rot_dim = int(store.rotation.shape[0])
    bits = int(getattr(store, "bq_bits", 1))
    rotation_kind = getattr(store, "rotation_kind", "dense")
    scan_attrs = None
    if obs.enabled():
        q_obs = int(queries.shape[0])
        obs.add("ivf_bq.search_paged.queries", q_obs)
        obs.add("ivf_bq.search_paged.probes", q_obs * n_probes)
        obs.add(f"ivf_bq.search_paged.backend.{backend}", 1)
        scan_attrs = {"backend": backend, "queries": q_obs,
                      "probes": int(n_probes), "k": int(k),
                      "table_width": width}
        if filter_attrs:
            scan_attrs.update(filter_attrs)
        from raft_tpu.ops.strip_scan import paged_occupancy_stats
        occ = obs_roofline.memo_occupancy(
            store,
            (store.pages_used, store.size, store.tombstones, width,
             q_obs, int(n_probes), int(k), res.workspace_bytes),
            lambda: paged_occupancy_stats(
                width, store.page_rows, store._list_pages, store.size,
                store.tombstones, q_obs, int(n_probes), int(k),
                int(codes_pool.shape[-1]),
                workspace_bytes=res.workspace_bytes, dim=rot_dim * bits))
        obs_roofline.note_dispatch(
            "ivf_bq.paged_pallas",
            {"q": q_obs, "dim": store.dim, "n_lists": store.n_lists,
             "page_rows": store.page_rows, "table_width": width,
             "n_probes": int(n_probes), "k": int(k), "rot_dim": rot_dim,
             "bits": bits, "rotation_kind": rotation_kind},
            occupancy=occ)
    from raft_tpu.resilience import faultpoint

    interpret = jax.default_backend() != "tpu"
    q_tile = min(_paged_plan_static(store, n_probes, k, res,
                                    rot_dim * bits),
                 queries.shape[0])
    impl = "pallas" if backend == "paged_pallas" else "jnp"
    faultpoint("ivf_bq.search_paged.scan")
    with obs.record_span("ivf_bq::paged_pallas", attrs=scan_attrs):
        with obs_compile.watch():
            return _paged_fused_bq(
                queries, store.centers, store.rotation, codes_pool,
                scale_pool, bias_pool, page_ids, table, chain_pages,
                filter, int(k), n_probes, store.metric, select_algo,
                res.compute_dtype, int(q_tile), interpret, impl, bits,
                rotation_kind)


@traced("ivf_bq::search_refined")
def search_refined(
    index: IvfBqIndex,
    dataset,
    queries,
    k: int,
    n_probes: int = 20,
    refine_ratio: int = 4,
    filter: Optional[Bitset] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The recall-gated configuration: over-fetch ``k·refine_ratio``
    estimated candidates, then exact re-rank against ``dataset`` through
    neighbors/refine (refine-inl.cuh:70 analog — the same pipe the IVF-PQ
    headline uses). ``dataset`` is the caller-held original row matrix; the
    index itself stores only 1-bit codes."""
    from raft_tpu.neighbors import refine as refine_mod

    if refine_ratio < 1:
        raise ValueError(f"refine_ratio must be >= 1, got {refine_ratio}")
    k_fetch = min(int(k) * int(refine_ratio), 512)
    if filter is not None:
        # widen the over-fetch too: at low pass rates k·refine_ratio
        # candidates shrink to k·refine_ratio·pass_rate survivors
        _, k_fetch, _, _ = _filtering.widen_plan(
            filter, n_probes, index.n_lists, k_fetch=k_fetch, k_cap=512)
    _, cand = search(index, queries, k_fetch, n_probes=n_probes,
                     filter=filter, res=res)
    return refine_mod.refine(dataset, queries, cand, int(k),
                             metric=index.metric, res=res)


def reconstruct_rows(centers, rotation, codes, scale, labels, bits: int = 1,
                     rotation_kind: str = "dense", dim: Optional[int] = None):
    """Approximate original vectors from packed BQ codes:
    ``x̂ = c_label + R⁻¹(f·L)``, where ``f·L`` is the RaBitQ estimator's
    projection of the rotated residual onto its own code direction — the
    best reconstruction the code carries. Assignment-grade (maintenance
    re-clustering's row source when the raw vectors are gone), NOT
    bit-exact: re-encoding a reconstruction is near-idempotent but the
    scan estimates remain approximate either way."""
    from raft_tpu.ops.bq_scan import unpack_code_levels, unpack_sign_bits

    rot_dim = int(rotation.shape[-1])
    if bits == 1:
        levels = unpack_sign_bits(jnp.asarray(codes), rot_dim)
    else:
        levels = unpack_code_levels(jnp.asarray(codes), rot_dim, bits)
    u_hat = jnp.asarray(scale, jnp.float32)[:, None] * levels.astype(jnp.float32)
    resid = linalg.unrotate_rows(u_hat, rotation, rotation_kind)
    d = int(centers.shape[1]) if dim is None else int(dim)
    return centers[jnp.asarray(labels, jnp.int32)] + resid[:, :d]
